"""A/B probes for the axon tunnel's per-dispatch cost model.

Round-4 measurement (BENCH_CORE.md "tunnel per-call overhead"): a jitted
x+1 round-trips in 0.02 ms while a 48-weight (1.6 GB) matmul chain costs
~91 ms/call.  Unknown: does the per-call cost scale with the number of
argument HANDLES or with the argument BYTES?  The answer decides whether
restructuring the LLM engine around stacked scanned weight superarrays
(one handle instead of ~100) can recover the ~45x decode gap.

Probes (each timed steady-state, host-sync via a scalar device->host copy,
which on the axon platform is the only reliable completion barrier):

  A. list48   — 48 separate (2048, 4096->2048 alternating) bf16 weights
                passed as a list of args.
  B. stacked  — the SAME compute with weights stacked into one
                (48, 2048, 2048) superarray consumed via lax.scan.
  C. donated  — B with the activation donated (buffer-reuse signal).
  D. count-sweep — N tiny (8,) args for N in 1/8/48/96: pure handle cost.
  E. bytes-sweep — ONE arg of 8/128/512 MiB: pure byte cost.
  F. overlap — the engine's pipelined-readback schedule (ISSUE 4) with
     CONTROLLED components: a jitted chain worth a few ms of device
     time and a sleep standing in for the host fold. Sync ticks
     (dispatch -> block -> fold) should cost ~host+device per tick;
     pipelined ticks (dispatch t -> read t-1 -> fold t-1) should cost
     ~max(host, device) — the probe prints both against the measured
     components so the claim is checkable per platform.

Prints one JSON line per row:  {"probe": ..., "ms_per_call": ...}
and a final {"probe": "ab_summary", ...} line with the inferred model.
Runs in a watchdogged subprocess like bench.py (the tunnel can wedge
mid-run); on outage prints {"probe": "skipped"} and exits 0.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

TIMEOUT_S = int(os.environ.get("RAY_TPU_AB_TIMEOUT", "600"))


def _sync(x) -> float:
    # device->host copy: cannot return before remote execution finishes
    # (block_until_ready can, on the axon platform).
    return float(x.reshape(-1)[0])


def _time_call(fn, args, iters: int = 8) -> float:
    out = fn(*args)
    _sync(out if not isinstance(out, tuple) else out[0])  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync(out if not isinstance(out, tuple) else out[0])
    return (time.perf_counter() - t0) / iters * 1e3


def run_inner() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    rows = []

    def emit(probe: str, ms: float, **extra):
        row = {"probe": probe, "ms_per_call": round(ms, 3), **extra}
        rows.append(row)
        print("AB_JSON " + json.dumps(row), flush=True)

    dev = jax.devices()[0]
    emit("platform", 0.0, platform=dev.platform,
         kind=getattr(dev, "device_kind", str(dev)))

    # ---- A/B/C: 48-layer matmul chain, list args vs stacked scan ----
    H = 2048
    L = 48
    key = jax.random.PRNGKey(0)
    ws_list = [jax.device_put(jax.random.normal(jax.random.fold_in(key, i),
                                                (H, H), jnp.bfloat16) * 0.02)
               for i in range(L)]
    w_stack = jax.device_put(jnp.stack(ws_list))          # (48, H, H) = 384 MiB
    x = jax.device_put(jnp.ones((8, H), jnp.bfloat16))

    @jax.jit
    def chain_list(x, *ws):
        for w in ws:
            x = jnp.tanh(x @ w)
        return x

    def _chain_stacked(x, ws):
        def body(h, w):
            return jnp.tanh(h @ w), None
        h, _ = jax.lax.scan(body, x, ws)
        return h

    chain_stacked = jax.jit(_chain_stacked)

    emit("list48", _time_call(chain_list, (x, *ws_list)),
         n_args=L + 1, arg_mib=round(L * H * H * 2 / 2**20))
    emit("stacked", _time_call(chain_stacked, (x, w_stack)),
         n_args=2, arg_mib=round(L * H * H * 2 / 2**20))

    chain_don = jax.jit(_chain_stacked, donate_argnums=(0,))
    emit("stacked_donated_x", _time_call(
        lambda w: chain_don(jax.device_put(jnp.ones((8, H), jnp.bfloat16)), w),
        (w_stack,)), n_args=2)

    # ---- D: handle-count sweep with tiny args ----
    for n in (1, 8, 48, 96):
        tiny = [jax.device_put(jnp.full((8,), float(i), jnp.float32))
                for i in range(n)]

        @jax.jit
        def add_all(*xs):
            s = xs[0]
            for t in xs[1:]:
                s = s + t
            return s

        emit(f"count_{n}", _time_call(add_all, tuple(tiny)), n_args=n)

    # ---- E: byte sweep with one handle ----
    for mib in (8, 128, 512):
        n_el = mib * 2**20 // 2
        big = jax.device_put(jnp.ones((n_el,), jnp.bfloat16))

        @jax.jit
        def touch(b):
            return b[:8].astype(jnp.float32) + 1.0

        emit(f"bytes_{mib}mib", _time_call(touch, (big,)), arg_mib=mib)

    # ---- F: pipelined-readback overlap probe (ISSUE 4) ----
    HH = 1024
    w_ov = jax.device_put(
        jax.random.normal(jax.random.fold_in(key, 99), (HH, HH),
                          jnp.float32) * 0.05)
    x_ov = jax.device_put(jnp.ones((64, HH), jnp.float32))

    @jax.jit
    def dev_step(x):
        h = x
        for _ in range(6):
            h = jnp.tanh(h @ w_ov)
        return h

    np.asarray(dev_step(x_ov))                    # compile + settle
    t0 = time.perf_counter()
    for _ in range(8):
        np.asarray(dev_step(x_ov))
    step_ms = (time.perf_counter() - t0) / 8 * 1e3
    fold_ms = max(step_ms * 0.8, 0.5)             # comparable fold cost
    iters = 24

    t0 = time.perf_counter()
    for _ in range(iters):
        out = dev_step(x_ov)
        np.asarray(out)                           # block on this tick
        time.sleep(fold_ms / 1e3)                 # then fold it
    sync_ms = (time.perf_counter() - t0) / iters * 1e3

    t0 = time.perf_counter()
    prev = None
    for _ in range(iters):
        out = dev_step(x_ov)                      # dispatch tick t
        out.copy_to_host_async()
        if prev is not None:
            np.asarray(prev)                      # t-1 already landed
            time.sleep(fold_ms / 1e3)             # fold under t's step
        prev = out
    np.asarray(prev)
    time.sleep(fold_ms / 1e3)   # final fold: both loops do iters folds
    pipe_ms = (time.perf_counter() - t0) / iters * 1e3
    comp = dict(device_step_ms=round(step_ms, 3),
                host_fold_ms=round(fold_ms, 3),
                components_sum_ms=round(step_ms + fold_ms, 3),
                components_max_ms=round(max(step_ms, fold_ms), 3))
    emit("overlap_sync", sync_ms, **comp)
    emit("overlap_pipelined", pipe_ms, **comp)

    # ---- summary: infer the dominant axis ----
    by = {r["probe"]: r["ms_per_call"] for r in rows}
    handle_slope = (by.get("count_96", 0) - by.get("count_1", 0)) / 95.0
    byte_slope = (by.get("bytes_512mib", 0) - by.get("bytes_8mib", 0)) / 504.0
    summary = {
        "probe": "ab_summary",
        "list48_ms": by.get("list48"),
        "stacked_ms": by.get("stacked"),
        "stack_speedup": round(by["list48"] / by["stacked"], 2)
        if by.get("stacked") else None,
        "ms_per_extra_handle": round(handle_slope, 4),
        "ms_per_arg_mib": round(byte_slope, 4),
        # overlap verdict: pipelined wall tracking components_max
        # (not components_sum) is the ISSUE 4 claim
        "overlap_sync_ms": by.get("overlap_sync"),
        "overlap_pipelined_ms": by.get("overlap_pipelined"),
        "overlap_hidden_ms": round(
            by.get("overlap_sync", 0.0)
            - by.get("overlap_pipelined", 0.0), 3),
    }
    print("AB_JSON " + json.dumps(summary), flush=True)


def main() -> None:
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--inner"],
            capture_output=True, text=True, timeout=TIMEOUT_S)
    except subprocess.TimeoutExpired:
        print(json.dumps({"probe": "skipped", "reason": "tunnel wedged"}))
        return
    got = False
    for line in out.stdout.splitlines():
        if line.startswith("AB_JSON "):
            print(line[len("AB_JSON "):])
            got = True
    if not got:
        print(json.dumps({"probe": "skipped",
                          "reason": f"rc={out.returncode}",
                          "stderr": out.stderr[-500:]}))


if __name__ == "__main__":
    if "--inner" in sys.argv:
        run_inner()
    else:
        main()
