"""Headline benchmark: Llama train-step MFU on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: the north-star target from BASELINE.json — Ray-Train-equivalent
Llama training at 40% MFU (vs_baseline = achieved_mfu / 0.40).

Runs on the real chip (axon platform default in this environment); falls
back to a small CPU run if no TPU is present so the bench never crashes.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# Peak dense bf16 TFLOP/s per chip by TPU generation.
PEAK_FLOPS = {
    "v5e": 197e12, "v5litepod": 197e12, "v5 lite": 197e12,
    "v5p": 459e12, "v4": 275e12, "v6e": 918e12,
}


def peak_for(device) -> float:
    name = (getattr(device, "device_kind", "") or "").lower()
    for key, val in PEAK_FLOPS.items():
        if key in name:
            return val
    return 197e12  # conservative default


def main() -> None:
    from ray_tpu.models import llama
    from ray_tpu.models.training import TrainStepBundle, default_optimizer
    from ray_tpu.parallel import MeshSpec

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if on_tpu:
        # ~915M params: large enough to fill the chip's MXU (head_dim 128,
        # 2048-wide matmuls) while params + adam state fit a 16 GiB HBM.
        cfg = llama.config(
            "tiny", vocab_size=32768, hidden=2048, n_layers=12, n_heads=16,
            n_kv_heads=8, head_dim=128, ffn=8192, max_seq=2048,
            attention_impl="pallas", remat_policy="nothing")
        batch, seq, iters = 4, 2048, 10
    else:
        cfg = llama.config("debug")
        batch, seq, iters = 4, 256, 3

    mesh = MeshSpec(dp=1, fsdp=1, sp=1, tp=1).build([dev])
    bundle = TrainStepBundle(
        cfg, mesh, optimizer=default_optimizer(total_steps=1000, mu_dtype=jnp.bfloat16))
    state = bundle.init_state(0)
    rng = np.random.default_rng(0)
    tokens = bundle.shard_batch(jnp.asarray(
        rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32))

    # Warmup (compile) then steady-state timing. Sync via host transfer of
    # the final loss: on the axon platform block_until_ready can return
    # before remote execution finishes, but a device->host copy cannot.
    for _ in range(2):
        state, metrics = bundle.step(state, tokens)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = bundle.step(state, tokens)
    final_loss = float(metrics["loss"])   # forces the full chain
    dt = (time.perf_counter() - t0) / iters

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step / dt
    flops = llama.flops_per_token(cfg, seq) * tokens_per_sec
    mfu = flops / peak_for(dev) if on_tpu else 0.0

    result = {
        "metric": "llama_train_mfu" if on_tpu else "llama_train_mfu_cpu_fallback",
        "value": round(mfu, 4) if on_tpu else round(tokens_per_sec, 1),
        "unit": "fraction_of_peak" if on_tpu else "tokens_per_sec",
        "vs_baseline": round(mfu / 0.40, 4) if on_tpu else 0.0,
        "detail": {
            "device": getattr(dev, "device_kind", str(dev)),
            "params": cfg.num_params(),
            "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
            "step_time_s": round(dt, 4),
            "batch": batch, "seq": seq,
            "loss": round(final_loss, 4),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
