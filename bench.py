"""Headline benchmark: Llama train-step MFU on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: the north-star target from BASELINE.json — Ray-Train-equivalent
Llama training at 40% MFU (vs_baseline = achieved_mfu / 0.40).

Robustness contract (the axon TPU tunnel on this box can wedge so hard
that even an 8x8 matmul blocks forever at 0% CPU): the orchestrating
process never touches the JAX backend itself.  It first probes the
backend in a subprocess under a short watchdog; if the probe hangs or
errors, it prints a machine-readable
    {"metric": ..., "skipped": "tpu_unreachable", ...}
line and exits 0, so the driver can tell an outage from a perf
regression.  The real bench also runs in a subprocess under a longer
watchdog in case the tunnel wedges mid-run.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

PROBE_TIMEOUT_S = int(os.environ.get("RAY_TPU_BENCH_PROBE_TIMEOUT", "120"))
BENCH_TIMEOUT_S = int(os.environ.get("RAY_TPU_BENCH_TIMEOUT", "1200"))
# The tunnel to the TPU chip flaps: a single probe at round end is a coin
# flip.  Retry the probe up to N times with a pause between attempts
# (defaults: 6 probes spread over ~15 min) before declaring an outage.
PROBE_RETRIES = int(os.environ.get("RAY_TPU_BENCH_PROBE_RETRIES", "6"))
PROBE_RETRY_DELAY_S = int(os.environ.get("RAY_TPU_BENCH_PROBE_RETRY_DELAY", "60"))
# Every attempt — green or skipped — is appended here with a timestamp so
# at least one mid-round green run survives in a driver-auditable artifact
# even if the round-end run hits an outage.
ATTEMPTS_LOG = os.environ.get(
    "RAY_TPU_BENCH_ATTEMPTS_LOG",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_ATTEMPTS.jsonl"))

# Peak dense bf16 TFLOP/s per chip by TPU generation.
PEAK_FLOPS = {
    "v5e": 197e12, "v5litepod": 197e12, "v5 lite": 197e12,
    "v5p": 459e12, "v4": 275e12, "v6e": 918e12,
}

_PROBE_SRC = """
import jax, jax.numpy as jnp
d = jax.devices()[0]
x = (jnp.ones((128, 128), jnp.bfloat16) @ jnp.ones((128, 128), jnp.bfloat16))
# A device->host copy cannot return before remote execution finishes
# (block_until_ready can, on the axon platform).
float(x[0, 0])
print("PROBE_OK", d.platform, getattr(d, "device_kind", str(d)), flush=True)
"""


def _mesh_arg() -> str:
    """`--mesh DxT` (e.g. `--mesh 1x2`): run the serving bench on a
    tp-sharded engine (ISSUE 17). Forwarded to the watchdogged inner
    subprocess via RAY_TPU_BENCH_MESH; empty = single-chip engine."""
    if "--mesh" in sys.argv:
        i = sys.argv.index("--mesh")
        if i + 1 >= len(sys.argv):
            raise SystemExit("--mesh needs a value, e.g. --mesh 1x2")
        return sys.argv[i + 1]
    return os.environ.get("RAY_TPU_BENCH_MESH", "")


def _mesh_chips(text: str) -> int:
    """Device count for a mesh text, without importing jax (the
    orchestrator must not touch the backend; the inner run validates
    properly via ops.tp_mesh.parse_mesh_shape)."""
    dims = [int(p) for p in text.replace("x", ",").split(",")
            if p.strip()]
    out = 1
    for d in dims:
        out *= max(d, 1)
    return max(out, 1)


def peak_for(device_kind: str) -> float:
    name = (device_kind or "").lower()
    for key, val in PEAK_FLOPS.items():
        if key in name:
            return val
    return 197e12  # conservative default


def _log_attempt(record: dict) -> None:
    """Append a timestamped attempt record to BENCH_ATTEMPTS.jsonl."""
    try:
        entry = dict(record)
        entry["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
        with open(ATTEMPTS_LOG, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except OSError:
        pass  # logging the attempt must never break the bench contract


def _skip(reason: str, detail: str = "") -> None:
    result = {
        "metric": "llama_train_mfu",
        "value": 0.0,
        "unit": "fraction_of_peak",
        "vs_baseline": 0.0,
        "skipped": reason,
        "detail": {"note": detail[-800:]} if detail else {},
    }
    _log_attempt(result)
    print(json.dumps(result))
    sys.exit(0)


def probe_backend() -> tuple[str, str]:
    """Probe the JAX backend in a subprocess. Returns (platform, kind).

    Retries a flapping tunnel up to PROBE_RETRIES times, then exits the
    whole bench with a "skipped" marker if the backend never comes up —
    that is an environment outage, not a perf regression.
    """
    last_failure = ""
    for attempt in range(1, PROBE_RETRIES + 1):
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True, text=True, timeout=PROBE_TIMEOUT_S)
        except subprocess.TimeoutExpired:
            last_failure = f"probe hung >{PROBE_TIMEOUT_S}s (tunnel wedged)"
        else:
            for line in out.stdout.splitlines():
                if line.startswith("PROBE_OK"):
                    parts = line.split(maxsplit=2)
                    return parts[1], (parts[2] if len(parts) > 2 else "")
            last_failure = (
                f"probe rc={out.returncode}: {out.stderr.strip()[-400:]}")
        sys.stderr.write(
            f"backend probe attempt {attempt}/{PROBE_RETRIES} failed: "
            f"{last_failure}\n")
        if attempt < PROBE_RETRIES:
            time.sleep(PROBE_RETRY_DELAY_S)
    _skip("tpu_unreachable",
          f"{PROBE_RETRIES} probes failed; last: {last_failure}")
    raise AssertionError  # unreachable


def run_inner() -> None:
    """The actual benchmark (runs inside a watchdogged subprocess)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import llama
    from ray_tpu.models.training import TrainStepBundle, default_optimizer
    from ray_tpu.parallel import MeshSpec

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if on_tpu:
        # ~915M params: large enough to fill the chip's MXU (head_dim 128,
        # 2048-wide matmuls) while params + adam state fit a 16 GiB HBM.
        # Sweep knobs (defaults = the committed 0.5592-MFU config):
        # RAY_TPU_BENCH_BATCH / _SEQ / _REMAT let a tunnel-up window be
        # used for quick MFU sweeps without editing this file.
        batch = int(os.environ.get("RAY_TPU_BENCH_BATCH", "4"))
        seq = int(os.environ.get("RAY_TPU_BENCH_SEQ", "2048"))
        remat = os.environ.get("RAY_TPU_BENCH_REMAT", "nothing")
        cfg = llama.config(
            "tiny", vocab_size=32768, hidden=2048, n_layers=12, n_heads=16,
            n_kv_heads=8, head_dim=128, ffn=8192,
            max_seq=max(seq, 2048),
            attention_impl="pallas", remat_policy=remat)
        iters = 10
    else:
        cfg = llama.config("debug")
        batch, seq, iters = 4, 256, 3

    mesh = MeshSpec(dp=1, fsdp=1, sp=1, tp=1).build([dev])
    bundle = TrainStepBundle(
        cfg, mesh, optimizer=default_optimizer(total_steps=1000, mu_dtype=jnp.bfloat16))
    state = bundle.init_state(0)
    rng = np.random.default_rng(0)
    tokens = bundle.shard_batch(jnp.asarray(
        rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32))

    # Warmup (compile) then steady-state timing. Sync via host transfer of
    # the final loss: on the axon platform block_until_ready can return
    # before remote execution finishes, but a device->host copy cannot.
    for _ in range(2):
        state, metrics = bundle.step(state, tokens)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = bundle.step(state, tokens)
    final_loss = float(metrics["loss"])   # forces the full chain
    dt = (time.perf_counter() - t0) / iters

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step / dt
    flops = llama.flops_per_token(cfg, seq) * tokens_per_sec
    kind = getattr(dev, "device_kind", str(dev))
    mfu = flops / peak_for(kind) if on_tpu else 0.0
    serving = _serving_mfu_bench(on_tpu)

    result = {
        "metric": "llama_train_mfu" if on_tpu else "llama_train_mfu_cpu_fallback",
        "value": round(mfu, 4) if on_tpu else round(tokens_per_sec, 1),
        "unit": "fraction_of_peak" if on_tpu else "tokens_per_sec",
        "vs_baseline": round(mfu / 0.40, 4) if on_tpu else 0.0,
        "detail": {
            "device": kind,
            "params": cfg.num_params(),
            "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
            "step_time_s": round(dt, 4),
            "batch": batch, "seq": seq,
            "loss": round(final_loss, 4),
            # ISSUE 11 / ROADMAP item 4: serving MFU per chip against
            # the analytic cost model + hardware envelope, so the next
            # BENCH_rNN lands directly on the >=40% serving-MFU target
            "serving": serving,
        },
    }
    print("BENCH_JSON " + json.dumps(result), flush=True)


def _serving_mfu_bench(on_tpu: bool) -> dict:
    """Steady-state continuous-batching decode through the paged-KV
    engine, reported as analytic serving MFU/MBU per chip (the
    engine's ISSUE 11 perf accounting). On CPU this measures against
    the BENCH_CORE-calibrated CPU envelope — a real ratio today, the
    same JSON shape the TPU run fills when the tunnel returns."""
    import numpy as np

    from ray_tpu.llm._internal.engine import (EngineConfig,
                                              InferenceEngine, Request,
                                              SamplingParams)
    from ray_tpu.models import llama as llama_models

    try:
        if on_tpu:
            cfg = llama_models.config(
                "tiny", vocab_size=32000, hidden=2048, n_layers=12,
                n_heads=16, n_kv_heads=8, head_dim=128, ffn=8192,
                max_seq=2048)
            batch, prompt_len, gen = 8, 128, 128
        else:
            cfg = llama_models.config("debug")
            batch, prompt_len, gen = 4, 16, 24
        # --mesh (ISSUE 17): shard the whole engine tp-wise across a
        # named mesh; the perf accountant divides the analytic
        # envelope by the mesh size, so mfu below stays PER CHIP
        mesh_text = os.environ.get("RAY_TPU_BENCH_MESH", "")
        ekw = {}
        if mesh_text:
            from ray_tpu.ops.tp_mesh import parse_mesh_shape
            shape = parse_mesh_shape(mesh_text)
            if shape[0] * shape[1] > 1:
                ekw["mesh_shape"] = shape
                ekw["unified_step"] = True
        eng = InferenceEngine(EngineConfig(
            model=cfg, max_batch_size=batch,
            num_pages=max(256, batch * 32), page_size=16, **ekw))
        rng = np.random.default_rng(0)
        reqs = [Request(f"s{i}",
                        rng.integers(1, cfg.vocab_size,
                                     prompt_len).tolist(),
                        SamplingParams(max_tokens=gen))
                for i in range(batch)]
        for r in reqs:
            eng.add_request(r)
        # warm until the whole batch decodes, then window pure decode
        while any(not r.output_tokens for r in reqs):
            eng.step()
        steps = 0
        while steps < gen - 8 and eng.has_work():
            eng.step()
            steps += 1
        perf = eng.stats()["perf"]
        return {
            # per-chip: the accountant's envelope is peak × n_chips
            "mfu": perf["mfu"],
            "mbu": perf["mbu"],
            "roof": perf["roof"],
            "envelope": perf["envelope"],
            "n_chips": perf["n_chips"],
            "mesh": mesh_text or None,
            "decode_tokens_per_s": perf["decode_tokens_per_s"],
            "decode_tokens_per_s_per_chip": round(
                perf["decode_tokens_per_s"]
                / max(perf["n_chips"], 1), 3),
            "params": cfg.num_params(),
            "batch": batch,
            "vs_target_0.40": round(perf["mfu"] / 0.40, 4),
        }
    except Exception as exc:      # the train headline must survive a
        return {"error": repr(exc)[:400]}     # serving-bench failure


def main() -> None:
    platform, kind = probe_backend()  # exits with a "skipped" line on outage
    sys.stderr.write(
        f"backend probe ok: platform={platform} kind={kind or '?'}\n")
    env = dict(os.environ)
    mesh = _mesh_arg()
    if mesh:
        env["RAY_TPU_BENCH_MESH"] = mesh
        if platform == "cpu":
            # emulate the mesh on host devices so --mesh 1x2 is
            # testable without a pod (same trick as the tier-1 suite)
            from ray_tpu._private.cpu_mesh import apply_cpu_mesh_env
            apply_cpu_mesh_env(env, _mesh_chips(mesh))
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--inner"],
            capture_output=True, text=True, timeout=BENCH_TIMEOUT_S,
            env=env)
    except subprocess.TimeoutExpired:
        _skip("tpu_unreachable",
              f"bench hung >{BENCH_TIMEOUT_S}s after a good probe "
              "(tunnel wedged mid-run)")
    for line in out.stdout.splitlines():
        if line.startswith("BENCH_JSON "):
            try:
                result = json.loads(line[len("BENCH_JSON "):])
            except ValueError:
                break    # truncated/interleaved line: fall to error path
            _log_attempt(result)
            print(json.dumps(result))
            return
    # The bench subprocess died without producing a result: a real error
    # (not an outage) — surface it loudly with a nonzero exit.
    sys.stderr.write(out.stdout[-2000:] + "\n" + out.stderr[-4000:] + "\n")
    result = {
        "metric": "llama_train_mfu", "value": 0.0,
        "unit": "fraction_of_peak", "vs_baseline": 0.0,
        "error": f"bench subprocess rc={out.returncode}",
    }
    _log_attempt(result)
    print(json.dumps(result))
    sys.exit(1)


if __name__ == "__main__":
    if "--inner" in sys.argv:
        run_inner()
    else:
        main()
