"""Core runtime microbenchmarks (reference: python/ray/_private/ray_perf.py).

Measures the task/actor hot paths against the reference's published
numbers (BASELINE.md, single m5.16xlarge 64-vCPU). This box is a
single-core VM, so absolute parity is not expected; per-core parity is
the target. Prints one JSON line per metric plus a summary line.

Usage: python bench_core.py [--quick]
"""

from __future__ import annotations

import json
import sys
import threading
import time

import ray_tpu


@ray_tpu.remote
class Sink:
    def noop(self):
        return None

    def echo(self, x):
        return x


@ray_tpu.remote
class AsyncSink:
    async def noop(self):
        return None


@ray_tpu.remote
def noop_task():
    return None


def rate(n, t):
    return round(n / t, 1)


def timed(fn, *args):
    t0 = time.perf_counter()
    fn(*args)
    return time.perf_counter() - t0


def bench_sync_actor_calls(actor, n):
    def run():
        for _ in range(n):
            ray_tpu.get(actor.noop.remote())
    return rate(n, timed(run))


def bench_async_actor_calls(actor, n, window=1000):
    def run():
        done = 0
        while done < n:
            batch = min(window, n - done)
            ray_tpu.get([actor.noop.remote() for _ in range(batch)])
            done += batch
    return rate(n, timed(run))


def bench_1n_actor_calls(actors, n):
    def run():
        refs = []
        for i in range(n):
            refs.append(actors[i % len(actors)].noop.remote())
        ray_tpu.get(refs)
    return rate(n, timed(run))


def bench_nn_actor_calls(actors, n, n_threads=4):
    """n caller threads each driving all actors (the reference's n:n is
    n drivers x n actors; threads stand in for extra driver cores)."""
    per = n // n_threads

    def worker(i):
        refs = [actors[j % len(actors)].noop.remote() for j in range(per)]
        ray_tpu.get(refs)

    def run():
        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    return rate(per * n_threads, timed(run))


def bench_tasks(n, window=500):
    def run():
        done = 0
        while done < n:
            batch = min(window, n - done)
            ray_tpu.get([noop_task.remote() for _ in range(batch)])
            done += batch
    return rate(n, timed(run))


def bench_put_get(n, payload):
    def run():
        for _ in range(n):
            ray_tpu.get(ray_tpu.put(payload))
    return rate(n, timed(run))


def bench_actor_creation(n, window=20):
    """Actors created+ready per second (BASELINE many_actors row)."""
    created = []

    def run():
        done = 0
        while done < n:
            batch = min(window, n - done)
            actors = [Sink.options(num_cpus=0).remote()
                      for _ in range(batch)]
            ray_tpu.get([a.noop.remote() for a in actors])
            created.extend(actors)
            done += batch
    r = rate(n, timed(run))
    for a in created:
        ray_tpu.kill(a)
    return r


def bench_placement_groups(n):
    """PG create+ready / remove latency (BASELINE many_pgs +
    stress_test_placement_group rows)."""
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)
    pgs = []

    def create():
        for _ in range(n):
            pg = placement_group([{"CPU": 0.01}], strategy="PACK")
            pg.ready(timeout=30)
            pgs.append(pg)

    t_create = timed(create)

    def remove():
        for pg in pgs:
            remove_placement_group(pg)

    t_remove = timed(remove)
    return rate(n, t_create), 1000.0 * t_remove / n


def main():
    quick = "--quick" in sys.argv
    scale = 1 if quick else 5
    ray_tpu.init(num_cpus=8)
    results = {}

    sink = Sink.remote()
    asink = AsyncSink.options(max_concurrency=16).remote()
    csink = Sink.options(max_concurrency=4).remote()
    actors = [Sink.remote() for _ in range(4)]
    ray_tpu.get(sink.noop.remote())
    ray_tpu.get(asink.noop.remote())
    ray_tpu.get(csink.noop.remote())
    ray_tpu.get([a.noop.remote() for a in actors])

    results["1_1_actor_calls_sync"] = bench_sync_actor_calls(sink, 200 * scale)
    results["1_1_actor_calls_async"] = bench_async_actor_calls(
        sink, 1000 * scale)
    results["1_1_actor_calls_concurrent"] = bench_async_actor_calls(
        csink, 1000 * scale)
    results["1_1_async_actor_calls_sync"] = bench_sync_actor_calls(
        asink, 200 * scale)
    results["1_1_async_actor_calls_async"] = bench_async_actor_calls(
        asink, 1000 * scale)
    results["1_n_actor_calls_async"] = bench_1n_actor_calls(
        actors, 1000 * scale)
    results["n_n_actor_calls_async"] = bench_nn_actor_calls(
        actors, 1000 * scale)
    results["tasks_per_second"] = bench_tasks(500 * scale)
    results["put_get_small_per_second"] = bench_put_get(
        200 * scale, b"x" * 100)
    results["actors_created_per_second"] = bench_actor_creation(
        8 * scale)
    pg_rate, pg_remove_ms = bench_placement_groups(10 * scale)
    results["placement_groups_per_second"] = pg_rate
    results["pg_remove_latency_ms"] = pg_remove_ms

    units = {"pg_remove_latency_ms": "ms"}
    for k, v in results.items():
        print(json.dumps({"metric": k, "value": v,
                          "unit": units.get(k, "calls/s")}))

    baseline = {  # BASELINE.md, m5.16xlarge (64 vCPU)
        "1_1_actor_calls_sync": 1959,
        "1_1_actor_calls_async": 8174,
        "1_1_actor_calls_concurrent": 5131,
        "1_1_async_actor_calls_sync": 1426,
        "1_1_async_actor_calls_async": 4284,
        "1_n_actor_calls_async": 8061,
        "n_n_actor_calls_async": 27210,
        "tasks_per_second": 368,
        # distributed rows measured on 64x64-core clusters; recorded for
        # visibility, not parity on one core
        "actors_created_per_second": 588,
        "placement_groups_per_second": 13.6,
    }
    summary = {k: {"ours": results[k], "ref": baseline[k],
                   "ratio": round(results[k] / baseline[k], 3)}
               for k in baseline}
    print(json.dumps({"metric": "core_summary", "detail": summary}))
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
