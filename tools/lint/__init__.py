"""tools.lint: the repo's static analyzers as one gate (ISSUE 20
satellite).

`python -m tools.lint [PATH...]` discovers the Python files ONCE
(lintcore's shared discovery) and runs every registered analyzer —
jaxlint (dispatch discipline, JL001-JL008) and racelint
(host-concurrency discipline, RL001-RL006) — over the same file set,
each against its own committed baseline. One command, one exit code:

    0  every analyzer clean (or baselined)
    1  any analyzer has new findings
    2  usage error

This is the pre-commit / CI entry point; the per-tool CLIs
(`python -m tools.jaxlint`, `python -m tools.racelint`) remain for
baseline surgery (--fix-baseline) and rule selection.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# default sweep: the library and the tools themselves
DEFAULT_PATHS = ("ray_tpu", "tools")


def analyzers() -> List[Tuple[str, Any, str]]:
    """(label, analyze_paths, baseline_path) per registered tool.
    A function, not a constant: each tool imports lazily so a usage
    error in one CLI arg doesn't pay for both ASTs."""
    from tools.jaxlint.analyzer import analyze_paths as jax_analyze
    from tools.racelint.analyzer import analyze_paths as race_analyze
    return [
        ("jaxlint", jax_analyze,
         os.path.join(REPO_ROOT, "tools", "jaxlint",
                      "baseline.json")),
        ("racelint", race_analyze,
         os.path.join(REPO_ROOT, "tools", "racelint",
                      "baseline.json")),
    ]


def run(paths: List[str], root: str = ".") -> Dict[str, Any]:
    """Run every analyzer over `paths`; returns a per-tool report:
    {"<label>": {"new": [Finding...], "baselined": n, "stale": [...]},
     "ok": bool}."""
    from tools.lintcore import load_baseline

    report: Dict[str, Any] = {}
    ok = True
    for label, analyze, baseline_path in analyzers():
        findings = analyze(paths, root=root)
        baseline = load_baseline(baseline_path)
        new, old, stale = baseline.split(findings)
        report[label] = {"new": new, "baselined": len(old),
                         "stale": stale}
        ok = ok and not new
    report["ok"] = ok
    return report


__all__ = ["analyzers", "run", "DEFAULT_PATHS", "REPO_ROOT"]
