"""CLI: python -m tools.lint [PATH...]

Runs jaxlint + racelint in one pass over the shared lintcore file
discovery, each against its committed baseline. Exit codes: 0 = all
analyzers clean (or baselined), 1 = any new finding, 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="run every repo static analyzer (jaxlint + "
                    "racelint) with its committed baseline")
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: ray_tpu "
                         "and tools, from the repo root)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    from tools import lint

    if args.paths:
        paths, root = list(args.paths), "."
    else:
        # no args: sweep the canonical set from the repo root so the
        # baseline keys (repo-relative) line up regardless of cwd
        paths = [os.path.join(lint.REPO_ROOT, p)
                 for p in lint.DEFAULT_PATHS]
        root = lint.REPO_ROOT
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"lint: no such path(s): {missing}", file=sys.stderr)
        return 2

    report = lint.run(paths, root=root)

    if args.as_json:
        print(json.dumps({
            label: {
                "new": [vars(f) | {"key": f.key}
                        for f in body["new"]],
                "baselined": body["baselined"],
                "stale_baseline_keys": body["stale"],
            }
            for label, body in report.items() if label != "ok"
        }, indent=2))
        return 0 if report["ok"] else 1

    for label, body in report.items():
        if label == "ok":
            continue
        for f in body["new"]:
            print(f.render())
        if body["baselined"]:
            print(f"[{label}] {body['baselined']} baselined "
                  f"finding(s) suppressed", file=sys.stderr)
        for k in body["stale"]:
            print(f"[{label}] stale baseline entry (fixed? remove "
                  f"it): {k}", file=sys.stderr)
        if not body["new"]:
            print(f"[{label}] clean: 0 new", file=sys.stderr)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
