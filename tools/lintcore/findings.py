"""The Finding record shared by every analyzer.

Findings carry a line number for humans but their BASELINE KEY is
line-independent (rule : path : function-qualname : detail) so code
motion above a finding never churns the baseline.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # posix relpath (baseline-stable)
    line: int          # for humans; NOT part of the baseline key
    func: str          # qualname of the enclosing function ("" = module)
    detail: str        # stable symbol-level detail
    message: str

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.func}:{self.detail}"

    def render(self) -> str:
        where = self.func or "<module>"
        return (f"{self.path}:{self.line}: {self.rule} [{where}] "
                f"{self.message}")
