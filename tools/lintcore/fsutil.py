"""Path normalization + file discovery shared by every analyzer."""

from __future__ import annotations

import os
from typing import Iterable, List


def normalize_relpath(path: str, root: str) -> str:
    """The ONE producer of baseline-key paths (shared by the
    analyzers' add_file and the CLI's analyzed-paths set — they must
    never diverge, or scoped --fix-baseline retention breaks)."""
    rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    if rel.startswith(".."):
        rel = os.path.abspath(path)
    return rel.replace(os.sep, "/")


def iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"
                               and not d.startswith(".")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
    return out
