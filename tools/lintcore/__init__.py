"""lintcore: the tool-agnostic machinery shared by the repo's static
analyzers (tools/jaxlint for dispatch discipline, tools/racelint for
host-concurrency discipline).

What lives here is exactly the part that does not know what a rule
is: the Finding record with its line-independent baseline key, the
baseline store (justified accepted findings, occurrence counts,
scoped --fix-baseline retention), the inline-suppression parser
(`# <tool>: disable=XX123 -- reason`, plus shared `# noqa:`), file
discovery, and the CLI scaffold (exit codes, output format, baseline
plumbing). Each analyzer keeps its own indexer and rule catalogue.

Stdlib only — no new dependencies.
"""

from .findings import Finding  # noqa: F401
from .fsutil import iter_py_files, normalize_relpath  # noqa: F401
from .suppress import parse_suppressions, suppress_pattern  # noqa: F401
from .baseline import (  # noqa: F401
    Baseline,
    load_baseline,
    write_baseline,
)
from .cli import run_cli  # noqa: F401

__all__ = [
    "Finding", "iter_py_files", "normalize_relpath",
    "parse_suppressions", "suppress_pattern",
    "Baseline", "load_baseline", "write_baseline",
    "run_cli",
]
