"""Inline-suppression parsing.

Each tool has its own disable prefix (`# jaxlint: disable=JL006`,
`# racelint: disable=RL001`) so a jaxlint suppression can never
accidentally silence racelint on the same line; the bare `# noqa:`
form is shared. A justification rides in the same comment after
` -- `, by convention enforced by each tool's tier-1 lint test.
"""

from __future__ import annotations

import io
import re
import tokenize
from typing import Dict, Pattern, Set

_RULE_LIST = r"([A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)"


def suppress_pattern(tool: str) -> Pattern[str]:
    return re.compile(
        rf"#\s*(?:{re.escape(tool)}:\s*disable=|noqa:\s*)" + _RULE_LIST)


def parse_suppressions(source: str, tool: str) -> Dict[int, Set[str]]:
    """line -> set of rule ids disabled on that line."""
    pattern = suppress_pattern(tool)
    out: Dict[int, Set[str]] = {}
    try:
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT:
                continue
            m = pattern.search(tok.string)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                out.setdefault(tok.start[0], set()).update(rules)
    except tokenize.TokenError:
        pass
    return out
