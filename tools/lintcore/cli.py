"""Shared CLI scaffold: python -m tools.<tool> PATH... [--baseline F]

Exit codes: 0 = clean (or every finding baselined), 1 = new findings,
2 = usage error. `--fix-baseline` rewrites the baseline from the
current findings (carrying forward justifications; additions get a
TODO placeholder each tool's tier-1 lint test refuses to ship — write
the justification before committing).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Callable, Iterable, List, Optional, Set

from .baseline import Baseline, load_baseline, write_baseline
from .findings import Finding
from .fsutil import iter_py_files, normalize_relpath


def _relpaths(paths, root):
    """Baseline-key relpaths of the files this run analyzed."""
    return {normalize_relpath(p, root) for p in iter_py_files(paths)}


def run_cli(argv: Optional[List[str]], *, prog: str, description: str,
            label: str, all_rules: Iterable[str],
            analyze: Callable[..., List[Finding]]) -> int:
    """The whole CLI, minus what makes a tool a tool.

    `analyze(paths, root=..., select=...)` is the tool's driver;
    `label` prefixes the status lines ("[jaxlint] clean: ...")."""
    ap = argparse.ArgumentParser(prog=prog, description=description)
    ap.add_argument("paths", nargs="+", help="files or directories")
    ap.add_argument("--baseline", help="baseline JSON of accepted "
                                       "findings (with justifications)")
    ap.add_argument("--fix-baseline", action="store_true",
                    help="rewrite the baseline from current findings")
    ap.add_argument("--select", help="comma-separated rule ids "
                                     "(default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--root", default=".",
                    help="path-key root (default: cwd)")
    args = ap.parse_args(argv)

    select = None
    if args.select:
        select = {r.strip() for r in args.select.split(",")}
        unknown = select - set(all_rules)
        if unknown:
            print(f"unknown rule(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2

    findings = analyze(args.paths, root=args.root, select=select)

    baseline = Baseline({})
    if args.baseline and not args.fix_baseline:
        try:
            baseline = load_baseline(args.baseline)
        except FileNotFoundError:
            print(f"baseline not found: {args.baseline}",
                  file=sys.stderr)
            return 2
    if args.fix_baseline:
        if not args.baseline:
            print("--fix-baseline requires --baseline",
                  file=sys.stderr)
            return 2
        if select:
            # a rule-filtered rewrite would silently delete every
            # entry for the unselected rules
            print("--fix-baseline cannot be combined with --select",
                  file=sys.stderr)
            return 2
        prior = Baseline({})
        try:
            prior = load_baseline(args.baseline)
        except FileNotFoundError:
            pass
        n = write_baseline(args.baseline, findings, prior,
                           analyzed_paths=_relpaths(args.paths,
                                                    args.root))
        print(f"wrote {n} baseline entr{'y' if n == 1 else 'ies'} "
              f"to {args.baseline}")
        return 0

    new, old, stale = baseline.split(findings)

    if args.as_json:
        print(json.dumps({
            "new": [vars(f) | {"key": f.key} for f in new],
            "baselined": [f.key for f in old],
            "stale_baseline_keys": stale,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        if old:
            print(f"[{label}] {len(old)} baselined finding(s) "
                  f"suppressed", file=sys.stderr)
        for k in stale:
            print(f"[{label}] stale baseline entry (fixed? remove "
                  f"it): {k}", file=sys.stderr)
        if not new:
            print(f"[{label}] clean: {len(findings)} finding(s), "
                  f"0 new", file=sys.stderr)
    return 1 if new else 0
