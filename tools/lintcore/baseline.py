"""Baseline handling: known findings that are accepted WITH a
justification. New findings (keys not in the baseline, or MORE
occurrences of a baselined key than the baseline records) fail the
lint; stale entries (baselined keys no longer found, or found fewer
times) are warned about so the baseline only ever shrinks — burndown
is tracked in BENCH_CORE.md.

Keys are line-independent (rule:path:function:detail), so each entry
carries an occurrence COUNT: without it, adding a second identical
violation to an already-baselined function would be silently
accepted.
"""

from __future__ import annotations

import dataclasses
import json
from collections import Counter
from typing import Dict, Iterable, List

from .findings import Finding


@dataclasses.dataclass
class Baseline:
    entries: Dict[str, str]          # key -> justification
    counts: Dict[str, int] = dataclasses.field(default_factory=dict)

    def count(self, key: str) -> int:
        return self.counts.get(key, 1)

    def split(self, findings: Iterable[Finding]):
        """-> (new_findings, baselined_findings, stale_keys).

        Occurrences of a baselined key beyond its recorded count are
        NEW (last by line number, so the stable earlier sites stay
        baselined and the added one is reported); keys found fewer
        times than recorded are stale ("N-k occurrences fixed")."""
        new: List[Finding] = []
        old: List[Finding] = []
        by_key: Dict[str, List[Finding]] = {}
        for f in findings:
            by_key.setdefault(f.key, []).append(f)
        for key, group in by_key.items():
            if key not in self.entries:
                new.extend(group)
                continue
            group.sort(key=lambda f: f.line)
            allowed = self.count(key)
            old.extend(group[:allowed])
            new.extend(group[allowed:])
        stale = []
        for key in self.entries:
            found = len(by_key.get(key, ()))
            if found == 0:
                stale.append(key)
            elif found < self.count(key):
                stale.append(
                    f"{key} ({self.count(key) - found} of "
                    f"{self.count(key)} occurrences fixed)")
        return new, old, sorted(stale)


def load_baseline(path: str) -> Baseline:
    with open(path, "r", encoding="utf-8") as f:
        raw = json.load(f)
    entries: Dict[str, str] = {}
    counts: Dict[str, int] = {}
    for e in raw.get("entries", []):
        entries[e["key"]] = e.get("justification", "")
        counts[e["key"]] = int(e.get("count", 1))
    return Baseline(entries, counts)


def write_baseline(path: str, findings: Iterable[Finding],
                   prior: Baseline = None,
                   analyzed_paths: Iterable[str] = None) -> int:
    """Rewrite the baseline from current findings, carrying forward
    existing justifications; new entries get an explicit TODO that the
    lint test refuses to ship.

    analyzed_paths: the relpaths this run actually looked at. Prior
    entries for files OUTSIDE that set are retained untouched —
    running --fix-baseline on a subdirectory must not destroy the
    rest of the tree's entries (their staleness cannot be judged
    from a scoped run)."""
    prior_entries = prior.entries if prior else {}
    prior_counts = prior.counts if prior else {}
    counts = Counter(f.key for f in findings)
    if analyzed_paths is not None:
        analyzed = set(analyzed_paths)
        for key in prior_entries:
            key_path = key.split(":", 2)[1]
            if key_path not in analyzed and key not in counts:
                counts[key] = prior_counts.get(key, 1)
    entries = [{"key": k,
                "count": counts[k],
                "justification": prior_entries.get(
                    k, "TODO: justify or fix")}
               for k in sorted(counts)]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"entries": entries}, f, indent=2, sort_keys=False)
        f.write("\n")
    return len(entries)
