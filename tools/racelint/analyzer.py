"""Concurrency indexing + driver for racelint.

The indexer builds, per class, a picture of the host threading plane:

- **Lock fields** — `self._step_lock = threading.Lock()` (also RLock /
  Condition / Semaphore, and the sanitizer's `make_lock(...)`), plus
  module-level locks. Lock identity is `{Class}.{field}` so the
  acquisition graph is stable across instances.
- **Lock sets** — every interesting event (field write, container
  iteration, call, lock acquisition, thread construction) is recorded
  with the set of locks held at that point, from `with self._lock:`
  nesting. Cross-method inference: a private method's ENTRY lock set
  is the intersection over its intra-class call sites of (caller
  entry set ∪ locks held at the site), to a fixpoint — so a
  `_foo_locked` helper called only under `_step_lock` counts as
  locked without any annotation. Public methods (and methods with no
  intra-class callers) get an empty entry set: external callers hold
  nothing.
- **Async context** — whether an event sits directly in an
  `async def` body (not inside a nested `def`), for the
  blocking-call-on-the-event-loop rule.

Rules (rules.py) consume this index per module; there is no
cross-module propagation — the serving plane's locks are
class-scoped by design, and cross-module guessing is how false
positives happen.

Findings reuse lintcore's line-independent baseline keys
(rule:path:function:detail) and `# racelint: disable=RLnnn -- reason`
suppressions.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from ..lintcore import (
    Finding,
    iter_py_files,
    normalize_relpath,
    parse_suppressions,
)

# Constructors whose result is a lock-like object, by call-name tail.
LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "cond",
              "Semaphore": "sem", "BoundedSemaphore": "sem",
              "make_lock": "lock"}

# Constructors whose result is a shared mutable container (RL004
# tracks iterate-vs-mutate on these).
CONTAINER_CTORS = {"list", "dict", "set", "deque", "defaultdict",
                   "Counter", "OrderedDict"}

# Method calls that mutate a container in place.
MUTATOR_METHODS = {"append", "appendleft", "extend", "extendleft",
                   "add", "insert", "remove", "discard", "pop",
                   "popleft", "popitem", "clear", "update",
                   "setdefault", "rotate", "sort", "reverse"}

# Builtins whose call iterates their (first) argument.
ITERATING_BUILTINS = {"list", "tuple", "sorted", "set", "frozenset",
                      "dict", "sum", "max", "min", "any", "all",
                      "enumerate"}

# Snapshot-style accessor tails: `self.f.values()` etc. iterate f.
VIEW_METHODS = {"values", "items", "keys", "copy"}


def dotted_name(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _self_field(node: ast.AST) -> Optional[str]:
    """'f' for a bare `self.f` / `cls.f` attribute node."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls")):
        return node.attr
    return None


class Event:
    """One indexed occurrence inside a method body. `holds` is the
    LOCAL lock set (with-nesting inside this method); the effective
    set is entry_lockset | holds, resolved after the fixpoint."""

    __slots__ = ("kind", "name", "holds", "line", "async_direct",
                 "extra")

    def __init__(self, kind: str, name: str, holds: FrozenSet[str],
                 line: int, async_direct: bool, extra=None):
        self.kind = kind        # write|iter|acquire|call|self_call|thread
        self.name = name
        self.holds = holds
        self.line = line
        self.async_direct = async_direct
        self.extra = extra


class MethodIndex:
    def __init__(self, name: str, qualname: str, class_name: str,
                 node: ast.AST, is_async: bool):
        self.name = name
        self.qualname = qualname
        self.class_name = class_name
        self.node = node
        self.is_async = is_async
        self.events: List[Event] = []
        self.entry: FrozenSet[str] = frozenset()
        # call sites of this method from class siblings, filled by
        # ClassIndex.infer_entry_locksets
        self._entry_known = False

    def lockset(self, ev: Event) -> FrozenSet[str]:
        return self.entry | ev.holds

    @property
    def is_init(self) -> bool:
        return (self.name == "__init__"
                or self.qualname.split(".")[-1] == "__init__"
                or ".__init__." in f".{self.qualname}.")


class ClassIndex:
    def __init__(self, name: str):
        self.name = name
        self.lock_fields: Dict[str, str] = {}       # field -> kind
        self.container_fields: Set[str] = set()
        self.async_fields: Set[str] = set()          # asyncio.X() values
        self.methods: Dict[str, MethodIndex] = {}
        self.nested: List[MethodIndex] = []          # closures etc.
        self.joined_fields: Set[str] = set()         # self.X with X.join()
        self.daemon_fields: Set[str] = set()         # self.X.daemon = True

    def all_methods(self) -> List[MethodIndex]:
        return list(self.methods.values()) + self.nested

    def lock_id(self, field: str) -> str:
        return f"{self.name}.{field}"

    def lock_kind(self, lock_id: str) -> str:
        field = lock_id.rsplit(".", 1)[-1]
        return self.lock_fields.get(field, "lock")

    def infer_entry_locksets(self) -> None:
        """Fixpoint over intra-class call sites. Only private methods
        (leading underscore, not dunder) inherit — a public method is
        an API surface and must assume callers hold nothing."""
        sites: Dict[str, List[Tuple[MethodIndex, FrozenSet[str]]]] = {}
        for m in self.all_methods():
            for ev in m.events:
                if ev.kind == "self_call" and ev.name in self.methods:
                    sites.setdefault(ev.name, []).append((m, ev.holds))
        for _ in range(20):
            changed = False
            for name, method in self.methods.items():
                if (not name.startswith("_") or name.startswith("__")
                        or name not in sites):
                    continue
                new = None
                for caller, holds in sites[name]:
                    s = caller.entry | holds
                    new = s if new is None else (new & s)
                new = new or frozenset()
                if new != method.entry:
                    method.entry = frozenset(new)
                    changed = True
            if not changed:
                break


class ConcurrencyModule:
    def __init__(self, path: str, relpath: str, tree: ast.Module,
                 source: str):
        self.path = path
        self.relpath = relpath
        self.tree = tree
        self.source = source
        self.classes: Dict[str, ClassIndex] = {}
        self.module_locks: Dict[str, str] = {}      # name -> kind
        self.functions: List[MethodIndex] = []       # module-level defs
        self.suppressions: Dict[int, Set[str]] = {}
        self.local_joins: Set[str] = set()           # "qualname:var"
        self.local_daemons: Set[str] = set()

    def all_methods(self) -> List[MethodIndex]:
        out = list(self.functions)
        for cls in self.classes.values():
            out.extend(cls.all_methods())
        return out

    def suppressed(self, rule: str, line: int,
                   method: Optional[MethodIndex]) -> bool:
        """A disable comment suppresses on its own line or, placed on
        any line of the enclosing `def` signature, for the whole
        function (`# racelint: disable=RL001 -- reason`)."""
        if rule in self.suppressions.get(line, ()):
            return True
        if method is not None:
            node = method.node
            body = getattr(node, "body", None)
            end = (body[0].lineno if isinstance(body, list) and body
                   else node.lineno + 1)
            if any(rule in self.suppressions.get(ln, ())
                   for ln in range(node.lineno, end)):
                return True
        return False


class _FunctionWalker:
    """Walks ONE function body statement-by-statement, maintaining the
    with-nesting lock stack; nested defs are queued for their own
    walk (empty entry lock set — their execution time is unknown)."""

    def __init__(self, mod: ConcurrencyModule, cls: Optional[ClassIndex],
                 method: MethodIndex):
        self.mod = mod
        self.cls = cls
        self.method = method
        self.holds: List[str] = []
        self.nested_defs: List[ast.AST] = []

    # -- lock identity -------------------------------------------------
    def _lock_id_for(self, expr: ast.AST) -> Optional[str]:
        field = _self_field(expr)
        if field is not None and self.cls is not None \
                and field in self.cls.lock_fields:
            return self.cls.lock_id(field)
        if isinstance(expr, ast.Name) \
                and expr.id in self.mod.module_locks:
            return f"<module>.{expr.id}"
        return None

    # -- event emission ------------------------------------------------
    def _emit(self, kind: str, name: str, line: int, extra=None):
        self.method.events.append(Event(
            kind, name, frozenset(self.holds), line,
            self.method.is_async, extra))

    # -- statement dispatch --------------------------------------------
    def walk(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.nested_defs.append(stmt)
            return
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in stmt.items:
                self._exprs(item.context_expr)
                lock = self._lock_id_for(item.context_expr)
                if lock is not None:
                    self._emit("acquire", lock, stmt.lineno)
                    self.holds.append(lock)
                    acquired.append(lock)
            self.walk(stmt.body)
            for _ in acquired:
                self.holds.pop()
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._iteration(stmt.iter)
            self._exprs(stmt.iter)
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            return
        if isinstance(stmt, ast.While):
            self._exprs(stmt.test)
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            return
        if isinstance(stmt, ast.If):
            self._exprs(stmt.test)
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self.walk(stmt.body)
            for h in stmt.handlers:
                self.walk(h.body)
            self.walk(stmt.orelse)
            self.walk(stmt.finalbody)
            return
        if isinstance(stmt, ast.Assign):
            self._assign(stmt)
            return
        if isinstance(stmt, ast.AugAssign):
            field = _self_field(stmt.target)
            if field is None and isinstance(stmt.target, ast.Subscript):
                field = _self_field(stmt.target.value)
            if field is not None:
                self._emit("write", field, stmt.lineno, "augassign")
            self._exprs(stmt.value)
            return
        if isinstance(stmt, ast.AnnAssign):
            field = _self_field(stmt.target)
            if field is not None and stmt.value is not None:
                self._emit("write", field, stmt.lineno, "assign")
            if stmt.value is not None:
                self._exprs(stmt.value)
            return
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Subscript):
                    field = _self_field(tgt.value)
                    if field is not None:
                        self._emit("write", field, tgt.lineno, "del")
            return
        # Expr / Return / Raise / Assert / simple statements: scan
        # their expression trees
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._exprs(child)

    # -- assignments ---------------------------------------------------
    def _assign(self, stmt: ast.Assign) -> None:
        bound: Optional[str] = None
        for tgt in stmt.targets:
            field = _self_field(tgt)
            if field is not None:
                self._emit("write", field, stmt.lineno, "assign")
                bound = f"self.{field}"
            elif isinstance(tgt, ast.Subscript):
                sub = _self_field(tgt.value)
                if sub is not None:
                    self._emit("write", sub, stmt.lineno, "setitem")
            elif isinstance(tgt, ast.Name):
                bound = tgt.id
            elif isinstance(tgt, ast.Attribute):
                # `t.daemon = True` on a local thread handle
                if tgt.attr == "daemon" and isinstance(tgt.value,
                                                      ast.Name):
                    self.mod.local_daemons.add(
                        f"{self.method.qualname}:{tgt.value.id}")
                dfield = _self_field(tgt.value)
                if tgt.attr == "daemon" and dfield is not None \
                        and self.cls is not None:
                    self.cls.daemon_fields.add(dfield)
        self._exprs(stmt.value, bound_to=bound)

    # -- expression scanning -------------------------------------------
    def _exprs(self, node: ast.AST, bound_to: Optional[str] = None):
        """Scan an expression tree for events. Does not descend into
        lambdas / nested defs; comprehension iterables count as
        iterations. Calls directly under `await` are marked — awaiting
        a coroutine is how the loop is SUPPOSED to wait."""
        awaited = {id(sub.value) for sub in self._walk_expr(node)
                   if isinstance(sub, ast.Await)
                   and isinstance(sub.value, ast.Call)}
        for sub in self._walk_expr(node):
            if isinstance(sub, (ast.ListComp, ast.SetComp, ast.DictComp,
                                ast.GeneratorExp)):
                for gen in sub.generators:
                    self._iteration(gen.iter)
            elif isinstance(sub, ast.Call):
                self._call(sub, bound_to if sub is node else None,
                           awaited=id(sub) in awaited)

    def _walk_expr(self, node: ast.AST):
        stack = [node]
        while stack:
            cur = stack.pop()
            if isinstance(cur, (ast.Lambda, ast.FunctionDef,
                                ast.AsyncFunctionDef)):
                continue
            yield cur
            stack.extend(ast.iter_child_nodes(cur))

    def _iteration(self, expr: ast.AST) -> None:
        """`for x in <expr>` / comprehension iterable: is it a shared
        self-container (directly, or via .values()/.items()/...)?"""
        field = _self_field(expr)
        if field is None and isinstance(expr, ast.Call) \
                and isinstance(expr.func, ast.Attribute) \
                and expr.func.attr in VIEW_METHODS:
            field = _self_field(expr.func.value)
        if field is not None:
            self._emit("iter", field, expr.lineno)

    def _call(self, call: ast.Call, bound_to: Optional[str] = None,
              awaited: bool = False) -> None:
        name = dotted_name(call.func)
        if not name and isinstance(call.func, ast.Attribute):
            # method call on a computed receiver, e.g.
            # `asyncio.get_running_loop().run_in_executor(...)` —
            # keep the attr so loop-awareness checks still see it
            name = f"?.{call.func.attr}"
        tail = name.split(".")[-1] if name else ""
        # container mutation: self.f.append(...)
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in MUTATOR_METHODS:
            field = _self_field(call.func.value)
            if field is not None:
                self._emit("write", field, call.lineno, "mutcall")
        # iterating builtin: sorted(self.f), list(self.f.items())...
        if tail in ITERATING_BUILTINS and "." not in name and call.args:
            self._iteration(call.args[0])
        # thread construction
        if tail == "Thread" and name in ("Thread", "threading.Thread"):
            daemon = any(
                kw.arg == "daemon"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in call.keywords)
            self._emit("thread", bound_to or "", call.lineno, daemon)
        # .join() / .setDaemon() tracking for RL005
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in ("join", "setDaemon"):
            recv = call.func.value
            field = _self_field(recv)
            dest = (self.cls.joined_fields if call.func.attr == "join"
                    else self.cls.daemon_fields) if self.cls else None
            if field is not None and dest is not None:
                dest.add(field)
            elif isinstance(recv, ast.Name):
                key = f"{self.method.qualname}:{recv.id}"
                (self.mod.local_joins if call.func.attr == "join"
                 else self.mod.local_daemons).add(key)
        # self-method calls (for entry-lockset inference + RL002/RL006)
        if isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Name) \
                and call.func.value.id in ("self", "cls"):
            self._emit("self_call", call.func.attr, call.lineno,
                       {"nargs": len(call.args) + len(call.keywords),
                        "awaited": awaited})
        elif name:
            async_recv = (self.cls is not None
                          and isinstance(call.func, ast.Attribute)
                          and _self_field(call.func.value)
                          in self.cls.async_fields)
            self._emit("call", name, call.lineno,
                       {"nargs": len(call.args) + len(call.keywords),
                        "awaited": awaited, "async_recv": async_recv})


class _ModuleIndexer(ast.NodeVisitor):
    """Top-level walk: classes, their methods, module functions,
    module locks. Bodies are handed to _FunctionWalker."""

    def __init__(self, mod: ConcurrencyModule):
        self.mod = mod

    def index(self) -> None:
        for stmt in self.mod.tree.body:
            if isinstance(stmt, ast.ClassDef):
                self._index_class(stmt)
            elif isinstance(stmt, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self._index_function(stmt, None, stmt.name)
            elif isinstance(stmt, ast.Assign):
                self._module_assign(stmt)

    def _module_assign(self, stmt: ast.Assign) -> None:
        kind = _lock_ctor_kind(stmt.value)
        if kind is None:
            return
        for tgt in stmt.targets:
            if isinstance(tgt, ast.Name):
                self.mod.module_locks[tgt.id] = kind

    def _index_class(self, node: ast.ClassDef) -> None:
        cls = ClassIndex(node.name)
        self.mod.classes[node.name] = cls
        # pass 1: find lock + container fields from every method body
        # (they are almost always in __init__, but restores/rebinds
        # happen elsewhere)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                targets = sub.targets
                value = sub.value
            elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                targets = [sub.target]
                value = sub.value
            else:
                continue
            for tgt in targets:
                field = _self_field(tgt)
                if field is None:
                    continue
                kind = _lock_ctor_kind(value)
                if kind is not None:
                    cls.lock_fields[field] = kind
                elif _is_container_ctor(value):
                    cls.container_fields.add(field)
                if isinstance(value, ast.Call) and dotted_name(
                        value.func).startswith("asyncio."):
                    # e.g. self._q = asyncio.Queue(): methods on it
                    # return awaitables, they don't block the loop
                    cls.async_fields.add(field)
        # pass 2: walk method bodies
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_function(stmt, cls, stmt.name)

    def _index_function(self, node, cls: Optional[ClassIndex],
                        name: str, qual_prefix: str = "") -> None:
        qual = (f"{qual_prefix}.{name}" if qual_prefix
                else (f"{cls.name}.{name}" if cls else name))
        m = MethodIndex(name, qual, cls.name if cls else "", node,
                        isinstance(node, ast.AsyncFunctionDef))
        if cls is not None and not qual_prefix:
            cls.methods[name] = m
        elif cls is not None:
            cls.nested.append(m)
        else:
            self.mod.functions.append(m)
        walker = _FunctionWalker(self.mod, cls, m)
        walker.walk(node.body)
        for nested in walker.nested_defs:
            self._index_function(nested, cls, nested.name,
                                 qual_prefix=qual)


def _lock_ctor_kind(value: ast.AST) -> Optional[str]:
    if not isinstance(value, ast.Call):
        return None
    name = dotted_name(value.func)
    tail = name.split(".")[-1]
    if tail not in LOCK_CTORS:
        return None
    base = name.split(".")[0]
    if tail == "make_lock" or base in ("threading", "thread_sanitizer",
                                       tail):
        return LOCK_CTORS[tail]
    return None


def _is_container_ctor(value: ast.AST) -> bool:
    if isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        tail = dotted_name(value.func).split(".")[-1]
        return tail in CONTAINER_CTORS
    return False


class ConcurrencyProject:
    def __init__(self, root: str = "."):
        self.root = os.path.abspath(root)
        self.modules: List[ConcurrencyModule] = []

    def add_file(self, path: str) -> Optional[ConcurrencyModule]:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            return None
        rel = normalize_relpath(path, self.root)
        mod = ConcurrencyModule(path, rel, tree, source)
        mod.suppressions = parse_suppressions(source, "racelint")
        _ModuleIndexer(mod).index()
        for cls in mod.classes.values():
            cls.infer_entry_locksets()
        self.modules.append(mod)
        return mod


def analyze_paths(paths: Iterable[str], root: str = ".",
                  select: Optional[Set[str]] = None) -> List[Finding]:
    """Analyze files/dirs, returning suppression-filtered findings."""
    from . import rules
    project = ConcurrencyProject(root)
    for path in iter_py_files(paths):
        project.add_file(path)
    kept: List[Finding] = []
    for mod in project.modules:
        for f in rules.check_module(mod):
            if select and f.rule not in select:
                continue
            method = _find_method(mod, f.func)
            if not mod.suppressed(f.rule, f.line, method):
                kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return kept


def _find_method(mod: ConcurrencyModule,
                 qualname: str) -> Optional[MethodIndex]:
    for m in mod.all_methods():
        if m.qualname == qualname:
            return m
    return None
