"""CLI: python -m tools.racelint PATH... [--baseline FILE]

Exit codes: 0 = clean (or every finding baselined), 1 = new findings,
2 = usage error. Shares the scaffold (and therefore flags, exit
codes, and output format) with jaxlint via tools/lintcore/cli.py.
"""

from __future__ import annotations

import sys

from ..lintcore import run_cli
from .analyzer import analyze_paths
from .rules import ALL_RULES


def main(argv=None) -> int:
    return run_cli(
        argv,
        prog="python -m tools.racelint",
        description="host-concurrency race/lock-discipline analyzer "
                    "(rules RL001-RL006; see tools/racelint/README.md)",
        label="racelint",
        all_rules=ALL_RULES,
        analyze=analyze_paths,
    )


if __name__ == "__main__":
    sys.exit(main())
