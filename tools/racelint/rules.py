"""The racelint rule catalogue (RL001-RL006).

Each rule is tuned to this codebase's host-concurrency hazards (see
README.md for rationale + fix patterns). Rules are deliberately
narrow: a finding should either be fixed or carry a justified
suppression/baseline entry — noisy rules rot baselines.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, Iterable, List, Set, Tuple

from ..lintcore import Finding
from .analyzer import (ClassIndex, ConcurrencyModule, Event,
                       MethodIndex)

ALL_RULES = ("RL001", "RL002", "RL003", "RL004", "RL005", "RL006")

# Engine entry points that serialize on the step lock (or touch the
# device): calling one synchronously from an event-loop coroutine
# stalls every session on that server for up to a whole tick.
ENGINE_BLOCKING = {
    "step", "abort", "add_request", "preempt", "export_session",
    "import_session", "session_ids", "register_lora", "register_loras",
    "stats", "lane_counts", "import_prefix", "export_prefix",
    "profile_next_ticks", "dump_blackbox",
}

# Synchronous HTTP / process / misc blocking callees (RL002).
BLOCKING_CALLS = {
    "time.sleep",
    "requests.get", "requests.post", "requests.put", "requests.delete",
    "requests.request",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
}

_CALLBACK_RE = re.compile(r"^(on_\w+|\w*_hook|\w*_callback|\w*_cb)$")
# For dotted calls (`self.recorder.alert_hook(...)`) only the
# explicitly-callback-named tails count: `obj.on_x(...)` is usually a
# statically-known listener method (the telemetry surface), not a
# configurable callable.
_CALLBACK_ATTR_RE = re.compile(r"^(\w*_hook|\w*_callback|\w*_cb)$")

_WRITE_KINDS_MUT = ("mutcall", "setitem", "augassign", "del", "assign")


def _f(rule: str, mod: ConcurrencyModule, m: MethodIndex, line: int,
       detail: str, message: str) -> Finding:
    return Finding(rule=rule, path=mod.relpath, line=line,
                   func=m.qualname, detail=detail, message=message)


def _lock_names(locks: Iterable[str]) -> str:
    return ", ".join(sorted(locks))


# ---------------------------------------------------------------- RL001
def check_rl001(mod: ConcurrencyModule,
                cls: ClassIndex) -> Iterable[Finding]:
    """A mutable field written both under a lock and outside it: the
    unlocked writer races the locked one (the classic lost-update —
    `add_request` appending to `waiting` while `step` rebinds it)."""
    writes: Dict[str, List[Tuple[MethodIndex, Event]]] = {}
    for m in cls.all_methods():
        if m.is_init:
            continue
        for ev in m.events:
            if ev.kind == "write" and ev.name not in cls.lock_fields:
                writes.setdefault(ev.name, []).append((m, ev))
    for field, sites in writes.items():
        locked = [(m, ev) for m, ev in sites if m.lockset(ev)]
        unlocked = [(m, ev) for m, ev in sites if not m.lockset(ev)]
        if not locked or not unlocked:
            continue
        owners = set()
        for m, ev in locked:
            owners |= m.lockset(ev)
        for m, ev in unlocked:
            yield _f("RL001", mod, m, ev.line, f"field:{field}",
                     f"`self.{field}` is written here without a lock "
                     f"but elsewhere under {_lock_names(owners)} — "
                     f"unlocked writers race the locked ones")


# ---------------------------------------------------------------- RL002
def _blocking_reason(ev: Event) -> str:
    """'' if the call is loop-safe; else why it blocks."""
    if isinstance(ev.extra, dict) and ev.extra.get("async_recv"):
        return ""        # method on an asyncio object: awaitable
    name, tail = ev.name, ev.name.split(".")[-1]
    if name in BLOCKING_CALLS:
        return f"`{name}` blocks the event loop"
    if tail == "urlopen":
        return f"`{name}` does synchronous I/O on the event loop"
    if tail == "acquire" and "lock" in name.lower():
        return f"`{name}` can block the event loop behind the holder"
    recv = name.split(".")[:-1]
    if tail in ENGINE_BLOCKING and any(
            "engine" in seg.lower() or seg == "eng" for seg in recv):
        return (f"`{name}` serializes on the engine step lock (up to "
                f"a full tick) — run it via run_in_executor")
    if tail == "get" and isinstance(ev.extra, dict) \
            and ev.extra.get("nargs") == 0 and recv:
        seg = recv[-1].lower()
        if "queue" in seg or seg.endswith("_q"):
            return f"unbounded `{name}()` blocks until an item arrives"
    return ""


def _method_blocks(m: MethodIndex) -> Tuple[str, int]:
    """First blocking event in a sync method body (for the one-hop
    async -> sync helper propagation). -> (reason, line) or ('', 0).

    A helper that itself calls `run_in_executor`/`to_thread` is
    loop-AWARE: its blocking branches are off-loop fallbacks by
    construction (the server's `_abort_off_loop` teardown path), so
    it is exempt."""
    for ev in m.events:
        if ev.kind == "call" and ev.name.split(".")[-1] in (
                "run_in_executor", "to_thread"):
            return "", 0
    for ev in m.events:
        if ev.kind == "call":
            reason = _blocking_reason(ev)
            if reason:
                return reason, ev.line
        if ev.kind == "acquire":
            return f"acquires `{ev.name}`", ev.line
    return "", 0


def check_rl002(mod: ConcurrencyModule,
                cls: ClassIndex) -> Iterable[Finding]:
    """Blocking call directly in an `async def` body: stalls every
    coroutine sharing the event loop (heartbeats, aborts, scrapes)."""
    for m in cls.all_methods() if cls else mod.functions:
        if not m.is_async:
            continue
        for ev in m.events:
            if not ev.async_direct:
                continue
            if isinstance(ev.extra, dict) and ev.extra.get("awaited"):
                continue
            if ev.kind == "call":
                reason = _blocking_reason(ev)
                if reason:
                    yield _f("RL002", mod, m, ev.line,
                             f"call:{ev.name}",
                             f"{reason} (inside `async def {m.name}`)")
            elif ev.kind == "acquire":
                yield _f("RL002", mod, m, ev.line,
                         f"with:{ev.name}",
                         f"`with {ev.name}` blocks the event loop "
                         f"behind whichever thread holds it (inside "
                         f"`async def {m.name}`)")
            elif ev.kind == "self_call" and cls is not None \
                    and not (isinstance(ev.extra, dict)
                             and ev.extra.get("awaited")):
                callee = cls.methods.get(ev.name)
                if callee is None or callee.is_async:
                    continue
                reason, _line = _method_blocks(callee)
                if reason:
                    yield _f("RL002", mod, m, ev.line,
                             f"call:self.{ev.name}",
                             f"`self.{ev.name}()` {reason} — called "
                             f"directly from `async def {m.name}`")


# ---------------------------------------------------------------- RL003
def _acquisition_edges(mod: ConcurrencyModule
                       ) -> Dict[Tuple[str, str],
                                 Tuple[MethodIndex, int]]:
    edges: Dict[Tuple[str, str], Tuple[MethodIndex, int]] = {}
    for m in mod.all_methods():
        for ev in m.events:
            if ev.kind != "acquire":
                continue
            held = m.lockset(ev)
            for h in held:
                if h != ev.name:
                    edges.setdefault((h, ev.name), (m, ev.line))
    return edges


def check_rl003(mod: ConcurrencyModule) -> Iterable[Finding]:
    """Lock-order cycle in the nested-`with` acquisition graph: two
    threads taking the same pair of locks in opposite orders can
    deadlock even if each path individually looks fine."""
    edges = _acquisition_edges(mod)
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    seen_cycles: Set[Tuple[str, ...]] = set()
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == path[0] and len(path) > 1:
                    i = path.index(min(path))
                    canon = tuple(path[i:] + path[:i])
                    if canon in seen_cycles:
                        continue
                    seen_cycles.add(canon)
                    m, line = edges[(path[-1], path[0])]
                    order = "->".join(canon + (canon[0],))
                    yield _f("RL003", mod, m, line,
                             f"cycle:{order}",
                             f"lock-order cycle {order}: another "
                             f"thread acquiring in the opposite order "
                             f"deadlocks")
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))


# ---------------------------------------------------------------- RL004
def check_rl004(mod: ConcurrencyModule,
                cls: ClassIndex) -> Iterable[Finding]:
    """A shared container mutated under a lock but iterated without
    it elsewhere: the iterator sees a torn view or raises `RuntimeError:
    ... changed size during iteration` mid-scrape."""
    for field in sorted(cls.container_fields):
        if field in cls.lock_fields:
            continue
        mut_locks: Set[str] = set()
        for m in cls.all_methods():
            if m.is_init:
                continue
            for ev in m.events:
                if ev.kind == "write" and ev.name == field \
                        and ev.extra in _WRITE_KINDS_MUT:
                    mut_locks |= m.lockset(ev)
        if not mut_locks:
            continue
        for m in cls.all_methods():
            if m.is_init:
                continue
            for ev in m.events:
                if ev.kind == "iter" and ev.name == field \
                        and not (m.lockset(ev) & mut_locks):
                    yield _f("RL004", mod, m, ev.line,
                             f"field:{field}",
                             f"`self.{field}` is iterated here "
                             f"without {_lock_names(mut_locks)}, "
                             f"which guards its mutations — snapshot "
                             f"under the lock first")


# ---------------------------------------------------------------- RL005
def _thread_tracked(mod: ConcurrencyModule, cls: ClassIndex,
                    m: MethodIndex, bound: str) -> bool:
    if not bound:
        return False
    if bound.startswith("self."):
        field = bound[5:]
        return (cls is not None
                and (field in cls.joined_fields
                     or field in cls.daemon_fields))
    key = f"{m.qualname}:{bound}"
    return key in mod.local_joins or key in mod.local_daemons


def check_rl005(mod: ConcurrencyModule,
                cls: ClassIndex) -> Iterable[Finding]:
    """`threading.Thread` started without tracked ownership: neither
    daemon=True, nor a handle that is ever `.join()`ed — on shutdown
    it leaks, pins the process, or races teardown."""
    for m in cls.all_methods() if cls else mod.functions:
        for ev in m.events:
            if ev.kind != "thread" or ev.extra is True:
                continue
            if _thread_tracked(mod, cls, m, ev.name):
                continue
            label = ev.name or "<anonymous>"
            yield _f("RL005", mod, m, ev.line, f"thread:{label}",
                     f"Thread `{label}` has no tracked ownership: "
                     f"pass daemon=True or keep the handle and "
                     f"join() it on shutdown")


# ---------------------------------------------------------------- RL006
def check_rl006(mod: ConcurrencyModule,
                cls: ClassIndex) -> Iterable[Finding]:
    """Re-entrancy deadlock hazards under a held lock: re-acquiring a
    non-reentrant lock, calling a sibling method that takes it, or
    invoking a configurable callback/hook while holding it (the
    callee can call back into a lock-taking entry point — the PR 13
    `_arm_profile_locked` bug)."""
    for m in cls.all_methods():
        for ev in m.events:
            held = m.lockset(ev)
            if not held:
                continue
            if ev.kind == "acquire":
                if ev.name in held \
                        and cls.lock_kind(ev.name) != "rlock":
                    yield _f("RL006", mod, m, ev.line,
                             f"reacquire:{ev.name}",
                             f"re-acquiring non-reentrant `{ev.name}` "
                             f"while already holding it deadlocks")
                continue
            if ev.kind == "self_call":
                callee = cls.methods.get(ev.name)
                if callee is None:
                    if _CALLBACK_RE.match(ev.name):
                        yield _f("RL006", mod, m, ev.line,
                                 f"callback:{ev.name}",
                                 f"callback `self.{ev.name}` invoked "
                                 f"holding {_lock_names(held)}: the "
                                 f"callee can re-enter a lock-taking "
                                 f"entry point and deadlock")
                    continue
                for cev in callee.events:
                    if cev.kind == "acquire" and cev.name in held \
                            and cls.lock_kind(cev.name) != "rlock":
                        yield _f("RL006", mod, m, ev.line,
                                 f"deadlock:{ev.name}:{cev.name}",
                                 f"`self.{ev.name}()` acquires "
                                 f"`{cev.name}` (line {cev.line}) "
                                 f"which is already held here — "
                                 f"non-reentrant deadlock")
                        break
            elif ev.kind == "call":
                tail = ev.name.split(".")[-1]
                if _CALLBACK_ATTR_RE.match(tail) and "." in ev.name:
                    yield _f("RL006", mod, m, ev.line,
                             f"callback:{tail}",
                             f"callback `{ev.name}` invoked holding "
                             f"{_lock_names(held)}: the callee can "
                             f"re-enter a lock-taking entry point "
                             f"and deadlock")


def check_module(mod: ConcurrencyModule) -> List[Finding]:
    out: List[Finding] = []
    for cls in mod.classes.values():
        out.extend(check_rl001(mod, cls))
        out.extend(check_rl002(mod, cls))
        out.extend(check_rl004(mod, cls))
        out.extend(check_rl005(mod, cls))
        out.extend(check_rl006(mod, cls))
    # module-level functions: async-blocking + thread-ownership only
    for m in mod.functions:
        if m.is_async:
            for ev in m.events:
                if not ev.async_direct or (
                        isinstance(ev.extra, dict)
                        and ev.extra.get("awaited")):
                    continue
                if ev.kind == "call":
                    reason = _blocking_reason(ev)
                    if reason:
                        out.append(_f("RL002", mod, m, ev.line,
                                      f"call:{ev.name}",
                                      f"{reason} (inside `async def "
                                      f"{m.name}`)"))
                elif ev.kind == "acquire":
                    out.append(_f("RL002", mod, m, ev.line,
                                  f"with:{ev.name}",
                                  f"`with {ev.name}` blocks the event "
                                  f"loop (inside `async def {m.name}`)"))
        for ev in m.events:
            if ev.kind == "thread" and ev.extra is not True \
                    and not _thread_tracked(mod, None, m, ev.name):
                label = ev.name or "<anonymous>"
                out.append(_f("RL005", mod, m, ev.line,
                              f"thread:{label}",
                              f"Thread `{label}` has no tracked "
                              f"ownership: pass daemon=True or keep "
                              f"the handle and join() it on shutdown"))
    out.extend(check_rl003(mod))
    return out
