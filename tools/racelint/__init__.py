"""racelint: host-concurrency race/lock-discipline analyzer.

The reference Ray enforces concurrency discipline in a C++ core; this
rebuild's host plane is Python — the engine pump thread, the asyncio
ingress loop, fleet refresh/watchdog loops, and the scrape path all
share mutable state guarded (by convention) by `_step_lock`. racelint
checks that convention mechanically: lock-set inference from `with
self._lock:` scopes (cross-method, via intra-class call-site
propagation), plus rules for blocking calls on the event loop,
lock-order cycles, unlocked iteration of locked containers, untracked
threads, and callbacks invoked under a lock (RL001-RL006; see
README.md).

Paired with the **runtime** half, `ray_tpu/util/thread_sanitizer.py`
(instrumented locks + guarded-field descriptors, armed in tier-1
stress tests). Shares baseline/suppression/CLI machinery with
jaxlint via tools/lintcore. Stdlib `ast` only; no new dependencies.
"""

from ..lintcore import (  # noqa: F401
    Baseline,
    Finding,
    iter_py_files,
    load_baseline,
    write_baseline,
)
from .analyzer import ConcurrencyModule, ConcurrencyProject, analyze_paths  # noqa: F401

__all__ = [
    "Finding", "ConcurrencyModule", "ConcurrencyProject",
    "analyze_paths", "iter_py_files",
    "Baseline", "load_baseline", "write_baseline",
]
