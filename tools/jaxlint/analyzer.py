"""Core indexing + driver for jaxlint.

The analyzer works in three passes:

1. Index every module: function defs (with qualnames), per-node
   enclosing-function / loop-depth context, import bindings, simple
   local assignments, and suppression comments.
2. Mark TRACED functions — functions whose bodies run under a jax
   trace: decorated with / passed to `jax.jit`, `shard_map`,
   `pallas_call`, `lax.scan` etc., plus everything transitively
   reachable from a traced body by simple-name call resolution
   (nested scope -> same class -> module -> imports across the
   analyzed file set — the engine's jitted `run` closures reach
   `llama_infer.prefill` and the ops kernels this way).
3. Run the rule checks (rules.py) over every module.

Findings carry a line number for humans but their BASELINE KEY is
line-independent (rule : path : function-qualname : detail) so code
motion above a finding never churns the baseline.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..lintcore import (  # noqa: F401  (re-exported public surface)
    Finding,
    iter_py_files,
    normalize_relpath,
    parse_suppressions,
)

# Call targets that put their function argument under a jax trace.
TRACE_ENTRY_NAMES = {
    "jit", "pjit", "pmap", "vmap", "grad", "value_and_grad",
    "checkpoint", "remat", "scan", "while_loop", "cond", "fori_loop",
    "switch", "shard_map", "_shard_map", "pallas_call", "custom_vjp",
    "custom_jvp",
}
# Decorators that mark a def as traced.
TRACE_DECORATOR_NAMES = {"jit", "pjit", "pmap", "shard_map"}

class FunctionInfo:
    """One function/lambda: identity, trace status, and the call names
    its body mentions (for traced-reachability propagation)."""

    def __init__(self, node, qualname: str, module: "ModuleInfo",
                 parent: Optional["FunctionInfo"], class_name: str):
        self.node = node
        self.qualname = qualname
        self.module = module
        self.parent = parent
        self.class_name = class_name
        self.traced = False
        self.calls_bare: Set[str] = set()  # foo(...) calls
        self.calls_self: Set[str] = set()  # self.foo(...) calls
        self.local_names: Set[str] = set() # params + assigned names
        self.children: List[FunctionInfo] = []
        # defs nested directly in this function, by bare name
        self.nested: Dict[str, FunctionInfo] = {}
        # simple local assignments: name -> value AST (last wins)
        self.assigns: Dict[str, ast.AST] = {}
        # names returned by this function that are nested defs (the
        # `def _build_x(): def run(...); return run` factory pattern)
        self.returned_defs: List[FunctionInfo] = []
        # returns a jax.jit(...) binding (the memoized jit-factory
        # pattern `fn = jax.jit(run); ...; return fn`): callers of
        # this function hold a compiled dispatchable, so reading its
        # call results with np.asarray is a JL005 sync point
        self.returns_jit = False


class ModuleInfo:
    def __init__(self, path: str, relpath: str, tree: ast.Module,
                 source: str):
        self.path = path
        self.relpath = relpath
        self.tree = tree
        self.source = source
        self.functions: List[FunctionInfo] = []
        # bare name -> FunctionInfos (module-level AND nested; resolver
        # prefers closer scopes)
        self.by_name: Dict[str, List[FunctionInfo]] = {}
        # class name -> {method name -> FunctionInfo}
        self.methods: Dict[str, Dict[str, FunctionInfo]] = {}
        # imported name -> (dotted module, original name | None)
        self.imports: Dict[str, Tuple[str, Optional[str]]] = {}
        # MODULE-scope simple assigns (function-local ones live on
        # their FunctionInfo — a module-wide last-wins map made
        # unrelated same-named locals collide); attribute targets
        # ("self._fn") are kept here under their dotted name
        self.assigns: Dict[str, ast.AST] = {}
        # line -> set of suppressed rule ids
        self.suppressions: Dict[int, Set[str]] = {}
        # per-node context filled by the indexer:
        # id(node) -> (FunctionInfo | None, loop_depth)
        self.node_ctx: Dict[int, Tuple[Optional[FunctionInfo], int]] = {}
        self.dotted: Optional[str] = None   # e.g. "ray_tpu.models.llama"

    def suppressed(self, rule: str, line: int,
                   func: Optional[FunctionInfo]) -> bool:
        """A disable comment suppresses on its own line or, placed on
        any line of the enclosing `def` signature, for the whole
        function (justification rides in the same comment:
        `# jaxlint: disable=JL006 -- reason`)."""
        if rule in self.suppressions.get(line, ()):
            return True
        f = func
        while f is not None:
            node = f.node
            body = getattr(node, "body", None)
            end = (body[0].lineno if isinstance(body, list) and body
                   else node.lineno + 1)
            if any(rule in self.suppressions.get(ln, ())
                   for ln in range(node.lineno, end)):
                return True
            f = f.parent
        return False


def _parse_suppressions(source: str) -> Dict[int, Set[str]]:
    return parse_suppressions(source, "jaxlint")


def lookup_assign(mod: "ModuleInfo", ctx: Optional["FunctionInfo"],
                  name: str) -> Optional[ast.AST]:
    """Scope-aware assignment lookup: the enclosing function chain
    first (a local `fn = ...` in an unrelated function must not be
    visible here), then module scope. Dotted names ("self._fn") live
    at module scope."""
    if "." not in name:
        f = ctx
        while f is not None:
            if name in f.assigns:
                return f.assigns[name]
            if name in f.local_names:
                return None        # local, but not a simple binding
            f = f.parent
    return mod.assigns.get(name)


def is_jit_call(node: Optional[ast.AST]) -> bool:
    """True for a `jax.jit(...)` / `pjit(...)` call expression — the
    binding form whose result is a compiled dispatchable (shared by
    JL003's static-argnum lookup and JL005's dispatch-result
    tracing)."""
    if not isinstance(node, ast.Call):
        return False
    name = call_name(node)
    return (name.split(".")[-1] in ("jit", "pjit")
            and name.split(".")[0] in ("jax", "jit", "pjit"))


def dotted_name(node: ast.AST) -> str:
    """'jax.lax.scan' for nested Attribute/Name chains ('' if other)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(call: ast.Call) -> str:
    return dotted_name(call.func)


class _Indexer(ast.NodeVisitor):
    """Single walk building ModuleInfo: function tree, per-node
    (function, loop-depth) context, calls, imports, assignments."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.func_stack: List[FunctionInfo] = []
        self.class_stack: List[str] = []
        self.loop_depth = 0

    # -- helpers --
    def _cur(self) -> Optional[FunctionInfo]:
        return self.func_stack[-1] if self.func_stack else None

    def _qual(self, name: str) -> str:
        parts = [f.qualname for f in self.func_stack[-1:]]
        if parts:
            return f"{parts[0]}.{name}"
        if self.class_stack:
            return f"{'.'.join(self.class_stack)}.{name}"
        return name

    def _enter_function(self, node, name: str):
        parent = self._cur()
        info = FunctionInfo(node, self._qual(name), self.mod, parent,
                            self.class_stack[-1] if self.class_stack
                            else "")
        self.mod.functions.append(info)
        self.mod.by_name.setdefault(name, []).append(info)
        if parent is not None:
            parent.children.append(info)
            parent.nested[name] = info
            parent.local_names.add(name)
        if self.class_stack and parent is None:
            self.mod.methods.setdefault(
                self.class_stack[-1], {})[name] = info
        if not isinstance(node, ast.Lambda):
            for arg in ([*node.args.posonlyargs, *node.args.args,
                         *node.args.kwonlyargs]
                        + ([node.args.vararg] if node.args.vararg else [])
                        + ([node.args.kwarg] if node.args.kwarg else [])):
                info.local_names.add(arg.arg)
        else:
            for arg in [*node.args.posonlyargs, *node.args.args,
                        *node.args.kwonlyargs]:
                info.local_names.add(arg.arg)
        return info

    # -- visitors --
    def visit_ClassDef(self, node: ast.ClassDef):
        self.mod.node_ctx[id(node)] = (self._cur(), self.loop_depth)
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_func(self, node, name: str):
        self.mod.node_ctx[id(node)] = (self._cur(), self.loop_depth)
        info = self._enter_function(node, name)
        self.func_stack.append(info)
        saved_depth, self.loop_depth = self.loop_depth, 0
        self.generic_visit(node)
        self.loop_depth = saved_depth
        self.func_stack.pop()

    def visit_FunctionDef(self, node):
        self._visit_func(node, node.name)

    def visit_AsyncFunctionDef(self, node):
        self._visit_func(node, node.name)

    def visit_Lambda(self, node):
        self._visit_func(node, "<lambda>")

    def _visit_loop(self, node):
        self.mod.node_ctx[id(node)] = (self._cur(), self.loop_depth)
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_While = _visit_loop

    def _visit_for(self, node):
        # the iterable expression is evaluated ONCE, at the enclosing
        # depth; only target+body run per iteration
        self.mod.node_ctx[id(node)] = (self._cur(), self.loop_depth)
        self.visit(node.iter)
        self.loop_depth += 1
        self.visit(node.target)
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        self.loop_depth -= 1

    visit_For = visit_AsyncFor = _visit_for

    def _visit_comp(self, node):
        # comprehensions iterate: element/condition exprs run per
        # item, but the FIRST iterable is evaluated once
        self.mod.node_ctx[id(node)] = (self._cur(), self.loop_depth)
        self.visit(node.generators[0].iter)
        self.loop_depth += 1
        for i, gen in enumerate(node.generators):
            self.visit(gen.target)
            if i > 0:
                self.visit(gen.iter)
            for cond in gen.ifs:
                self.visit(cond)
        if isinstance(node, ast.DictComp):
            self.visit(node.key)
            self.visit(node.value)
        else:
            self.visit(node.elt)
        self.loop_depth -= 1

    visit_ListComp = visit_SetComp = visit_DictComp = _visit_comp
    visit_GeneratorExp = _visit_comp

    def visit_Import(self, node: ast.Import):
        self.mod.node_ctx[id(node)] = (self._cur(), self.loop_depth)
        for alias in node.names:
            self.mod.imports[alias.asname or alias.name.split(".")[0]] \
                = (alias.name, None)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        self.mod.node_ctx[id(node)] = (self._cur(), self.loop_depth)
        modname = ("." * node.level) + (node.module or "")
        for alias in node.names:
            self.mod.imports[alias.asname or alias.name] \
                = (modname, alias.name)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        self.mod.node_ctx[id(node)] = (self._cur(), self.loop_depth)
        cur = self._cur()
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                if cur is not None:
                    cur.local_names.add(tgt.id)
                    cur.assigns[tgt.id] = node.value
                else:
                    self.mod.assigns[tgt.id] = node.value
            elif isinstance(tgt, ast.Tuple):
                # tuple-unpack targets record the WHOLE RHS as each
                # name's value: `toks, pool = fn(...)` makes `toks`
                # resolvable to the dispatch call (JL005's
                # np.asarray-on-dispatch-result tracing)
                for el in tgt.elts:
                    if isinstance(el, ast.Name):
                        if cur is not None:
                            cur.local_names.add(el.id)
                            cur.assigns[el.id] = node.value
                        else:
                            self.mod.assigns[el.id] = node.value
            elif isinstance(tgt, ast.Attribute):
                # self._decode_fn = jax.jit(...) style bindings
                self.mod.assigns[dotted_name(tgt)] = node.value
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self.mod.node_ctx[id(node)] = (self._cur(), self.loop_depth)
        cur = self._cur()
        if isinstance(node.target, ast.Name) and cur is not None:
            cur.local_names.add(node.target.id)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        self.mod.node_ctx[id(node)] = (self._cur(), self.loop_depth)
        cur = self._cur()
        if cur is not None:
            if isinstance(node.func, ast.Name):
                cur.calls_bare.add(node.func.id)
            elif isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in ("self", "cls"):
                cur.calls_self.add(node.func.attr)
            # other attribute calls (obj.method) are NOT resolved — a
            # bare tail match against unrelated defs is how false
            # traced-propagation happens
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return):
        self.mod.node_ctx[id(node)] = (self._cur(), self.loop_depth)
        cur = self._cur()
        if (cur is not None and isinstance(node.value, ast.Name)
                and node.value.id in cur.nested):
            cur.returned_defs.append(cur.nested[node.value.id])
        if cur is not None and node.value is not None:
            # jit-factory detection: `return jax.jit(...)` directly,
            # or `return fn` where fn's latest prior binding is one
            if is_jit_call(node.value) or (
                    isinstance(node.value, ast.Name)
                    and is_jit_call(cur.assigns.get(node.value.id))):
                cur.returns_jit = True
        self.generic_visit(node)

    def generic_visit(self, node):
        self.mod.node_ctx.setdefault(
            id(node), (self._cur(), self.loop_depth))
        super().generic_visit(node)


class Project:
    """All analyzed modules + cross-module traced propagation."""

    def __init__(self, root: str = "."):
        self.root = os.path.abspath(root)
        self.modules: List[ModuleInfo] = []
        self.by_dotted: Dict[str, ModuleInfo] = {}

    # -- loading --
    def add_file(self, path: str) -> Optional[ModuleInfo]:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            return None
        rel = normalize_relpath(path, self.root)
        mod = ModuleInfo(path, rel, tree, source)
        mod.suppressions = _parse_suppressions(source)
        mod.dotted = self._dotted_for(rel)
        _Indexer(mod).visit(tree)
        self.modules.append(mod)
        if mod.dotted:
            self.by_dotted[mod.dotted] = mod
        return mod

    @staticmethod
    def _dotted_for(relpath: str) -> Optional[str]:
        if not relpath.endswith(".py") or ":" in relpath:
            return None
        parts = relpath[:-3].replace("\\", "/").split("/")
        if parts[-1] == "__init__":
            parts = parts[:-1]
        if not parts or any(not p.isidentifier() for p in parts):
            return None
        return ".".join(parts)

    # -- traced-function seeding + propagation --
    def mark_traced(self) -> None:
        for mod in self.modules:
            self._seed_module(mod)
        # fixpoint: propagate through calls
        changed = True
        while changed:
            changed = False
            for mod in self.modules:
                for fn in mod.functions:
                    if not fn.traced:
                        continue
                    # anything DEFINED inside a traced body executes
                    # under the trace when invoked (helpers passed as
                    # callbacks, nested lambdas, scan bodies)
                    for child in fn.children:
                        if not child.traced:
                            child.traced = True
                            changed = True
                    for name in fn.calls_bare:
                        for target in self._resolve(mod, fn, name):
                            if not target.traced:
                                target.traced = True
                                changed = True
                    for name in fn.calls_self:
                        for target in self._resolve(mod, fn, name,
                                                    is_self=True):
                            if not target.traced:
                                target.traced = True
                                changed = True

    def _seed_module(self, mod: ModuleInfo) -> None:
        for fn in mod.functions:
            node = fn.node
            if isinstance(node, ast.Lambda):
                continue
            for dec in node.decorator_list:
                if self._is_trace_entry(dec):
                    fn.traced = True
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if not self._is_trace_entry(node.func):
                continue
            ctx_fn, _ = mod.node_ctx.get(id(node), (None, 0))
            for arg in node.args:
                self._seed_arg(mod, ctx_fn, arg)

    def _seed_arg(self, mod: ModuleInfo, ctx_fn: Optional[FunctionInfo],
                  arg: ast.AST) -> None:
        if isinstance(arg, ast.Lambda):
            info = self._function_for_node(mod, arg)
            if info is not None:
                info.traced = True
            return
        if isinstance(arg, ast.Call):
            name = call_name(arg)
            tail = name.split(".")[-1]
            if tail == "partial" and arg.args:
                # jit(functools.partial(f, ...)) -> seed f
                self._seed_arg(mod, ctx_fn, arg.args[0])
                return
            # jax.jit(self._build_decode()) -> seed the defs the
            # factory returns
            for target in self._resolve(
                    mod, ctx_fn, tail,
                    is_self=name.startswith(("self.", "cls."))):
                for ret in target.returned_defs:
                    ret.traced = True
            return
        if isinstance(arg, (ast.Name, ast.Attribute)):
            name = dotted_name(arg)
            tail = name.split(".")[-1]
            if not tail:
                return
            targets = list(self._resolve(
                mod, ctx_fn, tail,
                is_self=name.startswith(("self.", "cls."))))
            for t in targets:
                t.traced = True
            if not targets and isinstance(arg, ast.Name):
                # name bound to functools.partial(f, ...)?
                val = lookup_assign(mod, ctx_fn, arg.id)
                if isinstance(val, ast.Call) \
                        and call_name(val).split(".")[-1] == "partial" \
                        and val.args:
                    self._seed_arg(mod, ctx_fn, val.args[0])

    @staticmethod
    def _is_trace_entry(node: ast.AST) -> bool:
        name = dotted_name(node)
        if not name:
            # @functools.partial(jax.jit, ...) decorator form
            if isinstance(node, ast.Call):
                tail = call_name(node).split(".")[-1]
                if tail == "partial" and node.args:
                    return Project._is_trace_entry(node.args[0])
            return False
        return name.split(".")[-1] in TRACE_ENTRY_NAMES

    def _function_for_node(self, mod: ModuleInfo,
                           node: ast.AST) -> Optional[FunctionInfo]:
        for fn in mod.functions:
            if fn.node is node:
                return fn
        return None

    def _resolve(self, mod: ModuleInfo, ctx: Optional[FunctionInfo],
                 name: str, is_self: bool = False
                 ) -> Iterable[FunctionInfo]:
        """Resolve a called name to function defs. Bare names walk the
        nested scope chain, then module level, then one import hop into
        another analyzed module (Python has no implicit self, so bare
        names never hit methods). `self.X` calls resolve ONLY against
        the enclosing class's methods."""
        if is_self:
            cls = ""
            f = ctx
            while f is not None and not cls:
                cls = f.class_name
                f = f.parent
            if cls:
                meth = mod.methods.get(cls, {})
                if name in meth:
                    return [meth[name]]
            return []
        f = ctx
        while f is not None:
            if name in f.nested:
                return [f.nested[name]]
            f = f.parent
        hits = [fn for fn in mod.by_name.get(name, ())
                if fn.parent is None and not fn.class_name]
        if hits:
            return hits
        imp = mod.imports.get(name)
        if imp is not None:
            target_mod = self._resolve_import(mod, imp[0])
            if target_mod is not None and imp[1]:
                return [fn for fn in target_mod.by_name.get(imp[1], ())
                        if fn.parent is None and not fn.class_name]
        return []

    def _resolve_import(self, mod: ModuleInfo,
                        modname: str) -> Optional[ModuleInfo]:
        if not modname.startswith("."):
            return self.by_dotted.get(modname)
        if mod.dotted is None:
            return None
        level = len(modname) - len(modname.lstrip("."))
        suffix = modname.lstrip(".")
        base = mod.dotted.split(".")
        # a module's relative import is resolved against its package
        base = base[: len(base) - level] if len(base) >= level else []
        parts = base + ([suffix] if suffix else [])
        return self.by_dotted.get(".".join(p for p in parts if p))


def analyze_paths(paths: Iterable[str], root: str = ".",
                  select: Optional[Set[str]] = None) -> List[Finding]:
    """Analyze files/dirs, returning suppression-filtered findings."""
    from . import rules
    project = Project(root)
    for path in iter_py_files(paths):
        project.add_file(path)
    project.mark_traced()
    kept: List[Finding] = []
    for mod in project.modules:
        for f in rules.check_module(project, mod):
            if select and f.rule not in select:
                continue
            if not mod.suppressed(f.rule, f.line, _find_func(mod, f.func)):
                kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return kept


def _find_func(mod: ModuleInfo, qualname: str):
    for fn in mod.functions:
        if fn.qualname == qualname:
            return fn
    return None
