"""jaxlint: dispatch-discipline static analyzer for the hot path.

PR 1/2 made the engine's steady state "one jitted dispatch per tick,
zero recompiles, two coalesced uploads"; jaxlint enforces that
invariant mechanically. Stdlib-ast only (no new deps). See README.md
for the rule catalogue and ray_tpu/util/jax_guard.py for the paired
runtime guard.
"""

from .analyzer import (  # noqa: F401
    Finding,
    Project,
    analyze_paths,
    iter_py_files,
)
from .baseline import (  # noqa: F401
    Baseline,
    load_baseline,
    write_baseline,
)

__all__ = [
    "Finding", "Project", "analyze_paths", "iter_py_files",
    "Baseline", "load_baseline", "write_baseline",
]
