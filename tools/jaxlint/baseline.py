"""Baseline handling — now shared machinery in tools/lintcore.

Kept as a re-export so `tools.jaxlint.baseline` stays a stable import
path; see tools/lintcore/baseline.py for the semantics (justified
entries, occurrence counts, scoped --fix-baseline retention).
"""

from __future__ import annotations

from ..lintcore.baseline import (  # noqa: F401
    Baseline,
    load_baseline,
    write_baseline,
)

__all__ = ["Baseline", "load_baseline", "write_baseline"]
