"""CLI: python -m tools.jaxlint PATH... [--baseline FILE]

Exit codes: 0 = clean (or every finding baselined), 1 = new findings,
2 = usage error. `--fix-baseline` rewrites the baseline from the
current findings (carrying forward justifications; additions get a
TODO placeholder the tier-1 lint test refuses to ship — write the
justification before committing). The scaffold lives in
tools/lintcore/cli.py, shared with tools/racelint.
"""

from __future__ import annotations

import sys

from ..lintcore import run_cli
from .analyzer import analyze_paths
from .rules import ALL_RULES


def main(argv=None) -> int:
    return run_cli(
        argv,
        prog="python -m tools.jaxlint",
        description="dispatch-discipline static analyzer "
                    "(rules JL001-JL008; see tools/jaxlint/README.md)",
        label="jaxlint",
        all_rules=ALL_RULES,
        analyze=analyze_paths,
    )


if __name__ == "__main__":
    sys.exit(main())
