"""The jaxlint rule catalogue (JL001-JL008).

Each rule is tuned to this codebase's dispatch-discipline hazards (see
README.md for rationale + fix patterns). Rules are deliberately
narrow: a finding should either be fixed or carry a baseline
justification — noisy rules rot baselines.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from .analyzer import (Finding, FunctionInfo, ModuleInfo, Project,
                       call_name, dotted_name, is_jit_call,
                       lookup_assign)

# KV-pool parameter names: functions taking these hold the engine's
# page pools, which MUST be donated through jit (JL002) or XLA copies
# the whole cache per token.
KV_POOL_NAMES = {
    "k_pages", "v_pages", "kv_pages", "dk", "dv",
    "k_cache", "v_cache", "cache_k", "cache_v",
}

# device-upload callees (JL006)
UPLOAD_CALLEES = {
    "jnp.asarray", "jnp.array", "jax.numpy.asarray", "jax.numpy.array",
    "jax.device_put", "device_put",
}

# host-sync callees banned under a trace (JL001)
HOST_SYNC_NP = {"asarray", "array", "copy", "save", "savez"}
HOST_SYNC_METHODS = {"item", "tolist", "numpy", "__array__"}

# modules/functions that are sanctioned sync points (JL005): timing
# and benchmarking utilities exist to block; tests may sync freely
SANCTIONED_SYNC = ("profil", "bench", "timing", "test")

JIT_NAMES = {"jit", "pjit"}
ALL_RULES = ("JL001", "JL002", "JL003", "JL004",
             "JL005", "JL006", "JL007", "JL008", "JL009")

# instrumentation receivers (JL009): a call whose dotted receiver
# chain names one of these — `metrics.*`, `tracing.span`,
# `self.telemetry.on_token`, `recorder.record`, `self.attrib.charge`
# (ISSUE 13 attribution/anomaly planes) — is observability code and
# must stay on the HOST side of the dispatch boundary
INSTRUMENT_RECEIVERS = {"metrics", "tracing", "telemetry",
                        "_telemetry", "recorder", "attrib",
                        "anomaly"}
# metric-handle method names specific enough to flag on their own
# (`ttft.observe(...)` on a bound histogram handle)
INSTRUMENT_TAILS = {"observe"}


def check_module(project: Project, mod: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    out.extend(check_decorated_defs(project, mod))
    out.extend(check_traced_mutator_calls(mod))
    for node in ast.walk(mod.tree):
        fn, loop_depth = mod.node_ctx.get(id(node), (None, 0))
        traced = fn is not None and fn.traced
        if isinstance(node, ast.Call):
            out.extend(_check_call(project, mod, node, fn, traced,
                                   loop_depth))
        elif isinstance(node, (ast.Global, ast.Nonlocal)) and traced:
            names = ", ".join(node.names)
            out.append(_f(mod, "JL004", node, fn,
                          f"scope:{names}",
                          f"`{type(node).__name__.lower()} {names}` "
                          f"inside a traced function: mutating "
                          f"enclosing scope under trace leaks tracers "
                          f"or captures stale values"))
        elif isinstance(node, (ast.Assign, ast.AugAssign)) and traced:
            out.extend(_check_traced_assign(mod, node, fn))
    return out


def _f(mod: ModuleInfo, rule: str, node: ast.AST,
       fn: Optional[FunctionInfo], detail: str, message: str) -> Finding:
    return Finding(rule=rule, path=mod.relpath,
                   line=getattr(node, "lineno", 1),
                   func=fn.qualname if fn else "", detail=detail,
                   message=message)


# ---------------------------------------------------------------- calls

def _check_call(project: Project, mod: ModuleInfo, node: ast.Call,
                fn: Optional[FunctionInfo], traced: bool,
                loop_depth: int) -> Iterable[Finding]:
    out: List[Finding] = []
    name = call_name(node)
    tail = name.split(".")[-1] if name else ""

    # JL001: host-sync calls under a trace
    if traced:
        root = name.split(".")[0] if name else ""
        if root in ("np", "numpy") and tail in HOST_SYNC_NP \
                and not name.startswith((f"{root}.random.",)):
            out.append(_f(mod, "JL001", node, fn, name,
                          f"`{name}(...)` inside a traced function "
                          f"forces a device->host sync per call; use "
                          f"jnp or hoist to the host side"))
        elif tail in HOST_SYNC_METHODS and "." in name:
            out.append(_f(mod, "JL001", node, fn, f".{tail}()",
                          f"`.{tail}()` inside a traced function "
                          f"blocks on device values (host sync)"))
        elif name in ("float", "int", "bool") and node.args \
                and not all(isinstance(a, ast.Constant)
                            for a in node.args):
            out.append(_f(mod, "JL001", node, fn, f"{name}()",
                          f"`{name}(...)` on a non-constant inside a "
                          f"traced function concretizes a tracer "
                          f"(ConcretizationTypeError or host sync)"))

        # JL007: wall-clock / host RNG on the traced path
        if name.startswith(("time.", "datetime.")) \
                or name.startswith(("random.", "np.random.",
                                    "numpy.random.")):
            out.append(_f(mod, "JL007", node, fn, name,
                          f"`{name}(...)` inside a traced function is "
                          f"baked in at trace time (stale clocks / "
                          f"fixed randomness); thread jax.random keys "
                          f"or compute host-side"))

        # JL009 (ISSUE 5): instrumentation under a trace. A
        # `metrics.observe`/`tracing.span`/`telemetry.on_*` call
        # inside a traced function runs at TRACE time only — the
        # compiled program replays WITHOUT it, so the metric records
        # once per compile instead of once per call (silently frozen
        # telemetry), and its wall-clock reads/locks are host work
        # that has no meaning inside a compiled program. All
        # instrumentation stays on the host side of the dispatch
        # boundary (the engine records from admission bookkeeping and
        # the fold).
        parts = name.split(".") if name else []
        if len(parts) > 1 and (set(parts[:-1]) & INSTRUMENT_RECEIVERS
                               or parts[-1] in INSTRUMENT_TAILS):
            out.append(_f(
                mod, "JL009", node, fn, name,
                f"`{name}(...)` inside a traced function: "
                f"instrumentation runs at trace time only (frozen "
                f"into the compiled program, never per call) — "
                f"record from host-side events outside the jit "
                f"boundary instead"))

    # JL005: explicit sync points
    if name in ("jax.device_get", "jax.block_until_ready") \
            or (tail == "block_until_ready" and "." in name):
        sync = name if name.startswith("jax.") else f".{tail}()"
        if traced:
            out.append(_f(mod, "JL005", node, fn, sync,
                          f"`{sync}` inside a traced function"))
        elif loop_depth > 0 and not _sanctioned_sync(mod, fn):
            out.append(_f(mod, "JL005", node, fn, sync,
                          f"`{sync}` inside a host loop serializes "
                          f"host and device per iteration; sync once "
                          f"after the loop"))

    # JL005 (async-readback discipline, ISSUE 4): a bare
    # np.asarray(...) on a dispatch result. The engine funnels every
    # device->host readback through ONE sanctioned fold site
    # (engine._read_tokens, inline-suppressed there); a stray
    # readback anywhere else re-serializes host and device exactly
    # where the pipelined tick loop hides the wait.
    if not traced and name in ("np.asarray", "numpy.asarray") \
            and node.args and not _sanctioned_sync(mod, fn) \
            and _is_dispatch_result(project, mod, fn, node.args[0]):
        out.append(_f(
            mod, "JL005", node, fn, f"{name}:dispatch-result",
            f"`{name}(...)` directly on a jitted-dispatch result "
            f"blocks the host on the device; route readbacks "
            f"through the one sanctioned sync point (the engine's "
            f"_read_tokens fold) so the async tick pipeline can "
            f"hide them"))

    # JL006: per-iteration device uploads in host loops
    if not traced and loop_depth > 0 and name in UPLOAD_CALLEES:
        out.append(_f(mod, "JL006", node, fn, name,
                      f"`{name}(...)` inside a host loop uploads per "
                      f"iteration; coalesce into one packed upload or "
                      f"cache device-side (like the engine's "
                      f"_samp_cache)"))

    # JL008 / JL002: jit call sites
    if is_jit_call(node):
        if loop_depth > 0:
            out.append(_f(mod, "JL008", node, fn, "jit-in-loop",
                          "`jax.jit` in a loop body builds a new "
                          "program (and cache entry) per iteration; "
                          "hoist + memoize with an explicit keyed "
                          "cache"))
        out.extend(_check_jit_donation(project, mod, node, fn))

    # JL003: hazardous args at jitted-callable call sites
    out.extend(_check_jit_callsite_args(mod, node, fn, name))
    return out


def _sanctioned_sync(mod: ModuleInfo, fn: Optional[FunctionInfo]) -> bool:
    hay = mod.relpath.lower()
    if fn is not None:
        hay += ":" + fn.qualname.lower()
    return any(s in hay for s in SANCTIONED_SYNC)


def _factory_returns_jit(project: Project, mod: ModuleInfo,
                         ctx: Optional[FunctionInfo],
                         call_node: ast.Call) -> bool:
    """Does this call yield a compiled dispatchable? True for
    `jax.jit(f)` itself and for calls of memoized jit factories
    (`self._ragged_fn(T, ctx)` whose def returns a jit binding)."""
    if is_jit_call(call_node):
        return True
    fname = call_name(call_node)
    tail = fname.split(".")[-1]
    if not tail:
        return False
    return any(getattr(t, "returns_jit", False)
               for t in project._resolve(
                   mod, ctx, tail,
                   is_self=fname.startswith(("self.", "cls."))))


def _has_jit_decorator(fninfo: FunctionInfo) -> bool:
    """Decorated directly with jit/pjit (incl. the
    @functools.partial(jax.jit, ...) form) — calling such a def from
    host code IS a dispatch. Deliberately narrower than .traced,
    which also covers scan bodies and helpers merely REACHABLE from
    traced code (calling those from host returns plain arrays)."""
    node = fninfo.node
    if isinstance(node, ast.Lambda):
        return False
    for dec in node.decorator_list:
        if dotted_name(dec).split(".")[-1] in JIT_NAMES:
            return True
        if isinstance(dec, ast.Call):
            tail = call_name(dec).split(".")[-1]
            if tail in JIT_NAMES:
                return True
            if tail == "partial" and dec.args and \
                    dotted_name(dec.args[0]).split(".")[-1] in JIT_NAMES:
                return True
    return False


def _dispatch_call(project: Project, mod: ModuleInfo,
                   ctx: Optional[FunctionInfo],
                   node: Optional[ast.AST]) -> bool:
    """Is `node` a Call executing a compiled program: a jax.jit
    binding (local / module / `self.x` attr), a @jax.jit-decorated
    def, a name bound to a jit-factory result
    (`fn = self._ragged_fn(...); fn(...)`), or a direct factory
    dispatch (`self._prefill_fn(b)(...)`)."""
    if not isinstance(node, ast.Call):
        return False
    name = call_name(node)
    if name:
        if _jitted_binding_statics(mod, ctx, name) is not None:
            return True
        # @jax.jit-decorated defs: only bare / self.-qualified names
        # resolve (a dotted `other.step` tail-matched against an
        # unrelated local def would false-positive)
        if "." not in name or name.startswith(("self.", "cls.")):
            if any(_has_jit_decorator(t) for t in project._resolve(
                    mod, ctx, name.split(".")[-1],
                    is_self=name.startswith(("self.", "cls.")))):
                return True
        val = lookup_assign(mod, ctx, name)
        return (isinstance(val, ast.Call)
                and _factory_returns_jit(project, mod, ctx, val))
    if isinstance(node.func, ast.Call):
        return _factory_returns_jit(project, mod, ctx, node.func)
    return False


def _is_dispatch_result(project: Project, mod: ModuleInfo,
                        ctx: Optional[FunctionInfo],
                        arg: ast.AST) -> bool:
    """np.asarray's argument traced back to a dispatch: either the
    call itself, or a name whose scope-aware binding (including
    tuple-unpack targets) is one."""
    if isinstance(arg, ast.Call):
        return _dispatch_call(project, mod, ctx, arg)
    if isinstance(arg, ast.Name):
        return _dispatch_call(project, mod, ctx,
                              lookup_assign(mod, ctx, arg.id))
    return False


# ---------------------------------------------------------- JL002 (jit)

def _check_jit_donation(project: Project, mod: ModuleInfo,
                        node: ast.Call,
                        fn: Optional[FunctionInfo]) -> Iterable[Finding]:
    if not node.args:
        return []
    targets = _resolve_jitted_fn(project, mod, node.args[0], fn)
    donated = _int_tuple(_kwarg(node, "donate_argnums"))
    donated_names = _str_tuple(_kwarg(node, "donate_argnames"))
    out = []
    for target, offset in targets:
        params = [a.arg for a in target.node.args.args]
        missing = []
        for i, p in enumerate(params):
            if i < offset:
                continue    # bound by functools.partial, not a jit arg
            if p in KV_POOL_NAMES and (i - offset) not in donated \
                    and p not in donated_names:
                missing.append(p)
        if missing:
            out.append(_f(
                mod, "JL002", node, fn,
                f"{target.qualname}:{','.join(missing)}",
                f"`jax.jit({target.node.name if hasattr(target.node, 'name') else '<lambda>'})` "
                f"passes KV pool arg(s) {missing} without donating "
                f"them (donate_argnums): XLA copies the whole page "
                f"pool per call instead of updating it in place"))
    return out


def check_decorated_defs(project: Project,
                         mod: ModuleInfo) -> List[Finding]:
    """JL002 for the decorator form: @jax.jit / @partial(jax.jit, ...)
    on a def taking KV-pool args."""
    out: List[Finding] = []
    for fninfo in mod.functions:
        node = fninfo.node
        if isinstance(node, ast.Lambda):
            continue
        for dec in node.decorator_list:
            donated: Set[int] = set()
            donated_names: Set[str] = set()
            is_jit = False
            if dotted_name(dec).split(".")[-1] in JIT_NAMES:
                is_jit = True
            elif isinstance(dec, ast.Call):
                tail = call_name(dec).split(".")[-1]
                if tail in JIT_NAMES:
                    is_jit = True
                elif tail == "partial" and dec.args and \
                        dotted_name(dec.args[0]).split(".")[-1] \
                        in JIT_NAMES:
                    is_jit = True
                if is_jit:
                    donated = _int_tuple(_kwarg(dec, "donate_argnums"))
                    donated_names = _str_tuple(
                        _kwarg(dec, "donate_argnames"))
            if not is_jit:
                continue
            params = [a.arg for a in node.args.args]
            missing = [p for i, p in enumerate(params)
                       if p in KV_POOL_NAMES and i not in donated
                       and p not in donated_names]
            if missing:
                out.append(_f(
                    mod, "JL002", node, fninfo,
                    f"{fninfo.qualname}:{','.join(missing)}",
                    f"jitted `{node.name}` takes KV pool arg(s) "
                    f"{missing} without donate_argnums: the pool is "
                    f"copied per call instead of updated in place"))
    return out


def _resolve_jitted_fn(project: Project, mod: ModuleInfo, arg: ast.AST,
                       ctx: Optional[FunctionInfo]
                       ) -> List[tuple]:
    """-> [(FunctionInfo, offset)]: the defs a jit-site argument
    resolves to, with the count of positional args pre-bound by
    functools.partial chains (jit-level donate indices are shifted by
    that many)."""
    if isinstance(arg, ast.Lambda):
        info = project._function_for_node(mod, arg)
        return [(info, 0)] if info else []
    if isinstance(arg, (ast.Name, ast.Attribute)):
        name = dotted_name(arg)
        tail = name.split(".")[-1]
        if not tail:
            return []
        hits = [(t, 0) for t in project._resolve(
            mod, ctx, tail,
            is_self=name.startswith(("self.", "cls.")))]
        if not hits and isinstance(arg, ast.Name):
            # name bound to functools.partial(f, ...) — mirror the
            # traced-seeding resolver so JL002 sees the same fns
            val = lookup_assign(mod, ctx, arg.id)
            if isinstance(val, ast.Call) \
                    and call_name(val).split(".")[-1] == "partial" \
                    and val.args:
                return [(t, off + len(val.args) - 1)
                        for t, off in _resolve_jitted_fn(
                            project, mod, val.args[0], ctx)]
        return hits
    if isinstance(arg, ast.Call):
        name = call_name(arg)
        tail = name.split(".")[-1]
        if tail == "partial" and arg.args:
            return [(t, off + len(arg.args) - 1)
                    for t, off in _resolve_jitted_fn(
                        project, mod, arg.args[0], ctx)]
        # factory: jax.jit(self._build_decode()) -> the returned defs
        out: List[tuple] = []
        for target in project._resolve(
                mod, ctx, tail,
                is_self=name.startswith(("self.", "cls."))):
            out.extend((ret, 0) for ret in target.returned_defs)
        return out
    return []


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _int_tuple(node: Optional[ast.AST]) -> Set[int]:
    if node is None:
        return set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        return {el.value for el in node.elts
                if isinstance(el, ast.Constant)
                and isinstance(el.value, int)}
    return set()


def _str_tuple(node: Optional[ast.AST]) -> Set[str]:
    if node is None:
        return set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        return {el.value for el in node.elts
                if isinstance(el, ast.Constant)
                and isinstance(el.value, str)}
    return set()


# --------------------------------------------------------------- JL003

def _jitted_binding_statics(mod: ModuleInfo,
                            ctx: Optional[FunctionInfo],
                            name: str) -> Optional[Set[int]]:
    """static_argnums of the jax.jit(...) call bound to `name` in the
    CALLER'S scope (enclosing-function chain, then module; dotted
    'self.x' names at module scope) — or None when the name is not a
    jit binding there. Scope-aware on purpose: an unrelated
    function's local `fn = jax.jit(...)` must not make every `fn(...)`
    in the module look jitted."""
    value = lookup_assign(mod, ctx, name)
    if is_jit_call(value):
        return _int_tuple(_kwarg(value, "static_argnums"))
    return None


def _check_jit_callsite_args(mod: ModuleInfo, node: ast.Call,
                             fn: Optional[FunctionInfo], name: str
                             ) -> Iterable[Finding]:
    if not name:
        return []
    statics = _jitted_binding_statics(mod, fn, name)
    if statics is None:
        return []
    out = []
    for i, arg in enumerate(node.args):
        if i in statics:
            if isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                out.append(_f(
                    mod, "JL003", node, fn, f"{name}:arg{i}",
                    f"unhashable {type(arg).__name__.lower()} literal "
                    f"at static position {i} of jitted `{name}`: "
                    f"static args must be hashable (TypeError at "
                    f"runtime)"))
            continue
        hazard = None
        if isinstance(arg, ast.Constant) \
                and isinstance(arg.value, (bool, int, float)):
            hazard = f"Python {type(arg.value).__name__} literal"
        elif isinstance(arg, ast.Call) \
                and call_name(arg) in ("len", "int", "float", "bool"):
            hazard = f"`{call_name(arg)}(...)` host scalar"
        elif isinstance(arg, ast.IfExp) and (
                (isinstance(arg.body, ast.Constant)
                 and arg.body.value is None)
                ^ (isinstance(arg.orelse, ast.Constant)
                   and arg.orelse.value is None)):
            hazard = "conditional None/array argument (pytree " \
                     "structure varies per call -> retrace)"
        if hazard:
            out.append(_f(
                mod, "JL003", node, fn, f"{name}:arg{i}",
                f"{hazard} at traced position {i} of jitted "
                f"`{name}`: type/shape drift here retraces or "
                f"re-uploads per call; mark static or pass a device "
                f"array"))
    return out


# --------------------------------------------------------------- JL004

def _closure_owner(fn: FunctionInfo, name: str
                   ) -> Optional[FunctionInfo]:
    """The function (self or ancestor) whose local `name` is, or None
    for module globals."""
    f = fn
    while f is not None:
        if name in f.local_names:
            return f
        f = f.parent
    return None


def _hazardous_closure_write(fn: FunctionInfo, name: str) -> bool:
    """Writing a name owned by an enclosing TRACED function is the
    Pallas-ref / scratch idiom (same trace, fine). Writing a host
    ancestor's local or a module global from traced code is the
    trace-time-only mutation / tracer-leak hazard."""
    owner = _closure_owner(fn, name)
    if owner is fn:
        return False
    return owner is None or not owner.traced


def _check_traced_assign(mod: ModuleInfo, node, fn: FunctionInfo
                         ) -> Iterable[Finding]:
    out = []
    targets = (node.targets if isinstance(node, ast.Assign)
               else [node.target])
    for tgt in targets:
        for el in (tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]):
            if isinstance(el, ast.Attribute):
                dn = dotted_name(el)
                out.append(_f(
                    mod, "JL004", node, fn, f"attr:{dn}",
                    f"assignment to `{dn}` inside a traced function: "
                    f"object state mutated under trace captures a "
                    f"tracer (leak) and silently no-ops on later "
                    f"cached calls"))
            elif isinstance(el, ast.Subscript) \
                    and isinstance(el.value, ast.Name) \
                    and _hazardous_closure_write(fn, el.value.id):
                out.append(_f(
                    mod, "JL004", node, fn, f"mutate:{el.value.id}",
                    f"subscript assignment to closure/global "
                    f"`{el.value.id}` inside a traced function: "
                    f"mutation happens at trace time only (stale on "
                    f"cached calls) and can leak tracers"))
    return out


# NOTE: no "update" — optax's pure `opt.update(grads, state)` is the
# canonical traced call and would false-positive constantly
MUTATORS = {"append", "extend", "add", "insert",
            "setdefault", "remove"}


def check_traced_mutator_calls(mod: ModuleInfo) -> List[Finding]:
    """JL004: container mutation on closure/global names under trace
    (separate walk — needs local-name sets finalized)."""
    out: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fn, _ = mod.node_ctx.get(id(node), (None, 0))
        if fn is None or not fn.traced:
            continue
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in MUTATORS \
                and isinstance(node.func.value, ast.Name) \
                and _hazardous_closure_write(fn, node.func.value.id):
            nm = node.func.value.id
            out.append(_f(
                mod, "JL004", node, fn, f"mutate:{nm}",
                f"`.{node.func.attr}()` on closure/global `{nm}` "
                f"inside a traced function: runs at trace time "
                f"only; cached calls skip it (and it may capture "
                f"tracers)"))
    return out
