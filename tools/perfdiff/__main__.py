"""CLI for the perf-fingerprint regression gate (tools/perfdiff).

    python -m tools.perfdiff                     # run + compare
    python -m tools.perfdiff --current run.json  # compare a recorded run
    python -m tools.perfdiff --write-baseline    # regenerate baseline
    python -m tools.perfdiff --baseline other.json

Exit status: 0 = fingerprint within baseline, 1 = regression (or a
baseline/schema problem). The run path forces JAX_PLATFORMS=cpu so the
canonical workload's exact fields stay machine-independent.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main() -> int:
    ap = argparse.ArgumentParser(prog="tools.perfdiff")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: PERF_BASELINE.json)")
    ap.add_argument("--current", default=None,
                    help="compare this recorded fingerprint instead of "
                         "running the canonical workload")
    ap.add_argument("--write-baseline", action="store_true",
                    help="run the canonical workload and (re)write the "
                         "baseline file")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from tools import perfdiff

    path = args.baseline or perfdiff.BASELINE_PATH
    if args.write_baseline:
        fp = perfdiff.run_canonical_workload()
        with open(path, "w") as f:
            json.dump(fp, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {path}")
        return 0

    if args.current:
        with open(args.current) as f:
            current = json.load(f)
        # accept either a bare fingerprint or a full bench_llm --smoke
        # JSON line (the fingerprint rides detail.perf.fingerprint)
        if "exact" not in current:
            current = (current.get("detail", {}).get("perf", {})
                       .get("fingerprint", {}))
    else:
        current = perfdiff.run_canonical_workload()

    baseline = perfdiff.load_baseline(path)
    failures = perfdiff.compare(baseline, current)
    if failures:
        print("PERF REGRESSION vs", path)
        for f_ in failures:
            print("  -", f_)
        return 1
    print(f"perf fingerprint OK vs {path} "
          f"({len(baseline.get('exact', {}))} exact, "
          f"{len(baseline.get('noisy', {}))} banded metrics)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
