"""perfdiff: perf-fingerprint regression checker (ISSUE 11).

Turns BENCH_CORE.md's prose perf trajectory into an ASSERTED one: a
perf fingerprint — the analytic cost model's exact per-token numbers,
the workload's dispatch mix and token totals, plus the (machine-
dependent) achieved rates — is recorded by `bench_llm --smoke` and by
`run_canonical_workload()` here, and `compare()` checks a fresh run
against the committed baseline (PERF_BASELINE.json at the repo root):

- `exact` metrics are DETERMINISTIC on any machine: closed-form model
  costs (FLOPs/bytes per token — they depend only on the model
  config) and the canonical workload's scheduling outcome (ticks,
  dispatches, token counts, analytic FLOP totals: token COUNTS are
  fixed by max_tokens even where near-tie argmax values flip). Any
  drift is a real change — a cost-model edit, a scheduler regression
  (extra dispatches), or a packing change — and fails the diff.
- `noisy` metrics (tokens/s, MFU, MBU) vary with the host; they are
  checked against a wide noise band (catastrophe detection, not
  micro-benchmarking) and reported, not trusted, across machines.

CLI:
    python -m tools.perfdiff                     # run + compare
    python -m tools.perfdiff --current f.json    # compare a recorded run
    python -m tools.perfdiff --write-baseline    # regenerate baseline
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional

SCHEMA = 1
BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "PERF_BASELINE.json")

# Default noise band for `noisy` metrics: current/baseline ratio must
# stay inside [lo, hi]. Deliberately wide — the committed baseline was
# measured on one shared 1-vCPU VM and the gate must not flake on a
# faster/slower host; it exists to catch order-of-magnitude collapses.
DEFAULT_BAND = (0.02, 50.0)
# Relative tolerance for `exact` float comparisons (they are computed,
# not measured; anything past rounding is real drift).
EXACT_RTOL = 1e-6


def run_canonical_workload() -> Dict[str, Any]:
    """Drive the canonical perf workload and return its fingerprint.

    Fixed seeded workload on the debug model (greedy, fixed
    max_tokens, prefix caching off, envelope pinned to "cpu"): every
    `exact` field is machine-independent. Small enough for tier-1
    (tests/test_perfdiff.py runs it)."""
    import numpy as np

    from ray_tpu.llm._internal.engine import (EngineConfig,
                                              InferenceEngine, Request,
                                              SamplingParams)
    from ray_tpu.models import llama

    cfg = llama.config("debug")
    eng = InferenceEngine(EngineConfig(
        model=cfg, max_batch_size=4, page_size=8, num_pages=128,
        prefill_buckets=(16, 32, 64), max_prefill_tokens=16, seed=7,
        enable_prefix_caching=False, perf_envelope="cpu"))
    rng = np.random.default_rng(11)
    reqs = [Request(f"pf{i}",
                    rng.integers(2, 250, 12 + 4 * (i % 3)).tolist(),
                    SamplingParams(max_tokens=16))
            for i in range(8)]
    pending = list(reqs)
    import time
    t0 = time.perf_counter()
    step = 0
    while pending or eng.has_work():
        # two requests land every 4 ticks: prefill and decode contend,
        # so the fingerprint covers ragged AND pure-decode ticks
        if step % 4 == 0:
            for r in pending[:2]:
                eng.add_request(r)
            del pending[:2]
        eng.step()
        step += 1
        assert step < 10_000
    dt = time.perf_counter() - t0
    stats = eng.stats()
    return make_fingerprint(stats, cfg, elapsed_s=dt)


def make_fingerprint(stats: Dict[str, Any], model_cfg,
                     elapsed_s: float = 0.0) -> Dict[str, Any]:
    """Build a fingerprint from engine stats() + the model config.
    Shared by run_canonical_workload and the bench_llm perf gate."""
    from ray_tpu.llm._internal.perfmodel import CostModel

    perf = stats.get("perf") or {}
    tot = perf.get("totals") or {}
    cm = CostModel(model_cfg, page_size=8)
    gen = tot.get("decode_tokens", 0.0) + tot.get("prefill_tokens", 0.0)
    return {
        "schema": SCHEMA,
        "exact": {
            # closed-form model costs (workload-independent)
            "gemm_flops_per_token": cm.gemm_flops_per_token,
            "head_flops": cm.head_flops,
            "attn_flops_per_pair": cm.attn_flops_per_pair,
            "kv_bytes_per_token": cm.kv_bytes_per_token,
            "weight_bytes": cm.weight_bytes,
            # scheduling outcome of the workload
            "ticks": stats.get("ticks", 0),
            "dispatches": stats.get("dispatches", 0),
            "dispatches_per_step": stats.get("dispatches_per_step",
                                             0.0),
            "decode_tokens": tot.get("decode_tokens", 0.0),
            "prefill_tokens": tot.get("prefill_tokens", 0.0),
            "flops_total": tot.get("flops", 0.0),
            "flops_attn_total": tot.get("flops_attn", 0.0),
            "hbm_bytes_weights": tot.get("bytes_weights", 0.0),
            "hbm_bytes_kv_read": tot.get("bytes_kv_read", 0.0),
            "hbm_bytes_kv_write": tot.get("bytes_kv_write", 0.0),
        },
        "noisy": {
            "tokens_per_s": round(gen / elapsed_s, 3)
            if elapsed_s > 0 else 0.0,
            "mfu": perf.get("mfu", 0.0),
            "mbu": perf.get("mbu", 0.0),
        },
        "envelope": perf.get("envelope", ""),
    }


def compare(baseline: Dict[str, Any], current: Dict[str, Any],
            band: Optional[tuple] = None) -> List[str]:
    """Diff a current fingerprint against the committed baseline.
    Returns a list of human-readable FAILURES (empty = pass): exact
    metrics must match to EXACT_RTOL, noisy metrics must stay inside
    the ratio band (baseline may override per-metric via "bands")."""
    failures: List[str] = []
    if baseline.get("schema") != current.get("schema"):
        failures.append(
            f"schema mismatch: baseline {baseline.get('schema')} vs "
            f"current {current.get('schema')}")
        return failures
    b_exact = baseline.get("exact", {})
    c_exact = current.get("exact", {})
    for key, bval in b_exact.items():
        if key not in c_exact:
            failures.append(f"exact metric missing from current: {key}")
            continue
        cval = c_exact[key]
        bf, cf = float(bval), float(cval)
        if not math.isclose(bf, cf, rel_tol=EXACT_RTOL, abs_tol=1e-9):
            failures.append(
                f"exact metric drifted: {key} baseline={bval} "
                f"current={cval}")
    bands = baseline.get("bands", {})
    lo, hi = band or DEFAULT_BAND
    for key, bval in baseline.get("noisy", {}).items():
        if key not in current.get("noisy", {}):
            failures.append(f"noisy metric missing from current: {key}")
            continue
        cval = float(current["noisy"][key])
        bf = float(bval)
        klo, khi = bands.get(key, (lo, hi))
        if bf > 0 and not (klo <= cval / bf <= khi):
            failures.append(
                f"noisy metric outside band: {key} baseline={bval} "
                f"current={cval} ratio={cval / bf:.4f} "
                f"band=[{klo}, {khi}]")
        elif bf <= 0 < cval:
            pass        # baseline idle, current live: fine
    return failures


def load_baseline(path: Optional[str] = None) -> Dict[str, Any]:
    with open(path or BASELINE_PATH) as f:
        return json.load(f)


__all__ = ["run_canonical_workload", "make_fingerprint", "compare",
           "load_baseline", "BASELINE_PATH", "SCHEMA", "DEFAULT_BAND"]
