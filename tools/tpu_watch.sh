#!/bin/bash
# Tunnel watcher: probe the axon TPU tunnel every PERIOD seconds; on the
# first green probe, run the A/B dispatch probes + bench.py + bench_llm.py,
# save outputs under tpu_watch/, and exit 0 (signals the driver session).
# Exits 3 after MAX_LOOPS fruitless probes.
cd /root/repo || exit 1
mkdir -p tpu_watch
PERIOD=${PERIOD:-1080}
MAX_LOOPS=${MAX_LOOPS:-40}
PROBE='
import jax, jax.numpy as jnp
d = jax.devices()[0]
x = (jnp.ones((128,128), jnp.bfloat16) @ jnp.ones((128,128), jnp.bfloat16))
float(x[0,0])
print("PROBE_OK", d.platform, getattr(d, "device_kind", str(d)), flush=True)
'
for i in $(seq 1 "$MAX_LOOPS"); do
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  out=$(timeout 90 python -c "$PROBE" 2>&1)
  if echo "$out" | grep -q PROBE_OK; then
    echo "$ts GREEN loop=$i: $out" >> tpu_watch/watch.log
    echo "$ts" > tpu_watch/GREEN_AT
    timeout 700 python bench_dispatch_ab.py > tpu_watch/ab_results.jsonl 2> tpu_watch/ab_stderr.log
    timeout 900 python bench.py > tpu_watch/bench_mfu.json 2> tpu_watch/bench_mfu.stderr
    timeout 1500 python bench_llm.py > tpu_watch/bench_llm.json 2> tpu_watch/bench_llm.stderr
    echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) done green-window runs" >> tpu_watch/watch.log
    exit 0
  fi
  echo "$ts down loop=$i: $(echo "$out" | tail -1 | cut -c1-120)" >> tpu_watch/watch.log
  sleep "$PERIOD"
done
exit 3
