"""CLI: python -m tools.simcal --out <calibration.json> [--name N]

Options:
    --out PATH     where to write the SimCalibration JSON (required)
    --name NAME    calibration name recorded in the file
    --ticks N      steady decode ticks measured per batch bucket
    --curve PATH   additionally run a small capacity sweep against
                   the fresh calibration and write the artifact
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tools.simcal",
                                 description=__doc__)
    ap.add_argument("--out", required=True)
    ap.add_argument("--name", default="cpu-debug")
    ap.add_argument("--ticks", type=int, default=48)
    ap.add_argument("--curve", default=None)
    args = ap.parse_args(argv)

    from tools.simcal import build_engine, drive_calibration_workload
    from ray_tpu.serve.llm.sim.calibration import SimCalibration

    eng = build_engine()
    drive_calibration_workload(eng, decode_ticks=args.ticks)
    calib = SimCalibration.from_engine(eng, name=args.name)
    calib.save(args.out)
    print(json.dumps({"wrote": args.out, "name": calib.name,
                      "buckets": sorted(calib.decode_tick_ms),
                      "prefill_ms_per_token":
                          calib.prefill_ms_per_token,
                      "spill_ms": calib.spill_ms,
                      "restore_ms": calib.restore_ms}))
    if args.curve:
        from ray_tpu.serve.llm.sim import (SimFleetConfig,
                                           TraceConfig,
                                           capacity_curve,
                                           write_artifact)
        curve = capacity_curve(
            TraceConfig(kind="diurnal", sessions=20_000,
                        duration_s=3600.0, seed=7),
            SimFleetConfig(calibration=calib),
            replica_counts=[1, 2, 4, 8])
        write_artifact(curve, args.curve)
        print(json.dumps({"wrote": args.curve,
                          "points": len(curve["points"])}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
