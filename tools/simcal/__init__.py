"""simcal: extract a SimCalibration from a REAL engine's telemetry.

The fleet simulator (ray_tpu/serve/llm/sim) is only as honest as its
timing model. This tool drives a real `InferenceEngine` through a
mixed calibration workload — decode-only phases at several batch
sizes (one per batch bucket), chunked prefills, and (when the host
tier is on) forced spill/restore cycles — then distills
`stats()["tick_times"]` plus the PR 11 per-tick PerfSample window
into the `SimCalibration` JSON the synthetic replicas consume:

    python -m tools.simcal --out ray_tpu/serve/llm/sim/calibration_cpu.json

The committed `calibration_cpu.json` was produced exactly this way
against the debug model in the tier-1 CPU environment; TPU-tier files
should be regenerated on real hardware (same command, bigger model)
when the tunnel returns. The sim-vs-real A/B in tests/test_fleet_sim
pins predictions from the committed file within CALIBRATION_BAND, so
a stale file fails loudly instead of quietly skewing every capacity
curve.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


def build_engine(num_pages: int = 96, max_batch: int = 8,
                 offload: bool = True) -> Any:
    from ray_tpu.llm._internal.engine import (EngineConfig,
                                              InferenceEngine)
    return InferenceEngine(EngineConfig(
        model="debug", max_batch_size=max_batch, page_size=16,
        num_pages=num_pages, max_prefill_tokens=128,
        enable_kv_offload=offload,
        kv_watermark_tokens=16 if offload else None,
        host_kv_pages=4 * num_pages if offload else None,
        enable_metrics=True, enable_blackbox=False, seed=0))


def drive_calibration_workload(engine: Any,
                               decode_ticks: int = 48) -> None:
    """The measurement workload: per batch bucket (1, 2, 4, ...,
    max_batch) admit that many requests, run the prefills off, then
    `decode_ticks` pure-decode ticks so every bucket's tick-wall
    distribution is populated; finish with an oversubscribed phase
    that forces spill/restore traffic for the preemption timings."""
    from ray_tpu.llm._internal.engine import Request, SamplingParams
    rid = iter(range(10_000))

    def submit(n: int, prompt: int, out: int, priority: int = 0):
        reqs = []
        for _ in range(n):
            r = Request(f"cal-{next(rid)}", list(range(2, 2 + prompt)),
                        SamplingParams(max_tokens=out,
                                       temperature=0.0),
                        priority=priority)
            engine.add_request(r)
            reqs.append(r)
        return reqs

    b = 1
    while b <= engine.config.max_batch_size:
        reqs = submit(b, prompt=24, out=decode_ticks + 8)
        # run the prefill phase off, then measure steady decode
        while any(len(r.output_tokens) < 2 and not r.finished
                  for r in reqs):
            engine.step()
        for _ in range(decode_ticks):
            engine.step()
        for r in reqs:
            engine.abort(r.request_id)
        b *= 2
    # chunked-prefill phase: prompts several chunk budgets long
    reqs = submit(2, prompt=3 * engine.config.max_prefill_tokens
                  // 4 * 2, out=4)
    while not all(r.finished for r in reqs):
        engine.step()
    if engine.host_tier is not None:
        # force preemption churn: low-priority residents, then a
        # higher-priority burst that spills them (ISSUE 14 priority
        # path — the same machinery the batch lane rides)
        low = submit(engine.config.max_batch_size, prompt=16, out=64)
        for _ in range(8):
            engine.step()
        high = submit(engine.config.max_batch_size, prompt=16,
                      out=8, priority=1)
        while not all(r.finished for r in high):
            engine.step()
        deadline = 4000
        while not all(r.finished for r in low) and deadline:
            engine.step()
            deadline -= 1


def extract(name: str = "cpu-debug",
            engine: Optional[Any] = None) -> Any:
    """Build (or take) an engine, drive the workload, return the
    SimCalibration."""
    from ray_tpu.serve.llm.sim.calibration import SimCalibration
    eng = engine if engine is not None else build_engine()
    drive_calibration_workload(eng)
    return SimCalibration.from_engine(eng, name=name)


def check_against(calib: Any, summary: Dict[str, Any],
                  measured_e2e_s: float) -> Dict[str, Any]:
    """The A/B helper: compare a sim run's mean e2e against a real
    measured one; returns the ratio + band verdict."""
    from ray_tpu.serve.llm.sim.calibration import CALIBRATION_BAND
    sim_e2e = summary["latency"]["e2e"]["mean_ms"] / 1e3
    ratio = sim_e2e / measured_e2e_s if measured_e2e_s > 0 else 0.0
    lo, hi = CALIBRATION_BAND
    return {"sim_e2e_s": round(sim_e2e, 4),
            "real_e2e_s": round(measured_e2e_s, 4),
            "ratio": round(ratio, 4),
            "band": [lo, hi],
            "within_band": lo <= ratio <= hi}


__all__ = ["build_engine", "drive_calibration_workload", "extract",
           "check_against"]
