"""CLI for the capture-replay regression gate (tools/tracereplay).

    # replay through the sim, emit + gate on the capture-diff
    python -m tools.tracereplay capture.jsonl --replicas 2 \\
        --out capture_diff.json

    # what-if re-pricing: same recorded workload, swept fleet shapes
    python -m tools.tracereplay capture.jsonl --what-if \\
        --replicas 2,4,8 --chips 2 --kv-dtype int8

    # highest-fidelity mode: re-dispatch through an in-process fleet
    python -m tools.tracereplay capture.jsonl --fleet --replicas 2

Exit status: 0 = replay inside the band (diff passes), 1 = capture-
diff failures (regression), 2 = unreadable/corrupt capture or usage
error. The replay path forces JAX_PLATFORMS=cpu; a given capture +
flags replays byte-identically (seeded sim, virtual clock).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools.tracereplay",
        description="replay a traffic capture; emit a capture-diff")
    ap.add_argument("capture", help="capture file (RTTC segments), "
                    "e.g. from GET /fleet/debug/traffic?capture=1")
    ap.add_argument("--replicas", default="2",
                    help="replica count, or comma list in --what-if "
                         "mode (default 2)")
    ap.add_argument("--chips", type=int, default=1,
                    help="chips per replica (slice shape)")
    ap.add_argument("--kv-dtype", default="bf16",
                    choices=["bf16", "f32", "int8", "fp8"],
                    help="KV cache dtype override (scales page "
                         "budget: int8/fp8 pack 2x)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--speed", type=float, default=1.0,
                    help="time-warp: >1 compresses recorded "
                         "inter-arrival gaps")
    ap.add_argument("--what-if", action="store_true",
                    help="sweep --replicas list and re-price instead "
                         "of diffing")
    ap.add_argument("--fleet", action="store_true",
                    help="replay against an in-process debug-model "
                         "fleet instead of the simulator")
    ap.add_argument("--out", default=None,
                    help="write the artifact JSON here")
    ap.add_argument("--json", action="store_true",
                    help="print the artifact to stdout")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ray_tpu.serve.llm.trafficlog import CaptureError, load_capture
    from tools import tracereplay

    try:
        capture = load_capture(args.capture)
    except CaptureError as e:
        print(f"tracereplay: bad capture: {e}", file=sys.stderr)
        return 2

    try:
        counts = [int(x) for x in str(args.replicas).split(",") if x]
    except ValueError:
        print(f"tracereplay: bad --replicas {args.replicas!r}",
              file=sys.stderr)
        return 2
    if not counts or any(n < 1 for n in counts):
        print(f"tracereplay: bad --replicas {args.replicas!r}",
              file=sys.stderr)
        return 2

    if args.what_if:
        doc = tracereplay.what_if(
            capture, counts, chips_per_replica=args.chips,
            kv_dtype=args.kv_dtype, seed=args.seed)
        rc = 0
    elif args.fleet:
        import asyncio
        doc = asyncio.run(tracereplay.replay_fleet(
            capture, replicas=counts[0]))
        rc = 0
    else:
        summary = tracereplay.replay_sim(
            capture, replicas=counts[0], speed=args.speed,
            seed=args.seed, chips_per_replica=args.chips,
            kv_dtype=args.kv_dtype)
        doc = tracereplay.capture_diff(capture, summary,
                                       seed=args.seed)
        rc = 0 if doc["pass"] else 1

    if args.out:
        tracereplay.write_artifact(doc, args.out)
        print(f"wrote {args.out}")
    if args.json or not args.out:
        print(json.dumps(doc, sort_keys=True, indent=2))
    if rc:
        for f_ in doc.get("failures", []):
            print(f"CAPTURE DIFF FAIL: {f_}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
