"""tracereplay: deterministic replay + capture-diff for traffic
captures (ISSUE 20).

The workload-level regression gate beside `tools/perfdiff`'s
per-dispatch one: take a capture the fleet's traffic recorder sealed
(`POST /fleet/debug/traffic {"action":"stop"}` →
`GET /fleet/debug/traffic?capture=1`), replay it through the
real-objects fleet simulator (`sim.traffic.RecordedTrace`) — or
against an in-process fleet — and compare what the replay predicts
against what production recorded:

- SLO histograms (p50/p99 TTFT and e2e), banded by the same
  CALIBRATION_BAND the sim-vs-real A/B uses;
- prefix-hit rate (recorded router `affinity` outcomes vs the sim
  router's affinity_hits/picks);
- route mix (affinity/spill/scored/... outcome counts);
- per-tenant cost rollups (requests + token volumes).

The emitted capture-diff artifact embeds provenance (calibration
checksum, seed, capture id) and a human-readable failure list —
empty means the workload still behaves. What-if mode re-runs the
SAME capture at overridden replica count / slice shape / kv-dtype
page scaling and re-prices the operating points like the capacity
sweep.

    python -m tools.tracereplay capture.jsonl --replicas 2 \
        --out capture_diff.json
    python -m tools.tracereplay capture.jsonl --what-if \
        --replicas 2,4,8 --chips 2
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

# the banded-compare tolerances: latency ratios ride the sim's
# calibration band; rate/mix comparisons are absolute (a hit RATE
# ratio explodes near zero)
RATE_TOLERANCE = 0.35          # |recorded - replayed| prefix-hit rate
MIX_TOLERANCE = 0.5            # per-outcome route-mix share drift

# kv_dtype → KV-page capacity multiplier vs bf16 (half-precision
# cache): int8/fp8 pack 2x the tokens per page budget
KV_DTYPE_PAGE_SCALE = {"bf16": 1.0, "f32": 0.5, "int8": 2.0,
                       "fp8": 2.0}


def recorded_stats(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Distill a capture's records into the recorded side of the
    diff: latency percentiles from the outcome briefs, route mix,
    prefix-hit rate, per-tenant rollups."""
    from ray_tpu.serve.llm.sim.replica import Hist

    ttft, e2e = Hist(), Hist()
    mix: Dict[str, int] = {}
    tenants: Dict[str, Dict[str, int]] = {}
    routed = 0
    affinity = 0
    completed = 0
    for r in records:
        out = r.get("outcome") or {}
        tenant = str(r.get("tenant") or "") or "default"
        row = tenants.setdefault(
            tenant, {"requests": 0, "prompt_tokens": 0,
                     "out_tokens": 0})
        row["requests"] += 1
        row["prompt_tokens"] += int(r.get("prompt_tokens") or 0)
        row["out_tokens"] += int(r.get("out_tokens") or 0)
        route = out.get("route")
        if route:
            mix[str(route)] = mix.get(str(route), 0) + 1
            routed += 1
            if route == "affinity":
                affinity += 1
        if str(out.get("status") or "ok") == "ok":
            completed += 1
        if out.get("ttft_ms") is not None:
            ttft.add(float(out["ttft_ms"]) / 1e3)
        if out.get("e2e_ms") is not None:
            e2e.add(float(out["e2e_ms"]) / 1e3)
    return {
        "requests": len(records),
        "completed": completed,
        "latency": {"ttft": ttft.summary_ms(),
                    "e2e": e2e.summary_ms()},
        "route_mix": dict(sorted(mix.items())),
        "prefix_hit_rate": round(affinity / routed, 6) if routed
        else None,
        "tenants": dict(sorted(tenants.items())),
    }


def replayed_stats(summary: Dict[str, Any]) -> Dict[str, Any]:
    """The replay side of the diff, from a FleetSimulator summary."""
    router = summary.get("router") or {}
    picks = int(router.get("picks") or 0)
    hits = int(router.get("affinity_hits") or 0)
    sessions = summary.get("sessions") or {}
    # FleetRouter.stats() exposes the outcome counters individually;
    # rebuild the same outcome-keyed mix the recorder's route briefs
    # use ("affinity"/"spill"/"scored", pick_ex's vocabulary)
    mix = {k: int(router.get(src) or 0)
           for k, src in (("affinity", "affinity_hits"),
                          ("spill", "spills"),
                          ("scored", "scored_fallbacks"))
           if router.get(src)}
    return {
        "requests": int(sessions.get("arrived") or 0),
        "completed": int(sessions.get("completed") or 0),
        "latency": {"ttft": summary["latency"]["ttft"],
                    "e2e": summary["latency"]["e2e"]},
        "route_mix": dict(sorted(mix.items())),
        "prefix_hit_rate": round(hits / picks, 6) if picks else None,
        "tenants": {t: {"requests": int(n)}
                    for t, n in (summary.get("tenants")
                                 or {}).items()},
    }


def replay_sim(capture: Dict[str, Any], replicas: int = 2,
               speed: float = 1.0, seed: int = 0,
               slots_per_replica: int = 8,
               pages_per_replica: int = 2048,
               chips_per_replica: int = 1,
               kv_dtype: str = "bf16",
               calibration: Optional[Any] = None) -> Dict[str, Any]:
    """Replay a decoded capture through the fleet simulator; returns
    the run summary (deterministic: same capture + args → byte-
    identical summary_json)."""
    from ray_tpu.serve.llm.sim import (FleetSimulator, RecordedTrace,
                                       SimFleetConfig,
                                       default_cpu_calibration)
    calib = calibration or default_cpu_calibration()
    scale = KV_DTYPE_PAGE_SCALE.get(kv_dtype, 1.0)
    cfg = SimFleetConfig(
        replicas=replicas, min_replicas=replicas,
        slots_per_replica=slots_per_replica,
        pages_per_replica=max(int(pages_per_replica * scale), 1),
        chips_per_replica=chips_per_replica,
        calibration=calib, seed=seed)
    sim = FleetSimulator(RecordedTrace(capture, speed=speed), cfg)
    return sim.run()


def _band_check(name: str, recorded: Optional[float],
                replayed: Optional[float],
                band) -> Optional[str]:
    """Latency ratio check: replayed/recorded must land in `band`.
    Either side missing (no streams recorded → no TTFT) skips the
    check rather than failing it — absence is visible in the
    metrics block, not a synthetic failure."""
    if not recorded or replayed is None:
        return None
    ratio = replayed / recorded
    lo, hi = band
    if lo <= ratio <= hi:
        return None
    return (f"{name}: replayed/recorded ratio {ratio:.3f} outside "
            f"band [{lo}, {hi}] (recorded {recorded:.3f}, "
            f"replayed {replayed:.3f})")


def capture_diff(capture: Dict[str, Any],
                 summary: Dict[str, Any],
                 band=None,
                 seed: int = 0,
                 calibration: Optional[Any] = None
                 ) -> Dict[str, Any]:
    """The banded comparison artifact. `failures` empty = the replay
    reproduces the recorded workload inside tolerance — the
    workload-level regression gate's verdict."""
    from ray_tpu.serve.llm.sim import (CALIBRATION_BAND,
                                       default_cpu_calibration)
    band = band or CALIBRATION_BAND
    calib = calibration or default_cpu_calibration()
    rec = recorded_stats(capture["records"])
    rep = replayed_stats(summary)
    failures: List[str] = []
    # gate on the SLO percentiles the fleet watches (p99): medians at
    # CPU-tier millisecond scale are dominated by fixed per-tick
    # overheads the calibration deliberately folds into the tail, so
    # a p50 ratio says more about the engine's floor than about
    # workload drift — p50s still ride the artifact for eyeballing
    for metric in ("ttft", "e2e"):
        f = _band_check(
            f"{metric}.p99_ms",
            (rec["latency"][metric] or {}).get("p99_ms"),
            (rep["latency"][metric] or {}).get("p99_ms"), band)
        if f:
            failures.append(f)
    if (rec["prefix_hit_rate"] is not None
            and rep["prefix_hit_rate"] is not None):
        drift = abs(rec["prefix_hit_rate"] - rep["prefix_hit_rate"])
        if drift > RATE_TOLERANCE:
            failures.append(
                f"prefix_hit_rate: recorded "
                f"{rec['prefix_hit_rate']:.3f} vs replayed "
                f"{rep['prefix_hit_rate']:.3f} "
                f"(drift {drift:.3f} > {RATE_TOLERANCE})")
    # route-mix shares: every outcome present on either side
    rec_total = max(sum(rec["route_mix"].values()), 1)
    rep_total = max(sum(rep["route_mix"].values()), 1)
    for outcome in sorted(set(rec["route_mix"])
                          | set(rep["route_mix"])):
        a = rec["route_mix"].get(outcome, 0) / rec_total
        b = rep["route_mix"].get(outcome, 0) / rep_total
        if abs(a - b) > MIX_TOLERANCE:
            failures.append(
                f"route_mix[{outcome}]: recorded share {a:.3f} vs "
                f"replayed {b:.3f} (drift > {MIX_TOLERANCE})")
    return {
        "object": "capture_diff",
        "capture_id": capture["header"].get("capture_id"),
        "provenance": {
            "calibration": calib.name,
            "calibration_sha256": calib.checksum(),
            "seed": seed,
            "capture_id": capture["header"].get("capture_id"),
        },
        "band": list(band),
        "recorded": rec,
        "replayed": rep,
        "failures": failures,
        "pass": not failures,
    }


def what_if(capture: Dict[str, Any], replica_counts: List[int],
            chips_per_replica: int = 1, kv_dtype: str = "bf16",
            seed: int = 0,
            calibration: Optional[Any] = None) -> Dict[str, Any]:
    """Re-price the recorded workload at overridden operating points
    (the capacity sweep over a RECORDED trace instead of a synthetic
    one): replicas vs tail latency + per-chip token economics."""
    from ray_tpu.serve.llm.sim import default_cpu_calibration
    calib = calibration or default_cpu_calibration()
    points: List[Dict[str, Any]] = []
    for n in replica_counts:
        s = replay_sim(capture, replicas=n, seed=seed,
                       chips_per_replica=chips_per_replica,
                       kv_dtype=kv_dtype, calibration=calib)
        lat = s["latency"]
        sessions = s["sessions"]
        shed = sum(s["shed"].values())
        chips = n * max(chips_per_replica, 1)
        tokens = (s["engine"]["decode_tokens"]
                  + s["batch"]["tokens"])
        virtual_s = s["sim"]["virtual_s"]
        points.append({
            "replicas": n,
            "chips": chips,
            "kv_dtype": kv_dtype,
            "p50_ttft_ms": lat["ttft"]["p50_ms"],
            "p99_ttft_ms": lat["ttft"]["p99_ms"],
            "p99_e2e_ms": lat["e2e"]["p99_ms"],
            "shed": shed,
            "completed": sessions["completed"],
            "tokens_per_chip_s": round(
                tokens / max(virtual_s, 1e-9) / chips, 3),
            "chip_s_per_1k_tokens": round(
                virtual_s * chips / max(tokens / 1e3, 1e-9), 3),
        })
    return {
        "object": "what_if",
        "capture_id": capture["header"].get("capture_id"),
        "provenance": {
            "calibration": calib.name,
            "calibration_sha256": calib.checksum(),
            "seed": seed,
            "capture_id": capture["header"].get("capture_id"),
        },
        "points": points,
    }


async def replay_fleet(capture: Dict[str, Any], replicas: int = 1,
                       max_tokens_cap: int = 32) -> Dict[str, Any]:
    """Replay a capture against an in-process fleet of debug-model
    replicas (real FleetManager + LLMServerImpl — the expensive,
    highest-fidelity mode): each record re-dispatches with a
    synthetic prompt of the recorded token count and the recorded
    sampling params. Returns the replay fleet's own recorded stats,
    diff-able against the original capture."""
    import asyncio

    from ray_tpu.llm._internal.server import LLMServerImpl
    from ray_tpu.serve.llm import (AdmissionConfig, AutoscaleConfig,
                                   FleetManager, LocalReplicaClient,
                                   RouterConfig, WatchdogConfig)

    servers = []
    clients = []
    for i in range(replicas):
        srv = LLMServerImpl({
            "model_id": "replay", "model_source": "debug",
            "engine_kwargs": dict(
                max_batch_size=4, page_size=8, num_pages=64,
                seed=7, enable_metrics=False, enable_blackbox=False,
                metrics_model_id="replay",
                metrics_replica_id=f"r{i}")})
        servers.append(srv)
        clients.append(LocalReplicaClient(f"r{i}", srv))
    fleet = FleetManager(
        clients, router=RouterConfig(prefix_depth=64),
        admission=AdmissionConfig(max_concurrent=8, max_queue=256),
        autoscale=AutoscaleConfig(min_replicas=replicas,
                                  max_replicas=replicas),
        watchdog=WatchdogConfig(enabled=False),
        enable_tracing=False, model_id="replay")
    fleet.traffic.start_capture("replay")

    async def one(r: Dict[str, Any]) -> None:
        fp = str(r.get("fp") or "")
        prompt = " ".join(
            ["tok"] * max(int(r.get("prompt_tokens") or 1), 1))
        # prefix identity: lead with the fingerprint so the replay
        # router sees the same chain structure (never the raw text —
        # the capture does not have it)
        body = {"prompt": f"{fp[:16]} {prompt}",
                "max_tokens": min(
                    max(int(r.get("out_tokens") or 1), 1),
                    max_tokens_cap),
                "user": r.get("tenant") or None,
                **{k: v for k, v in (r.get("params") or {}).items()
                   if k in ("temperature", "top_p", "top_k", "seed")}}
        try:
            await fleet.dispatch("completions", body)
        except Exception:
            pass                     # sheds are data, not failures

    try:
        records = capture["records"]
        for i in range(0, len(records), 8):
            await asyncio.gather(*(one(r)
                                   for r in records[i:i + 8]))
        from ray_tpu.serve.llm.trafficlog import decode_capture
        out = fleet.traffic.stop_capture()
        replay_capture = decode_capture(fleet.traffic.export())
        return {"object": "fleet_replay",
                "capture_id": capture["header"].get("capture_id"),
                "replay_capture_id": out["capture_id"],
                "recorded": recorded_stats(capture["records"]),
                "replayed": recorded_stats(
                    replay_capture["records"])}
    finally:
        await fleet.stop()
        for srv in servers:
            pump = getattr(srv, "_pump", None)
            if pump is not None:
                pump.cancel()


def write_artifact(doc: Dict[str, Any], path: str) -> str:
    """Canonical JSON artifact (sorted keys — diffs are meaningful),
    the capacity-sweep discipline."""
    with open(path, "w") as f:
        json.dump(doc, f, sort_keys=True, indent=2)
        f.write("\n")
    return path


__all__ = ["recorded_stats", "replayed_stats", "replay_sim",
           "capture_diff", "what_if", "replay_fleet",
           "write_artifact", "RATE_TOLERANCE", "MIX_TOLERANCE",
           "KV_DTYPE_PAGE_SCALE"]
