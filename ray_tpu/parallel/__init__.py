from .mesh import (MeshSpec, AXIS_DP, AXIS_FSDP, AXIS_SP, AXIS_TP, AXIS_EP,
                   AXIS_PP, BATCH_AXES, batch_sharding, replicated,
                   mesh_shape, single_device_mesh)
from .sharding import (DEFAULT_RULES, spec_for, named_sharding,
                       with_logical_constraint, tree_shardings, shard_tree)
