"""Device meshes: the TPU-native replacement for process groups.

Reference parity: where the reference wires NCCL process groups
(python/ray/util/collective/collective.py:123, train/torch/config.py:66),
we declare a `MeshSpec` — named parallelism axes over a
jax.sharding.Mesh — and let XLA compile collectives onto ICI. The axes:

    pp     pipeline parallel (layer stages, GPipe microbatch rotation —
           models/pipeline.py; outermost so stage hops can ride DCN)
    dp     data parallel (gradient allreduce / psum)
    fsdp   fully-sharded data parallel (params sharded, all-gather on use)
    sp     sequence/context parallel (ring attention over ppermute, or
           Ulysses head-scatter all-to-all — ops/ulysses.py)
    tp     tensor parallel (heads/ffn sharded, psum on projections)
    ep     expert parallel (MoE expert sharding, all_to_all dispatch)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXIS_PP = "pp"
AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_SP = "sp"
AXIS_TP = "tp"
AXIS_EP = "ep"
ALL_AXES = (AXIS_PP, AXIS_DP, AXIS_FSDP, AXIS_SP, AXIS_TP, AXIS_EP)
# Activation batch is sharded over every data-like axis.
BATCH_AXES = (AXIS_DP, AXIS_FSDP)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative parallelism layout. -1 on one axis = use remaining devices."""

    dp: int = 1
    fsdp: int = -1
    sp: int = 1
    tp: int = 1
    ep: int = 1
    pp: int = 1

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = {f.name: getattr(self, f.name)
                 for f in dataclasses.fields(self)}
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {wild}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {fixed}")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh spec {sizes} needs {fixed} devices, have {n_devices}")
        return MeshSpec(**sizes)

    def axis_sizes(self) -> Dict[str, int]:
        return {AXIS_PP: self.pp, AXIS_DP: self.dp, AXIS_FSDP: self.fsdp,
                AXIS_SP: self.sp, AXIS_TP: self.tp, AXIS_EP: self.ep}

    def build(self, devices: Optional[Sequence] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        spec = self.resolve(len(devices))
        sizes = spec.axis_sizes()
        arr = np.array(devices).reshape([sizes[a] for a in ALL_AXES])
        return Mesh(arr, ALL_AXES)

    @property
    def data_shards(self) -> int:
        """Number of distinct data shards (global batch divisor)."""
        return max(1, self.dp) * max(1, self.fsdp)


def single_device_mesh() -> Mesh:
    return MeshSpec(dp=1, fsdp=1, sp=1, tp=1, ep=1, pp=1).build(
        jax.devices()[:1])


def mesh_shape(mesh: Mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for host->device batches: batch over dp+fsdp, seq over sp."""
    return NamedSharding(mesh, PartitionSpec(BATCH_AXES, AXIS_SP))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
