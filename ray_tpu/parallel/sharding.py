"""Logical-axis sharding rules: how tensors map onto the mesh.

This is the GSPMD-native equivalent of the reference's per-strategy code
paths (DDP wraps, FSDP wraps, vLLM TP placement — SURVEY.md §2.4): one rule
table assigns each *logical* tensor axis to mesh axes, and pjit/XLA derive
every collective from it. Changing parallelism = changing this table, not
the model.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .mesh import (AXIS_DP, AXIS_FSDP, AXIS_SP, AXIS_TP, AXIS_EP, AXIS_PP,
                   BATCH_AXES)

# Logical axis -> mesh axis (or tuple of mesh axes, or None = replicate).
# The default table implements DP+FSDP+TP+SP for transformer LMs:
#   - params: embed dim sharded over fsdp (ZeRO-3 style), heads/ffn over tp
#   - activations: batch over (dp, fsdp), sequence over sp
DEFAULT_RULES: Dict[str, Union[None, str, Tuple[str, ...]]] = {
    "batch": BATCH_AXES,
    "seq": AXIS_SP,
    "embed": AXIS_FSDP,
    "heads": AXIS_TP,
    "kv_heads": AXIS_TP,
    "head_dim": None,
    "mlp": AXIS_TP,
    "vocab": AXIS_TP,
    # Stacked layer dim sharded over pp: contiguous L/pp blocks land on
    # their pipeline stage, so stage params (and optimizer state) never
    # replicate across stages (models/pipeline.py).
    "layers": AXIS_PP,
    "experts": AXIS_EP,
    "act_embed": None,       # activation feature dim stays unsharded
}


def spec_for(logical_axes: Sequence[Optional[str]],
             rules: Optional[Dict] = None) -> PartitionSpec:
    rules = {**DEFAULT_RULES, **(rules or {})}
    parts = []
    used = set()
    for name in logical_axes:
        axis = rules.get(name) if name is not None else None
        # A mesh axis may appear only once in a PartitionSpec.
        if axis is not None:
            flat = (axis,) if isinstance(axis, str) else tuple(axis)
            if any(a in used for a in flat):
                axis = None
            else:
                used.update(flat)
        parts.append(axis)
    return PartitionSpec(*parts)


def named_sharding(mesh: Mesh, *logical_axes: Optional[str],
                   rules: Optional[Dict] = None) -> NamedSharding:
    return NamedSharding(mesh, spec_for(logical_axes, rules))


def with_logical_constraint(x, *logical_axes: Optional[str],
                            rules: Optional[Dict] = None):
    """Annotate an intermediate value inside jit with its logical sharding."""
    try:
        mesh = get_abstract_mesh_or_none()
        if mesh is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec_for(logical_axes, rules)))
    except Exception:
        return x


def get_abstract_mesh_or_none():
    """The mesh from the enclosing `jax.set_mesh` /
    `ops.jax_compat.set_mesh_compat` context, if any. On the 0.4.x
    line there is no abstract-mesh API; the ambient mesh lives in the
    thread-local resource env a `with mesh:` context installs, so the
    fallback reads it from there — without it every logical-axis
    constraint silently no-ops on 0.4.x (which is exactly how the
    training-path shardings regressed unnoticed)."""
    try:
        m = jax.sharding.get_abstract_mesh()
        if m is not None and not m.empty:
            return m
    except Exception:
        pass
    try:
        from jax._src import mesh as _mesh_lib
        m = _mesh_lib.thread_resources.env.physical_mesh
        if m is not None and not m.empty:
            return m
    except Exception:
        pass
    return None


def tree_shardings(tree_of_logical_axes: Any, mesh: Mesh,
                   rules: Optional[Dict] = None):
    """Map a pytree of logical-axis tuples to NamedShardings."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, spec_for(axes, rules)),
        tree_of_logical_axes,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x),
    )


def shard_tree(tree: Any, axes_tree: Any, mesh: Mesh,
               rules: Optional[Dict] = None):
    """Device_put a pytree according to its logical axes."""
    shardings = tree_shardings(axes_tree, mesh, rules)
    return jax.tree.map(jax.device_put, tree, shardings)
