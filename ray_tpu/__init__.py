"""ray_tpu: a TPU-native distributed AI framework.

Capabilities modeled on Ray (reference: bobbercheng/ray @ 2.44), rebuilt
TPU-first: tasks/actors/objects over an asyncio control plane, placement
groups with pod-slice gang scheduling, and ML libraries (train/tune/rl/
data/serve) whose compute path is JAX/XLA/Pallas over device meshes.
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, List, Optional, Sequence, Union

from ._private import state as _state
from ._private.object_ref import ObjectRef
from ._private.streaming import ObjectRefGenerator
from ._private.worker import (init, shutdown, current_runtime,
                              add_fake_node, remove_node)
from .actor import ActorClass, ActorHandle
from .remote_function import RemoteFunction
from . import exceptions

__version__ = "0.1.0"

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "get_actor", "aio_get_actor", "nodes", "cluster_resources",
    "available_resources", "ObjectRef", "ObjectRefGenerator",
    "ActorHandle", "exceptions",
    "get_runtime_context", "method",
]


def is_initialized() -> bool:
    return _state.is_initialized()


def remote(*args, **kwargs):
    """Decorator turning a function into a RemoteFunction or a class into
    an ActorClass. Usable bare (@remote) or with options
    (@remote(num_cpus=2, num_tpus=4))."""
    if len(args) == 1 and not kwargs and (inspect.isfunction(args[0])
                                          or inspect.isclass(args[0])):
        target = args[0]
        return ActorClass(target) if inspect.isclass(target) \
            else RemoteFunction(target)
    if args:
        raise TypeError("use @remote or @remote(**options)")

    def decorator(target):
        return ActorClass(target, kwargs) if inspect.isclass(target) \
            else RemoteFunction(target, kwargs)

    return decorator


def method(**kwargs):
    """Per-method options decorator (accepted for API parity)."""
    def decorator(fn):
        fn._method_options = kwargs
        return fn
    return decorator


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    return _state.current_client().get(refs, timeout=timeout)


def put(value: Any) -> ObjectRef:
    return _state.current_client().put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    return _state.current_client().wait(refs, num_returns=num_returns,
                                        timeout=timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    _state.current_client().kill_actor(actor._actor_id, no_restart=no_restart)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    info = _state.current_client().get_actor_handle_info(name, namespace)
    if info is None:
        raise ValueError(f"no actor named {name!r} found")
    return ActorHandle(info["actor_id"], name)


async def aio_get_actor(name: str,
                        namespace: Optional[str] = None) -> ActorHandle:
    """Async variant of get_actor for use inside async actors."""
    info = await _state.current_client().aio_get_actor_handle_info(
        name, namespace)
    if info is None:
        raise ValueError(f"no actor named {name!r} found")
    return ActorHandle(info["actor_id"], name)


def nodes() -> List[dict]:
    return _state.current_client().nodes()


def cluster_resources() -> Dict[str, float]:
    return _state.current_client().cluster_resources()


def available_resources() -> Dict[str, float]:
    return _state.current_client().available_resources()


class RuntimeContext:
    def __init__(self, client):
        self._client = client
        info = getattr(client, "runtime_context", None) or {}
        self.worker_id = info.get("worker_id")
        self.node_id = info.get("node_id")
        runtime = info.get("runtime")
        self.actor_id = getattr(runtime, "current_actor_id", None)

    def get_actor_id(self):
        return self.actor_id

    def get_node_id(self):
        return self.node_id

    def get_worker_id(self):
        return self.worker_id


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_state.current_client())
