"""Replay buffers for off-policy algorithms.

Reference parity: rllib/utils/replay_buffers/episode_replay_buffer.py —
simplified to a transition-level uniform ring buffer (numpy, preallocated
on first add) feeding DQN/SAC minibatches.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform-sampling ring buffer over transition dicts."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = int(capacity)
        self._store: Optional[Dict[str, np.ndarray]] = None
        self._size = 0
        self._cursor = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        """batch: dict of arrays with a shared leading dim N."""
        n = len(next(iter(batch.values())))
        if self._store is None:
            self._store = {
                k: np.empty((self.capacity,) + np.asarray(v).shape[1:],
                            dtype=np.asarray(v).dtype)
                for k, v in batch.items()}
        if n >= self.capacity:                 # keep only the newest
            for k, v in batch.items():
                self._store[k][:] = np.asarray(v)[-self.capacity:]
            self._size = self.capacity
            self._cursor = 0
            return
        end = self._cursor + n
        for k, v in batch.items():
            v = np.asarray(v)
            if end <= self.capacity:
                self._store[k][self._cursor:end] = v
            else:
                split = self.capacity - self._cursor
                self._store[k][self._cursor:] = v[:split]
                self._store[k][:end - self.capacity] = v[split:]
        self._cursor = end % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, n: int) -> Dict[str, np.ndarray]:
        if self._size == 0:
            raise ValueError("cannot sample from an empty replay buffer")
        idx = self._rng.integers(0, self._size, size=n)
        return {k: v[idx] for k, v in self._store.items()}
