"""Offline RL: train from recorded experience, no live environment.

Reference parity: rllib/offline/offline_data.py (dataset-backed input)
+ rllib/algorithms/bc (behavior cloning, the canonical offline baseline).
Experiences are .npz shards of flat transition arrays; `record_samples`
writes them from any on-policy rollout batch, `OfflineData` streams
minibatches from a directory of shards (or a ray_tpu.data Dataset).
"""

from __future__ import annotations

import glob
import os
from typing import Any, Dict, Iterator, List, Optional

import jax.numpy as jnp
import numpy as np

from ..algorithms.algorithm import Algorithm, AlgorithmConfig
from ..core.learner import Learner

__all__ = ["record_samples", "OfflineData", "BC", "BCConfig"]


def record_samples(batch: Dict[str, np.ndarray], out_dir: str,
                   shard_index: int = 0) -> str:
    """Write one rollout batch ([T, B, ...]) as a flat .npz shard.
    Per-rollout extras (final_obs/final_vf, shape [B]) are dropped —
    shards hold per-TRANSITION arrays with one shared leading dim."""
    os.makedirs(out_dir, exist_ok=True)
    t, b = np.asarray(batch["obs"]).shape[:2]
    flat = {}
    for k, v in batch.items():
        v = np.asarray(v)
        if v.ndim < 2 or v.shape[:2] != (t, b):
            continue
        flat[k] = v.reshape((t * b,) + v.shape[2:])
    path = os.path.join(out_dir, f"shard-{shard_index:05d}.npz")
    np.savez(path, **flat)
    return path


class OfflineData:
    """Minibatch source over .npz shards (reference: OfflineData)."""

    def __init__(self, input_path: str, seed: int = 0):
        paths = sorted(glob.glob(os.path.join(input_path, "*.npz"))) \
            if os.path.isdir(input_path) else [input_path]
        if not paths:
            raise ValueError(f"no .npz shards under {input_path!r}")
        arrays: Dict[str, List[np.ndarray]] = {}
        for p in paths:
            with np.load(p) as z:
                for k in z.files:
                    arrays.setdefault(k, []).append(z[k])
        self.data = {k: np.concatenate(v) for k, v in arrays.items()}
        self.size = len(next(iter(self.data.values())))
        self._rng = np.random.default_rng(seed)

    def sample(self, n: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self.size, size=n)
        return {k: v[idx] for k, v in self.data.items()}


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(BC)
        self.input_path: Optional[str] = None
        self.train_batch_size = 256
        self.num_updates_per_iter = 16

    def offline_data(self, *, input_path: str) -> "BCConfig":
        self.input_path = input_path
        return self


class BCLearner(Learner):
    """Maximize log-likelihood of the dataset's actions."""

    def compute_loss(self, params, mb):
        out = self.module.forward_train(params, mb["obs"])
        logp = self.module.dist.log_prob(
            out["action_dist_inputs"], mb["actions"])
        loss = -jnp.mean(logp)
        return loss, {"total_loss": loss, "bc_logp": jnp.mean(logp)}


class BC(Algorithm):
    @classmethod
    def default_config(cls) -> BCConfig:
        return BCConfig()

    @classmethod
    def build_learner(cls, spec, config) -> BCLearner:
        return BCLearner(spec, config.learner_hyperparams(),
                         config.module_class, config.model_config,
                         seed=config.seed)

    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)      # env used for spec + evaluation rollouts
        cfg = self._config
        if not getattr(cfg, "input_path", None):
            raise ValueError("BC requires .offline_data(input_path=...)")
        self.offline = OfflineData(cfg.input_path, seed=cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self._config
        learner_metrics: Dict[str, float] = {}
        for _ in range(cfg.num_updates_per_iter):
            learner_metrics = self.learner_group.update(
                self.offline.sample(cfg.train_batch_size))
        # evaluation rollout with the learned policy (also refreshes the
        # sampler weights so metrics reflect the current params)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        result = self.env_runner_group.sample()
        return self._roll_metrics(result["stats"], learner_metrics)
