"""Offline RL: train from recorded experience, no live environment.

Reference parity: rllib/offline/offline_data.py (dataset-backed input)
+ rllib/algorithms/bc (behavior cloning, the canonical offline baseline).
Experiences are .npz shards of flat transition arrays; `record_samples`
writes them from any on-policy rollout batch, `OfflineData` streams
minibatches from a directory of shards (or a ray_tpu.data Dataset).
"""

from __future__ import annotations

import glob
import os
from typing import Any, Dict, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..algorithms.algorithm import Algorithm, AlgorithmConfig
from ..core.learner import Learner

__all__ = ["record_samples", "OfflineData", "BC", "BCConfig",
           "MARWIL", "MARWILConfig"]


def record_samples(batch: Dict[str, np.ndarray], out_dir: str,
                   shard_index: int = 0,
                   gamma: Optional[float] = None) -> str:
    """Write one rollout batch ([T, B, ...]) as a flat .npz shard.
    Per-rollout extras (final_obs/final_vf, shape [B]) are dropped —
    shards hold per-TRANSITION arrays with one shared leading dim.

    With gamma set, per-transition discounted reward-to-go is computed
    while the [T, B] episode structure is still known (bootstrapped
    from final_vf when present) and stored as 'returns' — the input
    MARWIL's advantage weighting needs; flattened shards can't recover
    it."""
    os.makedirs(out_dir, exist_ok=True)
    t, b = np.asarray(batch["obs"]).shape[:2]
    flat = {}
    for k, v in batch.items():
        v = np.asarray(v)
        if v.ndim < 2 or v.shape[:2] != (t, b):
            continue
        flat[k] = v.reshape((t * b,) + v.shape[2:])
    if gamma is not None and "returns" not in flat:
        rew = np.asarray(batch["rewards"], np.float32)
        done = np.asarray(batch["dones"], np.float32)
        acc = np.asarray(batch.get("final_vf", np.zeros(b)), np.float32)
        rtg = np.zeros((t, b), np.float32)
        for i in range(t - 1, -1, -1):
            acc = rew[i] + gamma * (1.0 - done[i]) * acc
            rtg[i] = acc
        flat["returns"] = rtg.reshape(t * b)
    path = os.path.join(out_dir, f"shard-{shard_index:05d}.npz")
    np.savez(path, **flat)
    return path


class OfflineData:
    """Minibatch source over .npz shards (reference: OfflineData)."""

    def __init__(self, input_path: str, seed: int = 0):
        paths = sorted(glob.glob(os.path.join(input_path, "*.npz"))) \
            if os.path.isdir(input_path) else [input_path]
        if not paths:
            raise ValueError(f"no .npz shards under {input_path!r}")
        arrays: Dict[str, List[np.ndarray]] = {}
        for p in paths:
            with np.load(p) as z:
                for k in z.files:
                    arrays.setdefault(k, []).append(z[k])
        self.data = {k: np.concatenate(v) for k, v in arrays.items()}
        self.size = len(next(iter(self.data.values())))
        ragged = {k: len(v) for k, v in self.data.items()
                  if len(v) != self.size}
        if ragged:
            # e.g. a directory mixing shards recorded with and without
            # gamma= (only some carry 'returns') — fail loudly here, not
            # with a sporadic IndexError mid-training
            raise ValueError(
                f"shard keys have inconsistent row counts: {ragged} vs "
                f"{self.size}; were some shards recorded with different "
                "keys (e.g. only some with gamma=)?")
        self._rng = np.random.default_rng(seed)

    def sample(self, n: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self.size, size=n)
        return {k: v[idx] for k, v in self.data.items()}


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(BC)
        self.input_path: Optional[str] = None
        self.train_batch_size = 256
        self.num_updates_per_iter = 16

    def offline_data(self, *, input_path: str) -> "BCConfig":
        self.input_path = input_path
        return self


class BCLearner(Learner):
    """Maximize log-likelihood of the dataset's actions."""

    def compute_loss(self, params, mb):
        out = self.module.forward_train(params, mb["obs"])
        logp = self.module.dist.log_prob(
            out["action_dist_inputs"], mb["actions"])
        loss = -jnp.mean(logp)
        return loss, {"total_loss": loss, "bc_logp": jnp.mean(logp)}


class BC(Algorithm):
    @classmethod
    def default_config(cls) -> BCConfig:
        return BCConfig()

    @classmethod
    def build_learner(cls, spec, config) -> BCLearner:
        return BCLearner(spec, config.learner_hyperparams(),
                         config.module_class, config.model_config,
                         seed=config.seed)

    def setup(self, config: Dict[str, Any]) -> None:
        pre = config.get("_algo_config")
        if pre is not None and getattr(pre, "framestack", 1) > 1 or \
                config.get("framestack", 1) > 1:
            raise ValueError(
                "framestack is not supported by offline algorithms: "
                "recorded datasets carry single-frame observations, "
                "which would mismatch a stacked learner module")
        super().setup(config)      # env used for spec + evaluation rollouts
        cfg = self._config
        if not getattr(cfg, "input_path", None):
            raise ValueError("BC requires .offline_data(input_path=...)")
        self.offline = OfflineData(cfg.input_path, seed=cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self._config
        learner_metrics: Dict[str, float] = {}
        for _ in range(cfg.num_updates_per_iter):
            learner_metrics = self.learner_group.update(
                self.offline.sample(cfg.train_batch_size))
        # evaluation rollout with the learned policy (also refreshes the
        # sampler weights so metrics reflect the current params)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        result = self.env_runner_group.sample()
        return self._roll_metrics(result["stats"], learner_metrics)


class MARWILConfig(BCConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = MARWIL
        self.beta = 1.0               # 0.0 degenerates to plain BC
        self.vf_coeff = 1.0
        self.moving_average_sqd_adv_norm_update_rate = 1e-7  # ref default-ish


class MARWILLearner(Learner):
    """Advantage-weighted behavior cloning (Wang et al. 2018; reference:
    rllib/algorithms/marwil) — maximize exp(beta * A / c) * logp, where
    A = returns - V(s) and c is a running norm of A^2, plus a value
    loss fitting V to the recorded returns. beta=0 is exactly BC."""

    def __init__(self, spec, config: "MARWILConfig"):
        self._beta = config.beta
        self._vf_coeff = config.vf_coeff
        self._ma_rate = config.moving_average_sqd_adv_norm_update_rate
        super().__init__(spec, config.learner_hyperparams(),
                         config.module_class, config.model_config,
                         seed=config.seed)
        # running estimate of E[A^2]; lives in learner state like SAC's
        # target params (single-learner algorithm)
        self.ma_sqd_adv = jnp.asarray(1.0, jnp.float32)

    def compute_loss(self, params, mb):
        out = self.module.forward_train(params, mb["obs"])
        logp = self.module.dist.log_prob(
            out["action_dist_inputs"], mb["actions"])
        returns = mb["returns"]
        vf_loss = jnp.mean((out["vf"] - returns) ** 2)
        adv = jax.lax.stop_gradient(returns - out["vf"])
        if self._beta > 0.0:
            # the running norm rides in as a batch operand — a closure
            # read of self.ma_sqd_adv would be baked as a constant at
            # first jit trace and never see later updates
            c = jnp.sqrt(mb["_ma_sqd_adv"][0]) + 1e-8
            weights = jnp.minimum(jnp.exp(self._beta * adv / c), 20.0)
        else:
            weights = jnp.ones_like(adv)
        policy_loss = -jnp.mean(weights * logp)
        loss = policy_loss + self._vf_coeff * vf_loss
        return loss, {"total_loss": loss, "policy_loss": policy_loss,
                      "vf_loss": vf_loss,
                      "mean_weight": jnp.mean(weights),
                      "sqd_adv": jnp.mean(adv ** 2)}

    def update(self, train_batch):
        if self._beta > 0.0:
            n = len(next(iter(train_batch.values())))
            train_batch = dict(train_batch)
            train_batch["_ma_sqd_adv"] = np.full(
                n, float(self.ma_sqd_adv), np.float32)
        metrics = super().update(train_batch)
        if self._beta > 0.0 and "sqd_adv" in metrics:
            # fold the batch's observed E[A^2] into the running norm
            # (reference: marwil update_averaged_weights)
            n = len(next(iter(train_batch.values())))
            rate = min(self._ma_rate * n, 1.0)
            self.ma_sqd_adv = jnp.asarray(
                (1.0 - rate) * float(self.ma_sqd_adv)
                + rate * float(metrics["sqd_adv"]), jnp.float32)
        return metrics

    def get_state(self):
        state = super().get_state()
        state["ma_sqd_adv"] = float(self.ma_sqd_adv)
        return state

    def set_state(self, state) -> None:
        super().set_state(state)
        if "ma_sqd_adv" in state:
            self.ma_sqd_adv = jnp.asarray(state["ma_sqd_adv"],
                                          jnp.float32)


class MARWIL(BC):
    @classmethod
    def default_config(cls) -> MARWILConfig:
        return MARWILConfig()

    @classmethod
    def build_learner(cls, spec, config) -> MARWILLearner:
        return MARWILLearner(spec, config)

    def setup(self, config: Dict[str, Any]) -> None:
        algo_cfg = config.get("_algo_config")
        if algo_cfg is not None and algo_cfg.num_learners > 1:
            raise ValueError(
                "MARWIL supports num_learners <= 1 (the advantage-norm "
                "moving average lives in learner state, outside the "
                "generic allreduce path)")
        super().setup(config)
        if "returns" not in self.offline.data:
            raise ValueError(
                "MARWIL shards need 'returns' — record with "
                "record_samples(..., gamma=...) so reward-to-go is "
                "computed while episode structure is known")
