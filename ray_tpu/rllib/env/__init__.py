from .jax_env import (CartPole, CatchPixels, EnvSpec, JaxEnv, Pendulum,
                      make_env, register_env)
from .env_runner import SingleAgentEnvRunner
from .env_runner_group import EnvRunnerGroup
