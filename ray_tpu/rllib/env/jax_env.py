"""Pure-functional JAX environments.

The reference's RLlib samples with gymnasium vector envs on CPU
(rllib/env/single_agent_env_runner.py:140 in the reference tree). The
TPU-native inversion: environments are pure functions of (state, action)
so rollouts compile into the same XLA program as the policy —
`vmap` for batching, `lax.scan` for time — and the whole sample step is
ONE device call instead of a per-step host loop.

Env protocol (gymnax-style):
  reset(key)        -> (state, obs)
  step(state, action, key) -> (state, obs, reward, done)

States are pytrees of arrays; everything static-shaped for XLA.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class EnvSpec:
    """Static description the module/connectors need."""
    obs_dim: int
    num_actions: int          # >0 -> discrete; 0 -> continuous
    action_dim: int = 0       # for continuous envs
    max_episode_steps: int = 500
    # pixel envs: the (H, W, C) the flat obs vector reshapes to — lets
    # conv modules recover the image without a side channel
    obs_shape: Tuple[int, ...] = ()

    @property
    def discrete(self) -> bool:
        return self.num_actions > 0


def stacked_spec(spec: "EnvSpec", framestack: int) -> "EnvSpec":
    """The spec a module sees under feature-wise frame stacking — ONE
    definition used by both the runner and the learner builder, so
    their module widths can never desynchronize."""
    if framestack <= 1:
        return spec
    return dataclasses.replace(spec, obs_dim=spec.obs_dim * framestack)


class JaxEnv:
    """Base class; subclasses are stateless — all state is in the pytree."""

    spec: EnvSpec

    def reset(self, key) -> Tuple[Any, jnp.ndarray]:
        raise NotImplementedError

    def step(self, state, action, key):
        raise NotImplementedError


class CartPole(JaxEnv):
    """Classic cart-pole balance, standard physics (Barto et al.).

    Matches gymnasium CartPole-v1 dynamics: force ±10 N, tau=0.02 s,
    terminate at |x|>2.4 or |theta|>12 deg, reward 1 per step.
    """

    def __init__(self, max_episode_steps: int = 500):
        self.spec = EnvSpec(obs_dim=4, num_actions=2,
                            max_episode_steps=max_episode_steps)

    def reset(self, key):
        state = jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)
        return (state, jnp.zeros((), jnp.int32)), state

    def step(self, state, action, key):
        del key
        s, t = state
        x, x_dot, theta, theta_dot = s[0], s[1], s[2], s[3]
        force = jnp.where(action == 1, 10.0, -10.0)
        costh, sinth = jnp.cos(theta), jnp.sin(theta)
        total_mass, polemass_length, length = 1.1, 0.05, 0.5
        temp = (force + polemass_length * theta_dot ** 2 * sinth) / total_mass
        theta_acc = (9.8 * sinth - costh * temp) / (
            length * (4.0 / 3.0 - 0.1 * costh ** 2 / total_mass))
        x_acc = temp - polemass_length * theta_acc * costh / total_mass
        tau = 0.02
        s2 = jnp.stack([x + tau * x_dot, x_dot + tau * x_acc,
                        theta + tau * theta_dot, theta_dot + tau * theta_acc])
        t2 = t + 1
        terminated = (jnp.abs(s2[0]) > 2.4) | (jnp.abs(s2[2]) > 0.2095)
        truncated = t2 >= self.spec.max_episode_steps
        done = terminated | truncated
        return (s2, t2), s2, jnp.float32(1.0), done


class Pendulum(JaxEnv):
    """Torque-controlled pendulum swing-up (continuous actions)."""

    def __init__(self, max_episode_steps: int = 200):
        self.spec = EnvSpec(obs_dim=3, num_actions=0, action_dim=1,
                            max_episode_steps=max_episode_steps)

    def reset(self, key):
        k1, k2 = jax.random.split(key)
        theta = jax.random.uniform(k1, (), minval=-np.pi, maxval=np.pi)
        theta_dot = jax.random.uniform(k2, (), minval=-1.0, maxval=1.0)
        state = (jnp.stack([theta, theta_dot]), jnp.zeros((), jnp.int32))
        return state, self._obs(state[0])

    @staticmethod
    def _obs(s):
        return jnp.stack([jnp.cos(s[0]), jnp.sin(s[0]), s[1]])

    def step(self, state, action, key):
        del key
        s, t = state
        theta, theta_dot = s[0], s[1]
        u = jnp.clip(action[0], -2.0, 2.0)
        norm_th = ((theta + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th ** 2 + 0.1 * theta_dot ** 2 + 0.001 * u ** 2
        theta_dot2 = jnp.clip(
            theta_dot + (3 * 9.81 / 2 * jnp.sin(theta) + 3.0 * u) * 0.05,
            -8.0, 8.0)
        theta2 = theta + theta_dot2 * 0.05
        t2 = t + 1
        done = t2 >= self.spec.max_episode_steps
        s2 = jnp.stack([theta2, theta_dot2])
        return (s2, t2), self._obs(s2), -cost, done


class CatchPixels(JaxEnv):
    """Pixel-observation catch game — the in-image-budget stand-in for
    the reference's Atari PPO learning regression
    (rllib/benchmarks/ppo/benchmark_atari_ppo.py commits reward
    targets; ale-py is not in this image). A ball falls one row per
    step on a HxW grid; a 3-px paddle on the bottom row moves
    left/stay/right; catching scores +1, missing -1, ball respawns.
    Observations are the raw pixels (ball 1.0, paddle 0.5) flattened —
    solvable only by reading the image, which is the point: it gates
    the CNN module + frame pipeline end to end.

    Random play expects about -4 per 8-drop episode; the committed
    regression target is +4 (>=75% catch rate)."""

    H, W = 10, 12
    PAD = 1            # paddle half-width

    def __init__(self, max_episode_steps: int = 80):
        self.spec = EnvSpec(obs_dim=self.H * self.W, num_actions=3,
                            max_episode_steps=max_episode_steps,
                            obs_shape=(self.H, self.W, 1))

    def _render(self, ball_r, ball_c, pad_c):
        grid = jnp.zeros((self.H, self.W), jnp.float32)
        grid = grid.at[ball_r, ball_c].set(1.0)
        cols = jnp.clip(pad_c + jnp.arange(-self.PAD, self.PAD + 1),
                        0, self.W - 1)
        grid = grid.at[self.H - 1, cols].add(0.5)
        return jnp.clip(grid, 0.0, 1.0).reshape(-1)

    def reset(self, key):
        k1, k2 = jax.random.split(key)
        ball_c = jax.random.randint(k1, (), 0, self.W)
        pad_c = jax.random.randint(k2, (), self.PAD, self.W - self.PAD)
        state = (jnp.zeros((), jnp.int32), ball_c, pad_c,
                 jnp.zeros((), jnp.int32))
        return state, self._render(state[0], ball_c, pad_c)

    def step(self, state, action, key):
        ball_r, ball_c, pad_c, t = state
        pad_c = jnp.clip(pad_c + (action - 1), self.PAD,
                         self.W - 1 - self.PAD)
        ball_r = ball_r + 1
        at_bottom = ball_r >= self.H - 1
        caught = jnp.abs(ball_c - pad_c) <= self.PAD
        reward = jnp.where(at_bottom,
                           jnp.where(caught, 1.0, -1.0), 0.0)
        new_c = jax.random.randint(key, (), 0, self.W)
        ball_r = jnp.where(at_bottom, 0, ball_r)
        ball_c = jnp.where(at_bottom, new_c, ball_c)
        t2 = t + 1
        done = t2 >= self.spec.max_episode_steps
        s2 = (ball_r, ball_c, pad_c, t2)
        return s2, self._render(ball_r, ball_c, pad_c), reward, done


_ENV_REGISTRY: Dict[str, Callable[[], JaxEnv]] = {
    "CartPole-v1": CartPole,
    "CartPole": CartPole,
    "Pendulum-v1": Pendulum,
    "Pendulum": Pendulum,
    "CatchPixels-v0": CatchPixels,
    "CatchPixels": CatchPixels,
}


def register_env(name: str, factory: Callable[[], JaxEnv]) -> None:
    """Register a user env factory (reference: ray.tune.register_env)."""
    _ENV_REGISTRY[name] = factory


def make_env(name_or_env) -> JaxEnv:
    if isinstance(name_or_env, JaxEnv):
        return name_or_env
    if isinstance(name_or_env, str):
        if name_or_env not in _ENV_REGISTRY:
            raise ValueError(
                f"unknown env {name_or_env!r}; registered: "
                f"{sorted(_ENV_REGISTRY)}")
        return _ENV_REGISTRY[name_or_env]()
    if callable(name_or_env):
        return name_or_env()
    raise TypeError(f"cannot build env from {name_or_env!r}")
