"""Pure-functional JAX environments.

The reference's RLlib samples with gymnasium vector envs on CPU
(rllib/env/single_agent_env_runner.py:140 in the reference tree). The
TPU-native inversion: environments are pure functions of (state, action)
so rollouts compile into the same XLA program as the policy —
`vmap` for batching, `lax.scan` for time — and the whole sample step is
ONE device call instead of a per-step host loop.

Env protocol (gymnax-style):
  reset(key)        -> (state, obs)
  step(state, action, key) -> (state, obs, reward, done)

States are pytrees of arrays; everything static-shaped for XLA.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class EnvSpec:
    """Static description the module/connectors need."""
    obs_dim: int
    num_actions: int          # >0 -> discrete; 0 -> continuous
    action_dim: int = 0       # for continuous envs
    max_episode_steps: int = 500

    @property
    def discrete(self) -> bool:
        return self.num_actions > 0


def stacked_spec(spec: "EnvSpec", framestack: int) -> "EnvSpec":
    """The spec a module sees under feature-wise frame stacking — ONE
    definition used by both the runner and the learner builder, so
    their module widths can never desynchronize."""
    if framestack <= 1:
        return spec
    return dataclasses.replace(spec, obs_dim=spec.obs_dim * framestack)


class JaxEnv:
    """Base class; subclasses are stateless — all state is in the pytree."""

    spec: EnvSpec

    def reset(self, key) -> Tuple[Any, jnp.ndarray]:
        raise NotImplementedError

    def step(self, state, action, key):
        raise NotImplementedError


class CartPole(JaxEnv):
    """Classic cart-pole balance, standard physics (Barto et al.).

    Matches gymnasium CartPole-v1 dynamics: force ±10 N, tau=0.02 s,
    terminate at |x|>2.4 or |theta|>12 deg, reward 1 per step.
    """

    def __init__(self, max_episode_steps: int = 500):
        self.spec = EnvSpec(obs_dim=4, num_actions=2,
                            max_episode_steps=max_episode_steps)

    def reset(self, key):
        state = jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)
        return (state, jnp.zeros((), jnp.int32)), state

    def step(self, state, action, key):
        del key
        s, t = state
        x, x_dot, theta, theta_dot = s[0], s[1], s[2], s[3]
        force = jnp.where(action == 1, 10.0, -10.0)
        costh, sinth = jnp.cos(theta), jnp.sin(theta)
        total_mass, polemass_length, length = 1.1, 0.05, 0.5
        temp = (force + polemass_length * theta_dot ** 2 * sinth) / total_mass
        theta_acc = (9.8 * sinth - costh * temp) / (
            length * (4.0 / 3.0 - 0.1 * costh ** 2 / total_mass))
        x_acc = temp - polemass_length * theta_acc * costh / total_mass
        tau = 0.02
        s2 = jnp.stack([x + tau * x_dot, x_dot + tau * x_acc,
                        theta + tau * theta_dot, theta_dot + tau * theta_acc])
        t2 = t + 1
        terminated = (jnp.abs(s2[0]) > 2.4) | (jnp.abs(s2[2]) > 0.2095)
        truncated = t2 >= self.spec.max_episode_steps
        done = terminated | truncated
        return (s2, t2), s2, jnp.float32(1.0), done


class Pendulum(JaxEnv):
    """Torque-controlled pendulum swing-up (continuous actions)."""

    def __init__(self, max_episode_steps: int = 200):
        self.spec = EnvSpec(obs_dim=3, num_actions=0, action_dim=1,
                            max_episode_steps=max_episode_steps)

    def reset(self, key):
        k1, k2 = jax.random.split(key)
        theta = jax.random.uniform(k1, (), minval=-np.pi, maxval=np.pi)
        theta_dot = jax.random.uniform(k2, (), minval=-1.0, maxval=1.0)
        state = (jnp.stack([theta, theta_dot]), jnp.zeros((), jnp.int32))
        return state, self._obs(state[0])

    @staticmethod
    def _obs(s):
        return jnp.stack([jnp.cos(s[0]), jnp.sin(s[0]), s[1]])

    def step(self, state, action, key):
        del key
        s, t = state
        theta, theta_dot = s[0], s[1]
        u = jnp.clip(action[0], -2.0, 2.0)
        norm_th = ((theta + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th ** 2 + 0.1 * theta_dot ** 2 + 0.001 * u ** 2
        theta_dot2 = jnp.clip(
            theta_dot + (3 * 9.81 / 2 * jnp.sin(theta) + 3.0 * u) * 0.05,
            -8.0, 8.0)
        theta2 = theta + theta_dot2 * 0.05
        t2 = t + 1
        done = t2 >= self.spec.max_episode_steps
        s2 = jnp.stack([theta2, theta_dot2])
        return (s2, t2), self._obs(s2), -cost, done


_ENV_REGISTRY: Dict[str, Callable[[], JaxEnv]] = {
    "CartPole-v1": CartPole,
    "CartPole": CartPole,
    "Pendulum-v1": Pendulum,
    "Pendulum": Pendulum,
}


def register_env(name: str, factory: Callable[[], JaxEnv]) -> None:
    """Register a user env factory (reference: ray.tune.register_env)."""
    _ENV_REGISTRY[name] = factory


def make_env(name_or_env) -> JaxEnv:
    if isinstance(name_or_env, JaxEnv):
        return name_or_env
    if isinstance(name_or_env, str):
        if name_or_env not in _ENV_REGISTRY:
            raise ValueError(
                f"unknown env {name_or_env!r}; registered: "
                f"{sorted(_ENV_REGISTRY)}")
        return _ENV_REGISTRY[name_or_env]()
    if callable(name_or_env):
        return name_or_env()
    raise TypeError(f"cannot build env from {name_or_env!r}")
