"""EnvRunnerGroup: local or remote fleet of EnvRunners.

Reference parity: rllib/env/env_runner_group.py:71 and the synchronous
sampling helper rllib/execution/rollout_ops.py:20. With num_env_runners=0
sampling happens in-process (the reference's local-worker path); otherwise
N ray_tpu actors sample in parallel and the group gathers batches.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

from .env_runner import SingleAgentEnvRunner


def _merge_batches(results: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Concatenate [T, B, ...] batches along the env axis; average stats."""
    batches = [r["batch"] for r in results]
    merged = {}
    for k in batches[0]:
        axis = 0 if k in ("final_vf", "final_obs") else 1
        merged[k] = np.concatenate([b[k] for b in batches], axis=axis)
    n_eps = sum(r["stats"]["num_episodes"] for r in results)
    ret_sum = sum(r["stats"]["episode_return_mean"]
                  * r["stats"]["num_episodes"] for r in results)
    len_sum = sum(r["stats"]["episode_len_mean"]
                  * r["stats"]["num_episodes"] for r in results)
    stats = {
        "num_episodes": n_eps,
        "episode_return_mean": ret_sum / max(n_eps, 1),
        "episode_len_mean": len_sum / max(n_eps, 1),
        "env_steps": sum(r["stats"]["env_steps"] for r in results),
    }
    return {"batch": merged, "stats": stats}


class EnvRunnerGroup:
    def __init__(self, env, num_env_runners: int = 0, num_envs_per_runner:
                 int = 8, rollout_length: int = 128, seed: int = 0,
                 module_class: Optional[type] = None,
                 model_config: Optional[Dict[str, Any]] = None,
                 runner_resources: Optional[Dict[str, float]] = None,
                 obs_filter: Optional[str] = None,
                 framestack: int = 1):
        self.num_env_runners = num_env_runners
        self.obs_filter = obs_filter
        self._filter_global = None      # merged cross-runner state
        self._inflight: Dict[Any, Any] = {}   # sample ref -> runner
        if num_env_runners == 0:
            self._local = SingleAgentEnvRunner(
                env, num_envs_per_runner, rollout_length, seed,
                module_class, model_config, obs_filter=obs_filter,
                framestack=framestack)
            self._remote = []
        else:
            self._local = None
            remote_cls = ray_tpu.remote(
                **(runner_resources or {"num_cpus": 1}))(SingleAgentEnvRunner)
            self._remote = [
                remote_cls.remote(env, num_envs_per_runner, rollout_length,
                                  seed + 1000 * (i + 1), module_class,
                                  model_config, obs_filter=obs_filter,
                                  framestack=framestack)
                for i in range(num_env_runners)]
            ray_tpu.get([r.ping.remote() for r in self._remote])

    def sample(self) -> Dict[str, Any]:
        """Synchronous parallel sample across all runners."""
        if self._local is not None:
            return self._local.sample()
        return _merge_batches(
            ray_tpu.get([r.sample.remote() for r in self._remote]))

    def sample_async_next(self, weights) -> Dict[str, Any]:
        """IMPALA's async path: keep one in-flight sample per remote
        runner, return whichever lands first, and re-arm that runner with
        the given (fresh) weights so sampling overlaps learning. Local
        mode degrades to sync sample with a weight sync."""
        if self._local is not None:
            self._local.set_weights(weights)
            return self._local.sample()
        if not self._inflight:
            for r in self._remote:
                self._inflight[r.sample.remote()] = r
        ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1)
        runner = self._inflight.pop(ready[0])
        result = ray_tpu.get(ready[0])
        ref = ray_tpu.put(weights)
        runner.set_weights.remote(ref)
        if self.obs_filter:
            # per-runner filter sync on the async cadence: fold THIS
            # runner's delta into the global state and hand the merged
            # state back before re-arming (the sync sync_weights path
            # never runs under IMPALA/APPO)
            from .env_runner import merge_moments
            d = ray_tpu.get(runner.get_filter_delta.remote())
            if d is not None:
                self._filter_global = (
                    d if self._filter_global is None
                    else merge_moments(self._filter_global, d))
                runner.set_filter_state.remote(self._filter_global)
        self._inflight[runner.sample.remote()] = runner
        return result

    def sync_weights(self, params) -> None:
        if self._local is not None:
            self._local.set_weights(params)
        else:
            # one put, fanned out by reference — the object store dedups
            ref = ray_tpu.put(params)
            ray_tpu.get([r.set_weights.remote(ref) for r in self._remote])
            # filter state rides the weight-sync cadence (reference
            # parity: connector states synchronize with weights)
            self.sync_filters()

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        return ray_tpu.get(self._remote[0].get_weights.remote())

    def sync_filters(self) -> None:
        """Merge every runner's since-last-sync filter DELTA into the
        group-held global state and push that back (reference parity:
        the filter-synchronization step of RLlib's connector pipelines).
        Deltas, not full states: re-merging full states would count the
        shared history once per runner per sync, growing the count
        ~R^k and freezing the stats."""
        if not self.obs_filter or self._local is not None:
            return
        deltas = [d for d in ray_tpu.get(
            [r.get_filter_delta.remote() for r in self._remote])
            if d is not None]
        from .env_runner import merge_moments
        for d in deltas:
            self._filter_global = (
                d if self._filter_global is None
                else merge_moments(self._filter_global, d))
        if self._filter_global is not None:
            ray_tpu.get([r.set_filter_state.remote(self._filter_global)
                         for r in self._remote])

    def get_filter_state(self):
        """Checkpointable filter state (a restored policy must see obs
        normalized by the stats it was trained against)."""
        if not self.obs_filter:
            return None
        if self._local is not None:
            return self._local.get_filter_state()
        self.sync_filters()
        return self._filter_global

    def set_filter_state(self, state) -> None:
        if not self.obs_filter or state is None:
            return
        if self._local is not None:
            self._local.set_filter_state(state)
            return
        self._filter_global = state
        ray_tpu.get([r.set_filter_state.remote(state)
                     for r in self._remote])
        # runner deltas predate the restored state: drop them
        ray_tpu.get([r.get_filter_delta.remote() for r in self._remote])

    @property
    def module(self):
        if self._local is not None:
            return self._local.module
        return None

    def stop(self) -> None:
        for r in self._remote:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
