"""EnvRunner: samples experience with a compiled rollout.

Reference parity: rllib/env/single_agent_env_runner.py:140 (sample loop
over gymnasium vector envs) and env_runner_group.py:71. TPU-native
inversion: the env is pure JAX (jax_env.py), so the WHOLE rollout —
policy forward, env physics, auto-reset, episode bookkeeping — is one
`lax.scan` under jit: a single device call per sample() instead of a
Python loop with T host↔device round-trips.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .jax_env import JaxEnv, make_env
from ..core.rl_module import RLModule, build_module


def merge_moments(a, b):
    """Chan parallel-Welford combine of two (count, mean, M2) states —
    the ONE implementation shared by the runner's per-batch merge and
    the group's cross-runner merge (numerically delicate; keep single)."""
    ca, ma, sa = a
    cb, mb, sb = b
    if cb <= 0:
        return a
    if ca <= 0:
        return b
    delta = mb - ma
    tot = ca + cb
    mean = ma + delta * (cb / tot)
    m2 = sa + sb + delta * delta * (ca * cb / tot)
    return (tot, mean, m2)


class SingleAgentEnvRunner:
    """Owns a vectorized env + module params; sample() returns a batch of
    shape [T, B, ...] plus episode stats. Runs as a plain object in-driver
    or as a ray_tpu actor in an EnvRunnerGroup."""

    def __init__(self, env, num_envs: int = 8, rollout_length: int = 128,
                 seed: int = 0, module_class: Optional[type] = None,
                 model_config: Optional[Dict[str, Any]] = None,
                 obs_filter: Optional[str] = None,
                 framestack: int = 1):
        self.env: JaxEnv = make_env(env)
        self.num_envs = num_envs
        self.rollout_length = rollout_length
        # env->module frame stacking (reference parity: rllib/connectors
        # env_to_module frame-stacking): the module sees the last N
        # frames concatenated feature-wise; the rolling buffer lives in
        # the compiled rollout's carry and refills with the reset obs
        # when an episode ends.
        self.framestack = int(framestack)
        if self.framestack < 1:
            raise ValueError("framestack must be >= 1")
        from .jax_env import stacked_spec
        self.module: RLModule = build_module(
            stacked_spec(self.env.spec, self.framestack),
            module_class, model_config)
        self._key = jax.random.PRNGKey(seed)
        self._key, init_key, reset_key = jax.random.split(self._key, 3)
        self.params = self.module.init(init_key)
        self._env_state, self._obs = jax.vmap(self.env.reset)(
            jax.random.split(reset_key, num_envs))
        if self.framestack > 1:
            self._stack = jnp.repeat(self._obs[:, None],
                                     self.framestack, axis=1)
        # env->module mean-std observation filter (reference parity:
        # rllib/connectors/env_to_module/mean_std_filter.py). The
        # normalization runs INSIDE the compiled rollout ((obs-mean)/std
        # clipped to ±10, applied before the policy and recorded as the
        # batch's obs); raw-obs sum/sumsq accumulate in-scan (no [T,B]
        # raw transfer) and fold into the running Welford state
        # host-side after each sample(). A DELTA buffer accumulates in
        # parallel so the group's cross-runner sync merges only what is
        # new since the last sync — re-merging full states would
        # double-count history and freeze the stats.
        if obs_filter not in (None, "mean_std"):
            raise ValueError(f"unknown obs_filter {obs_filter!r}")
        self.obs_filter = obs_filter
        if obs_filter:
            shape = tuple(np.asarray(self._obs).shape[1:])
            self._filt_state = (0.0, np.zeros(shape, np.float64),
                                np.zeros(shape, np.float64))
            self._filt_delta = (0.0, np.zeros(shape, np.float64),
                                np.zeros(shape, np.float64))
        self._sample_jit = jax.jit(self._build_sample())

    # -- compiled rollout ---------------------------------------------------
    def _build_sample(self):
        env, module = self.env, self.module
        B, T = self.num_envs, self.rollout_length
        use_filter = self.obs_filter is not None
        N = self.framestack
        use_stack = N > 1

        def filt(x, fmean, fstd):
            # broadcasts over a (B, D) obs or a (B, N, D) stack
            return (jnp.clip((x - fmean) / fstd, -10.0, 10.0)
                    if use_filter else x)

        recurrent = hasattr(module, "initial_state")

        def one_step(carry, step_key):
            (env_state, obs, stack, ep_ret, ep_len, params,
             fmean, fstd, fsum_in, fsq_in, mstate) = carry
            act_key, step_keys, reset_keys = (
                step_key[0], step_key[1], step_key[2])
            if use_stack:
                net_in = filt(stack, fmean, fstd).reshape(B, -1)
            else:
                net_in = filt(obs, fmean, fstd)
            if recurrent:
                # recurrent policies (world models) thread their state
                # through the scan; done envs reset it below
                action, logp, vf, mstate = module.forward_exploration(
                    params, net_in, act_key, mstate)
            else:
                action, logp, vf = module.forward_exploration(
                    params, net_in, act_key)
            next_state, next_obs, reward, done = jax.vmap(env.step)(
                env_state, action, jax.random.split(step_keys, B))
            ep_ret = ep_ret + reward
            ep_len = ep_len + 1
            # auto-reset finished envs (fresh state, keep static shapes)
            reset_state, reset_obs = jax.vmap(env.reset)(
                jax.random.split(reset_keys, B))
            sel = lambda a, b: jnp.where(
                jnp.reshape(done, (B,) + (1,) * (a.ndim - 1)), a, b)
            next_state = jax.tree_util.tree_map(sel, reset_state, next_state)
            next_obs = sel(reset_obs, next_obs)
            if use_stack:
                # slide the window; a finished episode refills the
                # whole buffer with its fresh reset obs
                rolled = jnp.concatenate(
                    [stack[:, 1:], next_obs[:, None]], axis=1)
                next_stack = sel(
                    jnp.repeat(next_obs[:, None], N, axis=1), rolled)
            else:
                next_stack = stack
            out = dict(obs=net_in, actions=action, logp=logp, vf=vf,
                       rewards=reward, dones=done,
                       finished_return=jnp.where(done, ep_ret, 0.0),
                       finished_len=jnp.where(done, ep_len, 0))
            ep_ret = jnp.where(done, 0.0, ep_ret)
            ep_len = jnp.where(done, 0, ep_len)
            if use_filter:
                # raw-obs moments accumulate in the carry: only two
                # obs-shaped arrays leave the device, not [T,B,obs]
                fsum = fsum_in + obs.sum(axis=0)
                fsq = fsq_in + (obs * obs).sum(axis=0)
            else:
                fsum, fsq = fsum_in, fsq_in
            if recurrent:
                fresh = module.initial_state(params, B)
                mstate = jax.tree_util.tree_map(
                    lambda f, s: jnp.where(
                        jnp.reshape(done, (B,) + (1,) * (s.ndim - 1)),
                        f, s), fresh, mstate)
            return (next_state, next_obs, next_stack, ep_ret, ep_len,
                    params, fmean, fstd, fsum, fsq, mstate), out

        def sample(params, env_state, obs, stack, ep_ret, ep_len, key,
                   fmean, fstd, mstate):
            key, sub = jax.random.split(key)
            step_keys = jax.random.split(sub, T * 3).reshape(T, 3, 2)
            zeros = jnp.zeros(obs.shape[1:], jnp.float32)
            carry, batch = jax.lax.scan(
                one_step, (env_state, obs, stack, ep_ret, ep_len,
                           params, fmean, fstd, zeros, zeros, mstate),
                step_keys)
            env_state, obs, stack, ep_ret, ep_len = carry[:5]
            mstate = carry[10]
            batch["filt_sum"], batch["filt_sumsq"] = carry[8], carry[9]
            if use_stack:
                ffinal = filt(stack, fmean, fstd).reshape(B, -1)
            else:
                ffinal = filt(obs, fmean, fstd)
            final_out = module.forward_train(params, ffinal)
            batch["final_vf"] = final_out["vf"]
            # the observation after the last step — off-policy algorithms
            # reconstruct next_obs[t] as obs[t+1] (+ this for t = T-1);
            # filtered/stacked like every obs the learner sees
            batch["final_obs"] = ffinal
            return (env_state, obs, stack, ep_ret, ep_len, key, batch,
                    mstate)

        return sample

    # -- public API ---------------------------------------------------------
    def _filter_std(self) -> np.ndarray:
        count, _, m2 = self._filt_state
        if count < 1.0:
            # no data yet: identity scaling, NOT std->0 (which would
            # saturate the whole first rollout to ±10 sign patterns)
            return np.ones(m2.shape, np.float32)
        return np.sqrt(np.maximum(m2 / count, 1e-12)).astype(np.float32)

    def _fold_filter_batch(self, fsum: np.ndarray, fsq: np.ndarray,
                           n: int) -> None:
        """Fold one rollout's in-scan (sum, sumsq) into BOTH the running
        state and the since-last-sync delta buffer."""
        fsum = fsum.astype(np.float64)
        mean = fsum / n
        m2 = np.maximum(fsq.astype(np.float64) - n * mean * mean, 0.0)
        batch = (float(n), mean, m2)
        self._filt_state = merge_moments(self._filt_state, batch)
        self._filt_delta = merge_moments(self._filt_delta, batch)

    def get_filter_state(self):
        if not self.obs_filter:
            return None
        c, m, s = self._filt_state
        return (c, m.copy(), s.copy())

    def set_filter_state(self, state) -> None:
        if not self.obs_filter or state is None:
            return
        self._filt_state = (float(state[0]),
                            np.asarray(state[1], np.float64).copy(),
                            np.asarray(state[2], np.float64).copy())

    def get_filter_delta(self):
        """Moments accumulated since the last call — the group's sync
        merges ONLY deltas, so history is never double-counted."""
        if not self.obs_filter:
            return None
        delta, self._filt_delta = self._filt_delta, (
            0.0, np.zeros_like(self._filt_delta[1]),
            np.zeros_like(self._filt_delta[2]))
        return delta

    def sample(self) -> Dict[str, Any]:
        if not hasattr(self, "_ep_ret"):
            self._ep_ret = jnp.zeros(self.num_envs)
            self._ep_len = jnp.zeros(self.num_envs, jnp.int32)
        if self.obs_filter:
            fmean = jnp.asarray(self._filt_state[1], jnp.float32)
            fstd = jnp.asarray(self._filter_std())
        else:
            fmean, fstd = jnp.float32(0.0), jnp.float32(1.0)
        stack = (self._stack if self.framestack > 1
                 else jnp.float32(0.0))
        if not hasattr(self, "_mstate"):
            # recurrent modules persist their state ACROSS fragments
            self._mstate = (self.module.initial_state(
                self.params, self.num_envs)
                if hasattr(self.module, "initial_state")
                else jnp.float32(0.0))
        (self._env_state, self._obs, stack, self._ep_ret, self._ep_len,
         self._key, batch, self._mstate) = self._sample_jit(
            self.params, self._env_state, self._obs, stack,
            self._ep_ret, self._ep_len, self._key, fmean, fstd,
            self._mstate)
        if self.framestack > 1:
            self._stack = stack
        batch = jax.device_get(batch)
        fsum = batch.pop("filt_sum")
        fsq = batch.pop("filt_sumsq")
        if self.obs_filter:
            self._fold_filter_batch(
                np.asarray(fsum), np.asarray(fsq),
                self.num_envs * self.rollout_length)
        done_mask = batch.pop("dones")
        fin_ret = batch.pop("finished_return")
        fin_len = batch.pop("finished_len")
        n_done = int(done_mask.sum())
        stats = {
            "num_episodes": n_done,
            "episode_return_mean": float(fin_ret.sum() / max(n_done, 1)),
            "episode_len_mean": float(fin_len.sum() / max(n_done, 1)),
            "env_steps": self.num_envs * self.rollout_length,
        }
        batch["dones"] = done_mask
        return {"batch": {k: np.asarray(v) for k, v in batch.items()},
                "stats": stats}

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, params) -> None:
        self.params = jax.device_put(params)

    def ping(self) -> bool:
        return True
