"""EnvRunner: samples experience with a compiled rollout.

Reference parity: rllib/env/single_agent_env_runner.py:140 (sample loop
over gymnasium vector envs) and env_runner_group.py:71. TPU-native
inversion: the env is pure JAX (jax_env.py), so the WHOLE rollout —
policy forward, env physics, auto-reset, episode bookkeeping — is one
`lax.scan` under jit: a single device call per sample() instead of a
Python loop with T host↔device round-trips.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .jax_env import JaxEnv, make_env
from ..core.rl_module import RLModule, build_module


class SingleAgentEnvRunner:
    """Owns a vectorized env + module params; sample() returns a batch of
    shape [T, B, ...] plus episode stats. Runs as a plain object in-driver
    or as a ray_tpu actor in an EnvRunnerGroup."""

    def __init__(self, env, num_envs: int = 8, rollout_length: int = 128,
                 seed: int = 0, module_class: Optional[type] = None,
                 model_config: Optional[Dict[str, Any]] = None):
        self.env: JaxEnv = make_env(env)
        self.num_envs = num_envs
        self.rollout_length = rollout_length
        self.module: RLModule = build_module(
            self.env.spec, module_class, model_config)
        self._key = jax.random.PRNGKey(seed)
        self._key, init_key, reset_key = jax.random.split(self._key, 3)
        self.params = self.module.init(init_key)
        self._env_state, self._obs = jax.vmap(self.env.reset)(
            jax.random.split(reset_key, num_envs))
        self._sample_jit = jax.jit(self._build_sample())

    # -- compiled rollout ---------------------------------------------------
    def _build_sample(self):
        env, module = self.env, self.module
        B, T = self.num_envs, self.rollout_length

        def one_step(carry, step_key):
            env_state, obs, ep_ret, ep_len, params = carry
            act_key, step_keys, reset_keys = (
                step_key[0], step_key[1], step_key[2])
            action, logp, vf = module.forward_exploration(
                params, obs, act_key)
            next_state, next_obs, reward, done = jax.vmap(env.step)(
                env_state, action, jax.random.split(step_keys, B))
            ep_ret = ep_ret + reward
            ep_len = ep_len + 1
            # auto-reset finished envs (fresh state, keep static shapes)
            reset_state, reset_obs = jax.vmap(env.reset)(
                jax.random.split(reset_keys, B))
            sel = lambda a, b: jnp.where(
                jnp.reshape(done, (B,) + (1,) * (a.ndim - 1)), a, b)
            next_state = jax.tree_util.tree_map(sel, reset_state, next_state)
            next_obs = sel(reset_obs, next_obs)
            out = dict(obs=obs, actions=action, logp=logp, vf=vf,
                       rewards=reward, dones=done,
                       finished_return=jnp.where(done, ep_ret, 0.0),
                       finished_len=jnp.where(done, ep_len, 0))
            ep_ret = jnp.where(done, 0.0, ep_ret)
            ep_len = jnp.where(done, 0, ep_len)
            return (next_state, next_obs, ep_ret, ep_len, params), out

        def sample(params, env_state, obs, ep_ret, ep_len, key):
            key, sub = jax.random.split(key)
            step_keys = jax.random.split(sub, T * 3).reshape(T, 3, 2)
            carry, batch = jax.lax.scan(
                one_step, (env_state, obs, ep_ret, ep_len, params), step_keys)
            env_state, obs, ep_ret, ep_len, _ = carry
            final_out = module.forward_train(params, obs)
            batch["final_vf"] = final_out["vf"]
            # the observation after the last step — off-policy algorithms
            # reconstruct next_obs[t] as obs[t+1] (+ this for t = T-1)
            batch["final_obs"] = obs
            return env_state, obs, ep_ret, ep_len, key, batch

        return sample

    # -- public API ---------------------------------------------------------
    def sample(self) -> Dict[str, Any]:
        if not hasattr(self, "_ep_ret"):
            self._ep_ret = jnp.zeros(self.num_envs)
            self._ep_len = jnp.zeros(self.num_envs, jnp.int32)
        (self._env_state, self._obs, self._ep_ret, self._ep_len,
         self._key, batch) = self._sample_jit(
            self.params, self._env_state, self._obs, self._ep_ret,
            self._ep_len, self._key)
        batch = jax.device_get(batch)
        done_mask = batch.pop("dones")
        fin_ret = batch.pop("finished_return")
        fin_len = batch.pop("finished_len")
        n_done = int(done_mask.sum())
        stats = {
            "num_episodes": n_done,
            "episode_return_mean": float(fin_ret.sum() / max(n_done, 1)),
            "episode_len_mean": float(fin_len.sum() / max(n_done, 1)),
            "env_steps": self.num_envs * self.rollout_length,
        }
        batch["dones"] = done_mask
        return {"batch": {k: np.asarray(v) for k, v in batch.items()},
                "stats": stats}

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, params) -> None:
        self.params = jax.device_put(params)

    def ping(self) -> bool:
        return True
