"""Pure-functional multi-agent JAX environments.

Reference parity: rllib/env/multi_agent_env.py:32 (MultiAgentEnv — dict
obs/action/reward spaces keyed by agent id). TPU-native inversion, same
as jax_env.py: the env is a pure function of (state, action_dict), so a
multi-agent rollout — every policy's forward, the joint physics, the
per-agent bookkeeping — compiles into ONE `lax.scan` program.

Design deltas from the reference (documented, deliberate):
  * Simultaneous-move, static agent set. The reference supports agents
    appearing/disappearing mid-episode (dict obs may omit agents per
    step); that shape is dynamic and defeats XLA. Turn-based games are
    expressed by masking (an agent whose turn it isn't receives reward 0
    and its action is ignored).
  * Episode termination is env-level (`done`), shared by all agents —
    the common case in the reference's own multi-agent examples.

Env protocol:
  agents: tuple of agent-id strings (static)
  specs:  {agent_id: EnvSpec}
  reset(key) -> (state, obs_dict)
  step(state, action_dict, key) -> (state, obs_dict, reward_dict, done)
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from .jax_env import CartPole, EnvSpec


class MultiAgentJaxEnv:
    """Base class; subclasses are stateless — state is in the pytree."""

    agents: Tuple[str, ...]
    specs: Dict[str, EnvSpec]

    def reset(self, key):
        raise NotImplementedError

    def step(self, state, actions: Dict[str, jnp.ndarray], key):
        raise NotImplementedError


class DualCartPole(MultiAgentJaxEnv):
    """Two independent cart-poles, one per agent, in a shared episode.

    The episode ends when EITHER pole falls (or at truncation), so each
    agent's return depends on both policies — the simplest env where
    "both policies improving" is observable per agent. Physics are
    exactly jax_env.CartPole's.
    """

    def __init__(self, max_episode_steps: int = 200):
        self._cart = CartPole(max_episode_steps=max_episode_steps)
        self.max_episode_steps = max_episode_steps
        self.agents = ("cart_0", "cart_1")
        spec = EnvSpec(obs_dim=4, num_actions=2,
                       max_episode_steps=max_episode_steps)
        self.specs = {aid: spec for aid in self.agents}

    def reset(self, key):
        k0, k1 = jax.random.split(key)
        (s0, _), obs0 = self._cart.reset(k0)
        (s1, _), obs1 = self._cart.reset(k1)
        state = (s0, s1, jnp.zeros((), jnp.int32))
        return state, {"cart_0": obs0, "cart_1": obs1}

    def step(self, state, actions, key):
        del key
        s0, s1, t = state
        # reuse the single-cart physics; its step tracks its own t — feed
        # zero and keep the joint clock here
        (s0n, _), obs0, _, d0 = self._cart.step(
            (s0, jnp.zeros((), jnp.int32)), actions["cart_0"], None)
        (s1n, _), obs1, _, d1 = self._cart.step(
            (s1, jnp.zeros((), jnp.int32)), actions["cart_1"], None)
        t2 = t + 1
        done = d0 | d1 | (t2 >= self.max_episode_steps)
        one = jnp.float32(1.0)
        return ((s0n, s1n, t2),
                {"cart_0": obs0, "cart_1": obs1},
                {"cart_0": one, "cart_1": one},
                done)


class RockPaperScissors(MultiAgentJaxEnv):
    """Iterated rock-paper-scissors, zero-sum, two agents.

    Obs is the one-hot of the opponent's previous move (zeros on the
    first step). Good for exercising competitive two-policy mechanics:
    rewards sum to zero by construction.
    """

    def __init__(self, episode_len: int = 10):
        self.episode_len = episode_len
        self.agents = ("player_0", "player_1")
        spec = EnvSpec(obs_dim=3, num_actions=3,
                       max_episode_steps=episode_len)
        self.specs = {aid: spec for aid in self.agents}

    def reset(self, key):
        del key
        state = jnp.zeros((), jnp.int32)
        obs = jnp.zeros((3,), jnp.float32)
        return state, {"player_0": obs, "player_1": obs}

    def step(self, state, actions, key):
        del key
        a0, a1 = actions["player_0"], actions["player_1"]
        # 0 beats 2, 1 beats 0, 2 beats 1 (rock/paper/scissors)
        win0 = ((a0 - a1) % 3) == 1
        win1 = ((a1 - a0) % 3) == 1
        r0 = jnp.where(win0, 1.0, jnp.where(win1, -1.0, 0.0))
        t2 = state + 1
        obs = {"player_0": jax.nn.one_hot(a1, 3),
               "player_1": jax.nn.one_hot(a0, 3)}
        return t2, obs, {"player_0": r0, "player_1": -r0}, (
            t2 >= self.episode_len)


_MA_ENV_REGISTRY: Dict[str, Callable[[], MultiAgentJaxEnv]] = {
    "DualCartPole": DualCartPole,
    "RockPaperScissors": RockPaperScissors,
}


def register_multi_agent_env(name: str,
                             factory: Callable[[], MultiAgentJaxEnv]) -> None:
    _MA_ENV_REGISTRY[name] = factory


def make_multi_agent_env(name_or_env) -> MultiAgentJaxEnv:
    if isinstance(name_or_env, MultiAgentJaxEnv):
        return name_or_env
    if isinstance(name_or_env, str):
        if name_or_env not in _MA_ENV_REGISTRY:
            raise ValueError(
                f"unknown multi-agent env {name_or_env!r}; registered: "
                f"{sorted(_MA_ENV_REGISTRY)}")
        return _MA_ENV_REGISTRY[name_or_env]()
    if callable(name_or_env):
        return name_or_env()
    raise TypeError(f"cannot build multi-agent env from {name_or_env!r}")
