"""MultiAgentEnvRunner: compiled multi-agent rollouts.

Reference parity: rllib/env/multi_agent_env_runner.py:67 (sample over a
MultiAgentEnv with per-agent episodes and a policy-mapping fn) and
multi_agent_episode.py. TPU-native inversion: agents are static, so the
per-agent policy forwards unroll at trace time and the whole joint
rollout is one `lax.scan` under jit.

Policy mapping: the reference's `policy_mapping_fn(agent_id, episode)`
may vary per episode; a compiled rollout needs it static, so the fn is
evaluated ONCE per agent at construction (self-play = map every agent to
the same module id). This covers the reference's tuned multi-agent
examples, which all use episode-independent mappings.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu

from .multi_agent_env import MultiAgentJaxEnv, make_multi_agent_env
from ..core.multi_rl_module import MultiRLModule


def call_mapping_fn(fn: Callable, agent_id: str) -> str:
    """Evaluate a policy-mapping fn, tolerating the reference's 2-arg
    signature fn(agent_id, episode, **kw)."""
    try:
        return str(fn(agent_id))
    except TypeError:
        return str(fn(agent_id, None))


def derive_module_specs(env: MultiAgentJaxEnv, policy_mapping_fn: Callable
                        ) -> tuple:
    """(agent->module mapping, module->EnvSpec) for an env + mapping fn,
    validating that agents sharing a module share an EnvSpec. Single
    source of truth for the runner and the runner group."""
    mapping = {aid: call_mapping_fn(policy_mapping_fn, aid)
               for aid in env.agents}
    module_specs: Dict[str, Any] = {}
    for aid in env.agents:
        mid = mapping[aid]
        spec = env.specs[aid]
        if mid in module_specs and module_specs[mid] != spec:
            raise ValueError(
                f"agents mapped to module {mid!r} have different "
                f"EnvSpecs; use separate modules")
        module_specs[mid] = spec
    return mapping, module_specs


class MultiAgentEnvRunner:
    """Samples {module_id: [T, B_mod, ...]} batches from a multi-agent
    env. Streams of agents mapped to the same module are concatenated
    along the env axis, so each module's learner sees one batch."""

    def __init__(self, env, policy_mapping_fn: Callable[[str], str],
                 num_envs: int = 8, rollout_length: int = 128,
                 seed: int = 0,
                 module_classes: Optional[Dict[str, type]] = None,
                 model_configs: Optional[Dict[str, dict]] = None):
        self.env: MultiAgentJaxEnv = make_multi_agent_env(env)
        self.agents = tuple(self.env.agents)
        self.num_envs = num_envs
        self.rollout_length = rollout_length
        # static mapping (see module docstring)
        self.mapping, self.module_specs = derive_module_specs(
            self.env, policy_mapping_fn)
        module_specs = self.module_specs
        self.multi_module = MultiRLModule.from_specs(
            module_specs, module_classes, model_configs)
        self._key = jax.random.PRNGKey(seed)
        self._key, init_key, reset_key = jax.random.split(self._key, 3)
        self.params = self.multi_module.init(init_key)
        self._env_state, self._obs = jax.vmap(self.env.reset)(
            jax.random.split(reset_key, num_envs))
        self._sample_jit = jax.jit(self._build_sample())

    # -- compiled rollout ---------------------------------------------------
    def _build_sample(self):
        env, mm = self.env, self.multi_module
        agents, mapping = self.agents, self.mapping
        B, T = self.num_envs, self.rollout_length

        def one_step(carry, step_key):
            env_state, obs, ep_ret, ep_len, params = carry
            act_key, env_keys, reset_keys = (
                step_key[0], step_key[1], step_key[2])
            actions, logps, vfs = {}, {}, {}
            for i, aid in enumerate(agents):      # static unroll
                a, lp, v = mm.forward_exploration(
                    mapping[aid], params, obs[aid],
                    jax.random.fold_in(act_key, i))
                actions[aid], logps[aid], vfs[aid] = a, lp, v
            next_state, next_obs, rewards, done = jax.vmap(env.step)(
                env_state, actions, jax.random.split(env_keys, B))
            ep_ret = {aid: ep_ret[aid] + rewards[aid] for aid in agents}
            ep_len = ep_len + 1
            reset_state, reset_obs = jax.vmap(env.reset)(
                jax.random.split(reset_keys, B))
            sel = lambda a, b: jnp.where(
                jnp.reshape(done, (B,) + (1,) * (a.ndim - 1)), a, b)
            next_state = jax.tree_util.tree_map(sel, reset_state, next_state)
            next_obs = jax.tree_util.tree_map(sel, reset_obs, next_obs)
            out = dict(
                obs=obs, actions=actions, logp=logps, vf=vfs,
                rewards=rewards, dones=done,
                finished_return={aid: jnp.where(done, ep_ret[aid], 0.0)
                                 for aid in agents},
                finished_len=jnp.where(done, ep_len, 0))
            ep_ret = {aid: jnp.where(done, 0.0, ep_ret[aid])
                      for aid in agents}
            ep_len = jnp.where(done, 0, ep_len)
            return (next_state, next_obs, ep_ret, ep_len, params), out

        def sample(params, env_state, obs, ep_ret, ep_len, key):
            key, sub = jax.random.split(key)
            step_keys = jax.random.split(sub, T * 3).reshape(T, 3, 2)
            carry, batch = jax.lax.scan(
                one_step, (env_state, obs, ep_ret, ep_len, params),
                step_keys)
            env_state, obs, ep_ret, ep_len, _ = carry
            batch["final_vf"] = {
                aid: mm.forward_train(mapping[aid], params, obs[aid])["vf"]
                for aid in agents}
            batch["final_obs"] = obs
            return env_state, obs, ep_ret, ep_len, key, batch

        return sample

    # -- public API ---------------------------------------------------------
    def sample(self) -> Dict[str, Any]:
        if not hasattr(self, "_ep_ret"):
            self._ep_ret = {aid: jnp.zeros(self.num_envs)
                            for aid in self.agents}
            self._ep_len = jnp.zeros(self.num_envs, jnp.int32)
        (self._env_state, self._obs, self._ep_ret, self._ep_len,
         self._key, batch) = self._sample_jit(
            self.params, self._env_state, self._obs, self._ep_ret,
            self._ep_len, self._key)
        batch = jax.device_get(batch)
        dones = np.asarray(batch.pop("dones"))           # [T, B]
        fin_ret = batch.pop("finished_return")           # {aid: [T, B]}
        fin_len = np.asarray(batch.pop("finished_len"))
        n_done = int(dones.sum())
        agent_returns = {
            aid: float(np.asarray(fin_ret[aid]).sum() / max(n_done, 1))
            for aid in self.agents}
        stats = {
            "num_episodes": n_done,
            "episode_len_mean": float(fin_len.sum() / max(n_done, 1)),
            "episode_return_mean": float(
                sum(agent_returns.values())),      # sum-of-agents return
            "agent_episode_returns": agent_returns,
            "env_steps": self.num_envs * self.rollout_length,
            "agent_steps": (self.num_envs * self.rollout_length
                            * len(self.agents)),
        }
        # regroup per-agent streams into per-module batches, concat along
        # the env axis ([T, B] -> [T, B * n_agents_of_module])
        per_module: Dict[str, Dict[str, np.ndarray]] = {}
        for mid in self.module_specs:
            aids = [a for a in self.agents if self.mapping[a] == mid]
            mb = {}
            for k in ("obs", "actions", "logp", "vf", "rewards"):
                mb[k] = np.concatenate(
                    [np.asarray(batch[k][a]) for a in aids], axis=1)
            mb["dones"] = np.concatenate([dones] * len(aids), axis=1)
            mb["final_vf"] = np.concatenate(
                [np.asarray(batch["final_vf"][a]) for a in aids], axis=0)
            mb["final_obs"] = np.concatenate(
                [np.asarray(batch["final_obs"][a]) for a in aids], axis=0)
            per_module[mid] = mb
        return {"batches": per_module, "stats": stats}

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, params) -> None:
        self.params = jax.device_put(params)

    def ping(self) -> bool:
        return True


def _merge_ma(results):
    """Merge remote runners' results: concat per-module env axes,
    weight-average stats."""
    merged: Dict[str, Dict[str, np.ndarray]] = {}
    for mid in results[0]["batches"]:
        mb = {}
        for k in results[0]["batches"][mid]:
            axis = 0 if k in ("final_vf", "final_obs") else 1
            mb[k] = np.concatenate(
                [r["batches"][mid][k] for r in results], axis=axis)
        merged[mid] = mb
    n_eps = sum(r["stats"]["num_episodes"] for r in results)
    agents = list(results[0]["stats"]["agent_episode_returns"])
    agent_returns = {
        aid: sum(r["stats"]["agent_episode_returns"][aid]
                 * r["stats"]["num_episodes"] for r in results)
        / max(n_eps, 1)
        for aid in agents}
    stats = {
        "num_episodes": n_eps,
        "episode_len_mean": sum(
            r["stats"]["episode_len_mean"] * r["stats"]["num_episodes"]
            for r in results) / max(n_eps, 1),
        "episode_return_mean": float(sum(agent_returns.values())),
        "agent_episode_returns": agent_returns,
        "env_steps": sum(r["stats"]["env_steps"] for r in results),
        "agent_steps": sum(r["stats"]["agent_steps"] for r in results),
    }
    return {"batches": merged, "stats": stats}


class MultiAgentEnvRunnerGroup:
    """Local or remote fleet of MultiAgentEnvRunners (mirror of
    env_runner_group.EnvRunnerGroup for the multi-agent path)."""

    def __init__(self, env, policy_mapping_fn, num_env_runners: int = 0,
                 num_envs_per_runner: int = 8, rollout_length: int = 128,
                 seed: int = 0,
                 module_classes: Optional[Dict[str, type]] = None,
                 model_configs: Optional[Dict[str, dict]] = None,
                 runner_resources: Optional[Dict[str, float]] = None):
        self.num_env_runners = num_env_runners
        # specs computed here (not via an actor round-trip): env + mapping
        # fully determine them
        self.mapping, self._module_specs = derive_module_specs(
            make_multi_agent_env(env), policy_mapping_fn)
        if num_env_runners == 0:
            self._local = MultiAgentEnvRunner(
                env, policy_mapping_fn, num_envs_per_runner,
                rollout_length, seed, module_classes, model_configs)
            self._remote = []
        else:
            self._local = None
            remote_cls = ray_tpu.remote(
                **(runner_resources or {"num_cpus": 1}))(MultiAgentEnvRunner)
            self._remote = [
                remote_cls.remote(env, policy_mapping_fn,
                                  num_envs_per_runner, rollout_length,
                                  seed + 1000 * (i + 1), module_classes,
                                  model_configs)
                for i in range(num_env_runners)]
            ray_tpu.get([r.ping.remote() for r in self._remote])

    def sample(self) -> Dict[str, Any]:
        if self._local is not None:
            return self._local.sample()
        return _merge_ma(
            ray_tpu.get([r.sample.remote() for r in self._remote]))

    def sync_weights(self, params_by_module) -> None:
        if self._local is not None:
            self._local.set_weights(params_by_module)
        else:
            ref = ray_tpu.put(params_by_module)
            ray_tpu.get([r.set_weights.remote(ref) for r in self._remote])

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        return ray_tpu.get(self._remote[0].get_weights.remote())

    @property
    def module_specs(self):
        return self._module_specs

    def stop(self) -> None:
        for r in self._remote:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
