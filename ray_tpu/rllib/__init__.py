"""ray_tpu.rllib: TPU-native reinforcement learning.

Reference parity: the new-stack RLlib (EnvRunners + Connectors +
RLModule + Learner, rllib/algorithms/algorithm.py:198). Rollouts are
compiled: pure-JAX envs scanned with the policy in one XLA program.
"""

from .._private.usage import record_library_usage as _rlu
_rlu("rllib")
del _rlu


from .algorithms.algorithm import Algorithm, AlgorithmConfig
from .algorithms.appo import APPO, APPOConfig
from .algorithms.cql import CQL, CQLConfig
from .algorithms.dqn import DQN, DQNConfig
from .algorithms.dreamer_v3 import DreamerV3, DreamerV3Config
from .algorithms.impala import IMPALA, IMPALAConfig
from .algorithms.multi_agent_ppo import MultiAgentPPO, MultiAgentPPOConfig
from .algorithms.ppo import PPO, PPOConfig
from .algorithms.sac import SAC, SACConfig
from .core.learner import Learner, LearnerGroup
from .core.multi_rl_module import MultiRLModule
from .core.rl_module import CNNRLModule, DefaultRLModule, RLModule
from .env.env_runner import SingleAgentEnvRunner
from .env.env_runner_group import EnvRunnerGroup
from .env.jax_env import CartPole, EnvSpec, JaxEnv, Pendulum, register_env
from .env.multi_agent_env import (DualCartPole, MultiAgentJaxEnv,
                                  RockPaperScissors,
                                  register_multi_agent_env)
from .env.multi_agent_env_runner import (MultiAgentEnvRunner,
                                         MultiAgentEnvRunnerGroup)
from .offline import (BC, BCConfig, MARWIL, MARWILConfig, OfflineData,
                      record_samples)
from .utils.replay_buffers import ReplayBuffer

__all__ = [
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "IMPALA",
    "IMPALAConfig", "APPO", "APPOConfig", "DQN", "DQNConfig",
    "SAC", "SACConfig", "CQL", "CQLConfig", "DreamerV3", "DreamerV3Config",
    "BC", "BCConfig", "MARWIL", "MARWILConfig", "OfflineData",
    "record_samples", "ReplayBuffer",
    "Learner", "LearnerGroup", "RLModule",
    "CNNRLModule", "DefaultRLModule", "SingleAgentEnvRunner", "EnvRunnerGroup",
    "JaxEnv", "CartPole", "Pendulum", "EnvSpec", "register_env",
    "MultiAgentPPO", "MultiAgentPPOConfig", "MultiRLModule",
    "MultiAgentJaxEnv", "DualCartPole", "RockPaperScissors",
    "register_multi_agent_env", "MultiAgentEnvRunner",
    "MultiAgentEnvRunnerGroup",
]
