"""MultiRLModule: a dict of RLModules keyed by module id.

Reference parity: rllib/core/rl_module/multi_rl_module.py (MultiRLModule
holds sub-RLModules; get_module / add_module / params-per-module). The
TPU-native shape keeps it functional: params are a plain
{module_id: pytree} dict, so the whole thing jits and shards like any
other pytree.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax

from .rl_module import RLModule, build_module


class MultiRLModule:
    """Container of per-policy modules. Stateless; params live outside."""

    def __init__(self, modules: Dict[str, RLModule]):
        self._modules = dict(modules)

    @classmethod
    def from_specs(cls, specs: Dict[str, Any],
                   module_classes: Optional[Dict[str, type]] = None,
                   model_configs: Optional[Dict[str, dict]] = None
                   ) -> "MultiRLModule":
        module_classes = module_classes or {}
        model_configs = model_configs or {}
        return cls({
            mid: build_module(spec, module_classes.get(mid),
                              model_configs.get(mid))
            for mid, spec in specs.items()})

    @property
    def module_ids(self):
        return tuple(self._modules)

    def get_module(self, module_id: str) -> RLModule:
        return self._modules[module_id]

    def __getitem__(self, module_id: str) -> RLModule:
        return self._modules[module_id]

    def __contains__(self, module_id: str) -> bool:
        return module_id in self._modules

    def add_module(self, module_id: str, module: RLModule) -> None:
        self._modules[module_id] = module

    def init(self, key) -> Dict[str, Any]:
        keys = jax.random.split(key, len(self._modules))
        return {mid: m.init(k)
                for (mid, m), k in zip(sorted(self._modules.items()), keys)}

    # per-module forward_* (params is the {module_id: pytree} dict)
    def forward_exploration(self, module_id: str, params, obs, key):
        return self._modules[module_id].forward_exploration(
            params[module_id], obs, key)

    def forward_inference(self, module_id: str, params, obs):
        return self._modules[module_id].forward_inference(
            params[module_id], obs)

    def forward_train(self, module_id: str, params, obs):
        return self._modules[module_id].forward_train(params[module_id], obs)
