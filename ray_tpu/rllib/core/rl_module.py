"""RLModule: the framework-agnostic policy container.

Reference parity: rllib/core/rl_module/rl_module.py:260
(forward_inference/forward_exploration/forward_train :549-633). TPU-native
shape: a module is a pair (apply_fn, params-pytree); apply_fn is pure so
it jits/vmaps/scans and shards with pjit. Default module is a flax.linen
actor-critic MLP (the reference's default MLP catalog,
rllib/core/models/catalog.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

from typing import TYPE_CHECKING

import flax.linen as nn
import jax
import jax.numpy as jnp

from . import distributions

if TYPE_CHECKING:  # EnvSpec is duck-typed at runtime (avoids an env
    from ..env.jax_env import EnvSpec  # package import cycle)


class RLModule:
    """Stateless spec + pure apply; params live outside (functional)."""

    def __init__(self, spec: "EnvSpec"):
        self.spec = spec
        self.dist = distributions.for_spec(spec)

    # subclasses define
    def init(self, key) -> Any:
        raise NotImplementedError

    def apply(self, params, obs) -> Dict[str, jnp.ndarray]:
        """Returns {"action_dist_inputs": ..., "vf": ...}."""
        raise NotImplementedError

    # reference forward_* surface ------------------------------------------
    def forward_inference(self, params, obs):
        out = self.apply(params, obs)
        return self.dist.deterministic(out["action_dist_inputs"])

    def forward_exploration(self, params, obs, key):
        out = self.apply(params, obs)
        inputs = out["action_dist_inputs"]
        action = self.dist.sample(inputs, key)
        logp = self.dist.log_prob(inputs, action)
        return action, logp, out["vf"]

    def forward_train(self, params, obs):
        return self.apply(params, obs)


class _ActorCriticMLP(nn.Module):
    hiddens: Sequence[int]
    out_dim: int

    @nn.compact
    def __call__(self, x):
        pi = x
        for h in self.hiddens:
            pi = nn.tanh(nn.Dense(h)(pi))
        logits = nn.Dense(self.out_dim,
                          kernel_init=nn.initializers.orthogonal(0.01))(pi)
        v = x
        for h in self.hiddens:
            v = nn.tanh(nn.Dense(h)(v))
        vf = nn.Dense(1, kernel_init=nn.initializers.orthogonal(1.0))(v)
        return logits, vf[..., 0]


class DefaultRLModule(RLModule):
    """MLP actor-critic with separate policy/value torsos."""

    def __init__(self, spec, hiddens: Sequence[int] = (64, 64)):
        super().__init__(spec)
        out_dim = spec.num_actions if spec.discrete else 2 * spec.action_dim
        self._net = _ActorCriticMLP(tuple(hiddens), out_dim)

    def init(self, key):
        dummy = jnp.zeros((1, self.spec.obs_dim), jnp.float32)
        return self._net.init(key, dummy)

    def apply(self, params, obs):
        logits, vf = self._net.apply(params, obs)
        return {"action_dist_inputs": logits, "vf": vf}


class _ActorCriticCNN(nn.Module):
    """Shared conv torso + separate policy/value heads (the Nature-CNN
    shape scaled to the env image; convs land on the MXU on TPU)."""
    obs_shape: Sequence[int]
    channels: Sequence[int]
    dense: int
    out_dim: int

    n_frames: int = 1

    @nn.compact
    def __call__(self, x):
        b = x.shape[0]
        h, w, c = self.obs_shape
        if self.n_frames > 1:
            # frame-major flat input: fold frames into CHANNELS (a raw
            # reshape to (H, W, C*N) would interleave frames into row
            # blocks and scramble spatial locality)
            img = x.reshape(b, self.n_frames, h, w, c)
            img = jnp.concatenate(
                [img[:, i] for i in range(self.n_frames)], axis=-1)
        else:
            img = x.reshape(b, h, w, c)
        for i, ch in enumerate(self.channels):
            img = nn.relu(nn.Conv(
                ch, (3, 3), strides=(2, 2) if i else (1, 1))(img))
        flat = img.reshape(b, -1)
        h = nn.relu(nn.Dense(self.dense)(flat))
        logits = nn.Dense(self.out_dim,
                          kernel_init=nn.initializers.orthogonal(0.01))(h)
        vf = nn.Dense(1, kernel_init=nn.initializers.orthogonal(1.0))(h)
        return logits, vf[..., 0]


class CNNRLModule(RLModule):
    """Pixel-observation actor-critic: the env's flat obs vector is
    reshaped to spec.obs_shape (H, W, C) — under feature-wise frame
    stacking the stacked copies become extra channels. Use via
    ``.rl_module(module_class=CNNRLModule)`` (reference role: the
    Atari CNN default in catalog-built torch/TF modules)."""

    def __init__(self, spec, channels: Sequence[int] = (16, 32),
                 dense: int = 128):
        super().__init__(spec)
        base = tuple(getattr(spec, "obs_shape", ()) or ())
        if len(base) != 3:
            raise ValueError(
                f"CNNRLModule needs spec.obs_shape == (H, W, C); "
                f"got {base!r}")
        pixels = base[0] * base[1] * base[2]
        if spec.obs_dim % pixels:
            raise ValueError(
                f"obs_dim {spec.obs_dim} is not a multiple of "
                f"prod(obs_shape) {pixels} — mixed pixel+vector "
                f"observations need a custom module")
        n_frames = spec.obs_dim // pixels     # framestack factor
        out_dim = spec.num_actions if spec.discrete else 2 * spec.action_dim
        self._net = _ActorCriticCNN(base, tuple(channels),
                                    dense, out_dim, n_frames)

    def init(self, key):
        dummy = jnp.zeros((1, self.spec.obs_dim), jnp.float32)
        return self._net.init(key, dummy)

    def apply(self, params, obs):
        logits, vf = self._net.apply(params, obs)
        return {"action_dist_inputs": logits, "vf": vf}


def build_module(spec,
                 module_class: Optional[type] = None,
                 model_config: Optional[Dict[str, Any]] = None) -> RLModule:
    model_config = model_config or {}
    if module_class is not None:
        return module_class(spec, **model_config)
    return DefaultRLModule(spec, **model_config)
