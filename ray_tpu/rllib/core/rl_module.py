"""RLModule: the framework-agnostic policy container.

Reference parity: rllib/core/rl_module/rl_module.py:260
(forward_inference/forward_exploration/forward_train :549-633). TPU-native
shape: a module is a pair (apply_fn, params-pytree); apply_fn is pure so
it jits/vmaps/scans and shards with pjit. Default module is a flax.linen
actor-critic MLP (the reference's default MLP catalog,
rllib/core/models/catalog.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

from typing import TYPE_CHECKING

import flax.linen as nn
import jax
import jax.numpy as jnp

from . import distributions

if TYPE_CHECKING:  # EnvSpec is duck-typed at runtime (avoids an env
    from ..env.jax_env import EnvSpec  # package import cycle)


class RLModule:
    """Stateless spec + pure apply; params live outside (functional)."""

    def __init__(self, spec: "EnvSpec"):
        self.spec = spec
        self.dist = distributions.for_spec(spec)

    # subclasses define
    def init(self, key) -> Any:
        raise NotImplementedError

    def apply(self, params, obs) -> Dict[str, jnp.ndarray]:
        """Returns {"action_dist_inputs": ..., "vf": ...}."""
        raise NotImplementedError

    # reference forward_* surface ------------------------------------------
    def forward_inference(self, params, obs):
        out = self.apply(params, obs)
        return self.dist.deterministic(out["action_dist_inputs"])

    def forward_exploration(self, params, obs, key):
        out = self.apply(params, obs)
        inputs = out["action_dist_inputs"]
        action = self.dist.sample(inputs, key)
        logp = self.dist.log_prob(inputs, action)
        return action, logp, out["vf"]

    def forward_train(self, params, obs):
        return self.apply(params, obs)


class _ActorCriticMLP(nn.Module):
    hiddens: Sequence[int]
    out_dim: int

    @nn.compact
    def __call__(self, x):
        pi = x
        for h in self.hiddens:
            pi = nn.tanh(nn.Dense(h)(pi))
        logits = nn.Dense(self.out_dim,
                          kernel_init=nn.initializers.orthogonal(0.01))(pi)
        v = x
        for h in self.hiddens:
            v = nn.tanh(nn.Dense(h)(v))
        vf = nn.Dense(1, kernel_init=nn.initializers.orthogonal(1.0))(v)
        return logits, vf[..., 0]


class DefaultRLModule(RLModule):
    """MLP actor-critic with separate policy/value torsos."""

    def __init__(self, spec, hiddens: Sequence[int] = (64, 64)):
        super().__init__(spec)
        out_dim = spec.num_actions if spec.discrete else 2 * spec.action_dim
        self._net = _ActorCriticMLP(tuple(hiddens), out_dim)

    def init(self, key):
        dummy = jnp.zeros((1, self.spec.obs_dim), jnp.float32)
        return self._net.init(key, dummy)

    def apply(self, params, obs):
        logits, vf = self._net.apply(params, obs)
        return {"action_dist_inputs": logits, "vf": vf}


def build_module(spec,
                 module_class: Optional[type] = None,
                 model_config: Optional[Dict[str, Any]] = None) -> RLModule:
    model_config = model_config or {}
    if module_class is not None:
        return module_class(spec, **model_config)
    return DefaultRLModule(spec, **model_config)
