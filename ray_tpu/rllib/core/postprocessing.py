"""Trajectory postprocessing: GAE as a compiled reverse scan.

Reference parity: rllib/connectors/learner/general_advantage_estimation.py
(GAE connector in the learner pipeline) and
rllib/evaluation/postprocessing.py:compute_advantages. TPU-native: a
`lax.scan` in reverse over the time axis, jitted once, batched over envs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("gamma", "lam"))
def compute_gae(rewards, values, dones, final_values, *,
                gamma: float = 0.99, lam: float = 0.95):
    """rewards/values/dones: [T, B]; final_values: [B].
    Returns (advantages [T, B], value_targets [T, B]).

    Episode boundaries (dones) cut the bootstrap; auto-reset rollouts make
    this exact for terminations and the standard approximation for
    truncations.
    """
    not_done = 1.0 - dones.astype(jnp.float32)
    next_values = jnp.concatenate([values[1:], final_values[None]], axis=0)
    deltas = rewards + gamma * next_values * not_done - values

    def backward(adv_next, inp):
        delta, nd = inp
        adv = delta + gamma * lam * nd * adv_next
        return adv, adv

    _, advantages = jax.lax.scan(
        backward, jnp.zeros_like(final_values), (deltas, not_done),
        reverse=True)
    return advantages, advantages + values
