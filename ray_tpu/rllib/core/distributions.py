"""Action distributions in pure JAX.

Reference parity: rllib/models/torch/torch_distributions.py (Categorical,
DiagGaussian). Here they are stateless namespaces over jnp arrays so they
trace cleanly under jit/vmap/scan.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Categorical:
    @staticmethod
    def sample(logits, key):
        return jax.random.categorical(key, logits, axis=-1)

    @staticmethod
    def log_prob(logits, actions):
        logp = jax.nn.log_softmax(logits, axis=-1)
        return jnp.take_along_axis(
            logp, actions[..., None].astype(jnp.int32), axis=-1)[..., 0]

    @staticmethod
    def entropy(logits):
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)

    @staticmethod
    def kl(logits_p, logits_q):
        logp = jax.nn.log_softmax(logits_p, axis=-1)
        logq = jax.nn.log_softmax(logits_q, axis=-1)
        return jnp.sum(jnp.exp(logp) * (logp - logq), axis=-1)

    @staticmethod
    def deterministic(logits):
        return jnp.argmax(logits, axis=-1)


class DiagGaussian:
    """Parameterised by concat([mean, log_std], axis=-1)."""

    @staticmethod
    def split(params):
        mean, log_std = jnp.split(params, 2, axis=-1)
        return mean, jnp.clip(log_std, -20.0, 2.0)

    @staticmethod
    def sample(params, key):
        mean, log_std = DiagGaussian.split(params)
        return mean + jnp.exp(log_std) * jax.random.normal(key, mean.shape)

    @staticmethod
    def log_prob(params, actions):
        mean, log_std = DiagGaussian.split(params)
        var = jnp.exp(2 * log_std)
        return jnp.sum(
            -0.5 * ((actions - mean) ** 2 / var)
            - log_std - 0.5 * jnp.log(2 * jnp.pi), axis=-1)

    @staticmethod
    def entropy(params):
        _, log_std = DiagGaussian.split(params)
        return jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)

    @staticmethod
    def kl(params_p, params_q):
        mp, lsp = DiagGaussian.split(params_p)
        mq, lsq = DiagGaussian.split(params_q)
        return jnp.sum(
            lsq - lsp
            + (jnp.exp(2 * lsp) + (mp - mq) ** 2) / (2 * jnp.exp(2 * lsq))
            - 0.5, axis=-1)

    @staticmethod
    def deterministic(params):
        mean, _ = DiagGaussian.split(params)
        return mean


def for_spec(spec):
    """Pick the distribution class for an EnvSpec."""
    return Categorical if spec.discrete else DiagGaussian
