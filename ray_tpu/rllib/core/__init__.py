from .rl_module import (CNNRLModule, DefaultRLModule, RLModule,
                        build_module)
from .learner import Learner, LearnerGroup, LearnerHyperparams
from . import distributions, postprocessing
