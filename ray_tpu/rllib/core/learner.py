"""Learner / LearnerGroup: the gradient path.

Reference parity: rllib/core/learner/learner.py:107 (compute_losses :887,
update :971), torch_learner.py:67 (DDP wrap :436), learner_group.py:72
(N learner actors over Train's BackendExecutor with NCCL).

TPU-native shape: the whole update — epochs × shuffled minibatches ×
grad/apply — is ONE jitted program (`lax.scan` over minibatch indices),
so an iteration is a single device call. Multi-learner data parallelism:
each learner actor computes per-minibatch grads (jitted) and allreduces
them through ray_tpu.util.collective (the ICI/DCN path) before a jitted
apply — replacing the reference's NCCL DDP.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu

from .rl_module import RLModule, build_module


@dataclasses.dataclass
class LearnerHyperparams:
    lr: float = 3e-4
    grad_clip: float = 0.5
    num_epochs: int = 4
    minibatch_size: int = 256


class Learner:
    """Subclasses implement compute_loss(params, minibatch) ->
    (loss, metrics-dict); everything else is built here."""

    def __init__(self, spec, hps: LearnerHyperparams,
                 module_class: Optional[type] = None,
                 model_config: Optional[Dict[str, Any]] = None,
                 seed: int = 0):
        self.hps = hps
        self.module: RLModule = build_module(spec, module_class, model_config)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(hps.grad_clip),
            optax.adam(hps.lr, eps=1e-5))
        self.params = self.module.init(jax.random.PRNGKey(seed))
        self.opt_state = self.optimizer.init(self.params)
        self._key = jax.random.PRNGKey(seed + 1)
        self._update_jit = jax.jit(self._build_update())
        self._grads_jit = jax.jit(self._build_grads())
        self._apply_jit = jax.jit(self._build_apply())

    # -- subclass hook ------------------------------------------------------
    def compute_loss(self, params, minibatch):
        raise NotImplementedError

    # -- fused single-learner update ---------------------------------------
    def _build_update(self):
        opt, hps = self.optimizer, self.hps

        def update(params, opt_state, batch, key):
            n = next(iter(batch.values())).shape[0]
            mb = min(hps.minibatch_size, n)
            nmb = max(n // mb, 1)

            def mb_step(carry, idx):
                params, opt_state = carry
                mbatch = jax.tree_util.tree_map(lambda a: a[idx], batch)
                (_, aux), grads = jax.value_and_grad(
                    self.compute_loss, has_aux=True)(params, mbatch)
                updates, opt_state = opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), aux

            def epoch(carry, ekey):
                perm = jax.random.permutation(ekey, n)
                idxs = perm[: nmb * mb].reshape(nmb, mb)
                return jax.lax.scan(mb_step, carry, idxs)

            keys = jax.random.split(key, hps.num_epochs)
            (params, opt_state), aux = jax.lax.scan(
                epoch, (params, opt_state), keys)
            metrics = jax.tree_util.tree_map(lambda a: a.mean(), aux)
            return params, opt_state, metrics

        return update

    # -- split-phase (multi-learner allreduce) ------------------------------
    def _build_grads(self):
        def grads_fn(params, minibatch):
            (_, aux), grads = jax.value_and_grad(
                self.compute_loss, has_aux=True)(params, minibatch)
            return grads, aux
        return grads_fn

    def _build_apply(self):
        opt = self.optimizer

        def apply_fn(params, opt_state, grads):
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state
        return apply_fn

    # -- public API ---------------------------------------------------------
    def update(self, train_batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        self._key, sub = jax.random.split(self._key)
        batch = {k: jnp.asarray(v) for k, v in train_batch.items()}
        self.params, self.opt_state, metrics = self._update_jit(
            self.params, self.opt_state, batch, sub)
        return {k: float(v) for k, v in jax.device_get(metrics).items()}

    def update_with_allreduce(self, train_batch, group_name: str,
                              world_size: int) -> Dict[str, float]:
        """One epoch pass over the local shard, allreducing grads per
        minibatch across the learner collective group."""
        from ray_tpu.util import collective

        hps = self.hps
        batch = {k: jnp.asarray(v) for k, v in train_batch.items()}
        n = next(iter(batch.values())).shape[0]
        if n == 0:
            raise ValueError("empty train-batch shard")
        mb = max(min(hps.minibatch_size, n), 1)
        nmb = max(n // mb, 1)
        auxes = []
        for _ in range(hps.num_epochs):
            self._key, sub = jax.random.split(self._key)
            perm = jax.random.permutation(sub, n)
            for i in range(nmb):
                idx = perm[i * mb:(i + 1) * mb]
                mbatch = jax.tree_util.tree_map(lambda a: a[idx], batch)
                grads, aux = self._grads_jit(self.params, mbatch)
                grads = collective.allreduce(
                    jax.device_get(grads), group_name=group_name)
                grads = jax.tree_util.tree_map(
                    lambda g: jnp.asarray(g) / world_size, grads)
                self.params, self.opt_state = self._apply_jit(
                    self.params, self.opt_state, grads)
                auxes.append(jax.device_get(aux))
        metrics = {}
        for k in auxes[0]:
            metrics[k] = float(np.mean([a[k] for a in auxes]))
        return metrics

    def get_state(self):
        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state)}

    def set_state(self, state) -> None:
        self.params = jax.device_put(state["params"])
        self.opt_state = jax.device_put(state["opt_state"])

    def get_weights(self):
        return jax.device_get(self.params)

    def ping(self) -> bool:
        return True


class LearnerGroup:
    """1 local learner, or N learner actors with collective-allreduce DP.

    Reference: learner_group.py:72,146-161 — there the actors get torch
    DDP over NCCL; here the group wires a ray_tpu collective group.
    """

    def __init__(self, learner_factory: Callable[[], Learner],
                 num_learners: int = 0,
                 learner_resources: Optional[Dict[str, float]] = None):
        import uuid

        self.num_learners = num_learners
        if num_learners <= 1:
            self._local = learner_factory()
            self._actors: List = []
            self._group = None
        else:
            from ray_tpu.util import collective
            self._local = None
            remote_cls = ray_tpu.remote(
                **(learner_resources or {"num_cpus": 1}))(_LearnerActor)
            self._actors = [remote_cls.remote(learner_factory)
                            for _ in range(num_learners)]
            ray_tpu.get([a.ping.remote() for a in self._actors])
            # uuid, not a counter: group names rendezvous through GLOBAL
            # named actors, so per-process counters collide across trials
            self._group = f"learner_group_{uuid.uuid4().hex[:8]}"
            collective.create_collective_group(
                self._actors, num_learners, list(range(num_learners)),
                group_name=self._group)
            # all learners must start from identical params
            state = ray_tpu.get(self._actors[0].get_state.remote())
            ray_tpu.get([a.set_state.remote(state)
                         for a in self._actors[1:]])

    def update(self, train_batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        if self._local is not None:
            return self._local.update(train_batch)
        n = next(iter(train_batch.values())).shape[0]
        num = len(self._actors)
        futs = []
        for i, a in enumerate(self._actors):
            # strided shards keep every row and leave no actor empty-handed
            # (every actor MUST contribute to the allreduce); when n < num
            # learners, wrap so each still gets at least one row
            idx = np.arange(i, n, num) if i < n else np.array([i % n])
            sl = {k: v[idx] for k, v in train_batch.items()}
            futs.append(a.update_with_allreduce.remote(
                sl, self._group, num))
        all_metrics = ray_tpu.get(futs)
        return {k: float(np.mean([m[k] for m in all_metrics]))
                for k in all_metrics[0]}

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        return ray_tpu.get(self._actors[0].get_weights.remote())

    def get_state(self):
        if self._local is not None:
            return self._local.get_state()
        return ray_tpu.get(self._actors[0].get_state.remote())

    def set_state(self, state) -> None:
        if self._local is not None:
            self._local.set_state(state)
        else:
            ray_tpu.get([a.set_state.remote(state) for a in self._actors])

    def stop(self) -> None:
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        if self._group is not None:
            # learner actors are gone (no member will deregister), so the
            # driver reclaims the detached rendezvous actor directly
            from ray_tpu.util.collective.collective import _group_actor_name
            try:
                ray_tpu.kill(ray_tpu.get_actor(
                    _group_actor_name(self._group)))
            except Exception:
                pass
            self._group = None


class _LearnerActor:
    """Actor shell delegating to a Learner built in-process."""

    def __init__(self, learner_factory):
        self._learner = learner_factory()

    def __getattr__(self, name):
        return getattr(self._learner, name)
