"""ConnectorV2 pipelines: env→module, module→env, learner.

Reference parity: rllib/connectors/connector_v2.py and the pipeline dirs
rllib/connectors/{env_to_module,module_to_env,learner}/. A connector is a
callable batch transform; pipelines compose them. The compiled rollout
(env_runner.py) fuses the env/module connectors' hot work into XLA, so the
default pipelines here carry the learner-side transforms: flatten
time×env, GAE, advantage normalization. The env→module mean-std
observation filter (reference: env_to_module/mean_std_filter.py) also
lives in the compiled rollout — `AlgorithmConfig.env_runners(
observation_filter="mean_std")` normalizes obs in-program with running
Welford stats merged host-side and synchronized across remote runners
on every weight sync.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .core.postprocessing import compute_gae


class ConnectorV2:
    def __call__(self, batch: Dict[str, Any], **kwargs) -> Dict[str, Any]:
        raise NotImplementedError


class ConnectorPipelineV2(ConnectorV2):
    def __init__(self, connectors: Optional[List[ConnectorV2]] = None):
        self.connectors = list(connectors or [])

    def append(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.append(connector)
        return self

    def __call__(self, batch, **kwargs):
        for c in self.connectors:
            batch = c(batch, **kwargs)
        return batch


class GeneralAdvantageEstimation(ConnectorV2):
    def __init__(self, gamma: float = 0.99, lam: float = 0.95):
        self.gamma, self.lam = gamma, lam

    def __call__(self, batch, **kwargs):
        adv, targets = compute_gae(
            batch["rewards"], batch["vf"], batch["dones"],
            batch["final_vf"], gamma=self.gamma, lam=self.lam)
        batch["advantages"] = np.asarray(adv)
        batch["value_targets"] = np.asarray(targets)
        return batch


class NormalizeAdvantages(ConnectorV2):
    def __call__(self, batch, **kwargs):
        adv = batch["advantages"]
        batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        return batch


class FlattenTimeEnv(ConnectorV2):
    """[T, B, ...] → [T*B, ...] train batch (drops rollout-only keys)."""

    DROP = ("final_vf", "final_obs")

    def __call__(self, batch, **kwargs):
        out = {}
        for k, v in batch.items():
            if k in self.DROP:
                continue
            v = np.asarray(v)
            out[k] = v.reshape((-1,) + v.shape[2:])
        return out


def default_learner_pipeline(gamma: float = 0.99, lam: float = 0.95,
                             normalize_advantages: bool = True
                             ) -> ConnectorPipelineV2:
    pipe = ConnectorPipelineV2([GeneralAdvantageEstimation(gamma, lam),
                                FlattenTimeEnv()])
    if normalize_advantages:
        pipe.append(NormalizeAdvantages())
    return pipe
