from .algorithm import Algorithm, AlgorithmConfig
from .ppo import PPO, PPOConfig, PPOLearner
from .impala import IMPALA, IMPALAConfig, IMPALALearner, vtrace
from .appo import APPO, APPOConfig, APPOLearner
from .cql import CQL, CQLConfig, CQLLearner
