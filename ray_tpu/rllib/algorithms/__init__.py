from .algorithm import Algorithm, AlgorithmConfig
from .ppo import PPO, PPOConfig, PPOLearner
from .impala import IMPALA, IMPALAConfig, IMPALALearner, vtrace
from .appo import APPO, APPOConfig, APPOLearner
from .cql import CQL, CQLConfig, CQLLearner
from .dreamer_v3 import (DreamerV3, DreamerV3Config, DreamerV3Learner,
                         DreamerV3Module)
