"""DQN: Q-learning with replay + target network (double-Q by default).

Reference parity: rllib/algorithms/dqn/dqn.py (training_step: sample ->
replay add -> N replay updates -> periodic target sync) and
dqn_rainbow_torch_learner (TD loss). The replay update — TD loss, grad,
apply — compiles into one XLA program; the target network is a second
params pytree carried in learner state and hard-synced every
`target_network_update_freq` updates.

Multi-learner note: DQN's update path is replay-driven with learner-held
target params, so num_learners > 1 is rejected (the generic allreduce
path can't see the target pytree).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from ..core.learner import Learner
from ..core.rl_module import RLModule
from ..utils.replay_buffers import ReplayBuffer
from .algorithm import Algorithm, AlgorithmConfig


class _QNet(nn.Module):
    hiddens: Sequence[int]
    num_actions: int

    @nn.compact
    def __call__(self, x):
        for h in self.hiddens:
            x = nn.relu(nn.Dense(h)(x))
        return nn.Dense(self.num_actions)(x)


class QModule(RLModule):
    """Q-network module with epsilon-greedy exploration. Epsilon is a
    static model_config knob (it bakes into the compiled rollout); the
    reference's per-step schedule would force a recompile per change."""

    def __init__(self, spec, hiddens: Sequence[int] = (64, 64),
                 epsilon: float = 0.1):
        if not spec.discrete:
            raise ValueError("DQN requires a discrete action space")
        super().__init__(spec)
        self.epsilon = float(epsilon)
        self._net = _QNet(tuple(hiddens), spec.num_actions)

    def init(self, key):
        dummy = jnp.zeros((1, self.spec.obs_dim), jnp.float32)
        return self._net.init(key, dummy)

    def apply(self, params, obs):
        q = self._net.apply(params, obs)
        return {"action_dist_inputs": q, "vf": jnp.max(q, axis=-1)}

    def forward_exploration(self, params, obs, key):
        q = self._net.apply(params, obs)
        greedy = jnp.argmax(q, axis=-1)
        k1, k2 = jax.random.split(key)
        random_a = jax.random.randint(
            k1, greedy.shape, 0, self.spec.num_actions)
        explore = jax.random.uniform(k2, greedy.shape) < self.epsilon
        action = jnp.where(explore, random_a, greedy)
        # logp of the epsilon-greedy behavior policy (for the batch shape;
        # DQN's TD loss never reads it)
        logp = jnp.log(jnp.where(
            action == greedy,
            1 - self.epsilon + self.epsilon / self.spec.num_actions,
            self.epsilon / self.spec.num_actions))
        vf = jnp.max(q, axis=-1)
        return action, logp, vf


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(DQN)
        self.lr = 5e-4
        self.buffer_size = 50_000
        self.train_batch_size = 128
        self.num_updates_per_iter = 8
        self.target_network_update_freq = 100     # in learner updates
        self.num_steps_before_learning = 1_000
        self.double_q = True
        self.epsilon = 0.1


class DQNLearner(Learner):
    def __init__(self, spec, config: DQNConfig):
        self._gamma = config.gamma
        self._double_q = config.double_q
        self._target_freq = config.target_network_update_freq
        super().__init__(spec, config.learner_hyperparams(),
                         config.module_class, config.model_config,
                         seed=config.seed)
        self.target_params = self.params
        self._updates = 0
        self._td_jit = jax.jit(self._build_td_update())

    def _build_td_update(self):
        opt, module, gamma, double_q = (self.optimizer, self.module,
                                        self._gamma, self._double_q)

        def td_update(params, target_params, opt_state, batch):
            def loss_fn(p):
                q = module.apply(p, batch["obs"])["action_dist_inputs"]
                q_sa = jnp.take_along_axis(
                    q, batch["actions"][:, None].astype(jnp.int32),
                    axis=-1)[:, 0]
                q_next_t = module.apply(
                    target_params,
                    batch["next_obs"])["action_dist_inputs"]
                if double_q:
                    q_next_online = module.apply(
                        p, batch["next_obs"])["action_dist_inputs"]
                    a_star = jnp.argmax(q_next_online, axis=-1)
                    v_next = jnp.take_along_axis(
                        q_next_t, a_star[:, None], axis=-1)[:, 0]
                else:
                    v_next = jnp.max(q_next_t, axis=-1)
                target = (batch["rewards"]
                          + gamma * (1.0 - batch["dones"])
                          * jax.lax.stop_gradient(v_next))
                td = q_sa - jax.lax.stop_gradient(target)
                loss = jnp.mean(td ** 2)
                return loss, {"total_loss": loss,
                              "qf_mean": jnp.mean(q_sa),
                              "td_error_abs": jnp.mean(jnp.abs(td))}

            (_, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, aux

        return td_update

    # replaces the on-policy epoch machinery
    def update(self, train_batch: Dict[str, Any]) -> Dict[str, float]:
        batch = {k: jnp.asarray(v) for k, v in train_batch.items()}
        self.params, self.opt_state, aux = self._td_jit(
            self.params, self.target_params, self.opt_state, batch)
        self._updates += 1
        if self._updates % self._target_freq == 0:
            self.target_params = self.params
        return {k: float(v) for k, v in jax.device_get(aux).items()}

    def get_state(self):
        state = super().get_state()
        state["target_params"] = jax.device_get(self.target_params)
        state["updates"] = self._updates
        return state

    def set_state(self, state) -> None:
        super().set_state(state)
        self.target_params = jax.device_put(
            state.get("target_params", state["params"]))
        self._updates = state.get("updates", 0)


def _to_transitions(batch: Dict[str, Any]) -> Dict[str, Any]:
    """[T, B, ...] rollout -> flat [T*B] transitions with next_obs."""
    import numpy as np
    obs, final_obs = batch["obs"], batch["final_obs"]
    next_obs = np.concatenate([obs[1:], final_obs[None]], axis=0)
    flat = lambda a: np.asarray(a).reshape((-1,) + np.asarray(a).shape[2:])
    return {
        "obs": flat(obs).astype(np.float32),
        "actions": flat(batch["actions"]),
        "rewards": flat(batch["rewards"]).astype(np.float32),
        "dones": flat(batch["dones"]).astype(np.float32),
        "next_obs": flat(next_obs).astype(np.float32),
    }


class DQN(Algorithm):
    @classmethod
    def default_config(cls) -> DQNConfig:
        return DQNConfig()

    @classmethod
    def build_learner(cls, spec, config) -> DQNLearner:
        return DQNLearner(spec, config)

    def setup(self, config: Dict[str, Any]) -> None:
        algo_cfg = config.get("_algo_config")
        if algo_cfg is None:
            algo_cfg = type(self).default_config().update_from_dict(config)
        if algo_cfg.num_learners > 1:
            raise ValueError("DQN supports num_learners <= 1 (the target "
                             "network lives in learner state)")
        if algo_cfg.module_class is None:
            algo_cfg.module_class = QModule
            algo_cfg.model_config = dict(algo_cfg.model_config,
                                         epsilon=algo_cfg.epsilon)
        super().setup({"_algo_config": algo_cfg})
        self.replay = ReplayBuffer(algo_cfg.buffer_size,
                                   seed=algo_cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self._config
        result = self.env_runner_group.sample()
        self.replay.add_batch(_to_transitions(result["batch"]))
        learner_metrics: Dict[str, float] = {}
        if len(self.replay) >= cfg.num_steps_before_learning:
            for _ in range(cfg.num_updates_per_iter):
                learner_metrics = self.learner_group.update(
                    self.replay.sample(cfg.train_batch_size))
            self.env_runner_group.sync_weights(
                self.learner_group.get_weights())
        return self._roll_metrics(result["stats"], learner_metrics)
