"""CQL: conservative Q-learning for offline RL (Kumar et al. 2020).

Reference parity: rllib/algorithms/cql/cql.py (+ cql_torch_learner) —
SAC machinery trained purely from a recorded dataset, with the CQL(H)
conservative penalty on the critics: push down the Q of out-of-
distribution actions (logsumexp over sampled random + policy actions,
importance-corrected) and push up the Q of dataset actions. The whole
update stays one XLA program via SACLearner's critic-penalty hook.

Dataset shards are OfflineData .npz transitions and must carry
obs/actions/rewards/next_obs/dones.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..offline import OfflineData
from .algorithm import Algorithm
from .sac import SAC, SACConfig, SACLearner, _squash


class CQLConfig(SACConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = CQL
        self.input_path = None
        self.cql_alpha = 1.0          # penalty weight (reference default 1.0)
        self.cql_n_actions = 4        # sampled actions per logsumexp term

    def offline_data(self, *, input_path: str) -> "CQLConfig":
        self.input_path = input_path
        return self


class CQLLearner(SACLearner):
    def __init__(self, spec, config: CQLConfig):
        self._cql_alpha = config.cql_alpha
        self._cql_n = config.cql_n_actions
        super().__init__(spec, config)

    def _make_critic_penalty(self):
        module = self.module
        n, weight = self._cql_n, self._cql_alpha
        action_dim = self.module.spec.action_dim
        scale = getattr(module, "action_scale", 1.0)

        def penalty(p, batch, key, alpha):
            del alpha
            obs = batch["obs"]
            bsz = obs.shape[0]
            kr, kp = jax.random.split(key)

            # random actions, uniform over the action box
            a_rand = jax.random.uniform(
                kr, (n, bsz, action_dim), minval=-scale, maxval=scale)
            logp_rand = -action_dim * jnp.log(2.0 * scale)  # uniform density

            # current-policy actions at obs
            pi, _, _ = module.pi_and_q(p, obs, batch["actions"])
            mean, log_std = jnp.split(pi, 2, axis=-1)
            keys = jax.random.split(kp, n)
            a_pi, logp_pi = jax.vmap(
                lambda k: _squash(mean, log_std, k))(keys)

            def q_at(a):
                _, q1, q2 = module.pi_and_q(p, obs, a)
                return q1, q2

            q1_rand, q2_rand = jax.vmap(q_at)(a_rand)      # [n, B]
            q1_pi, q2_pi = jax.vmap(q_at)(a_pi * scale)

            def lse(q_rand, q_pi_):
                cat = jnp.concatenate(
                    [q_rand - logp_rand, q_pi_ - logp_pi], axis=0)
                return jax.scipy.special.logsumexp(cat, axis=0) \
                    - jnp.log(2.0 * n)

            _, q1_data, q2_data = module.pi_and_q(
                p, obs, batch["actions"])
            gap = (jnp.mean(lse(q1_rand, q1_pi) - q1_data)
                   + jnp.mean(lse(q2_rand, q2_pi) - q2_data))
            return weight * gap, {"cql_penalty": weight * gap}

        return penalty


class CQL(SAC):
    @classmethod
    def default_config(cls) -> CQLConfig:
        return CQLConfig()

    @classmethod
    def build_learner(cls, spec, config) -> CQLLearner:
        return CQLLearner(spec, config)

    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        cfg = self._config
        if not getattr(cfg, "input_path", None):
            raise ValueError("CQL requires .offline_data(input_path=...)")
        self.offline = OfflineData(cfg.input_path, seed=cfg.seed)
        if "next_obs" not in self.offline.data:
            raise ValueError(
                "CQL shards need next_obs (record transition tuples, "
                "not policy-only batches)")

    def training_step(self) -> Dict[str, Any]:
        cfg = self._config
        learner_metrics: Dict[str, float] = {}
        for _ in range(cfg.num_updates_per_iter):
            learner_metrics = self.learner_group.update(
                self.offline.sample(cfg.train_batch_size))
        # evaluation rollout with the learned policy
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        result = self.env_runner_group.sample()
        return self._roll_metrics(result["stats"], learner_metrics)
