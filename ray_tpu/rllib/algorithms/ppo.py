"""PPO: clipped-surrogate policy optimization.

Reference parity: rllib/algorithms/ppo/ppo.py:388 (training_step) and
ppo_torch_learner (clipped loss). The loss and the epoch/minibatch SGD
loop compile into one XLA program via the base Learner.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from .. import connectors
from ..core.learner import Learner
from .algorithm import Algorithm, AlgorithmConfig


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(PPO)
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.normalize_advantages = True


class PPOLearner(Learner):
    def __init__(self, spec, config: PPOConfig):
        self._clip = config.clip_param
        self._vf_coeff = config.vf_loss_coeff
        self._ent_coeff = config.entropy_coeff
        super().__init__(spec, config.learner_hyperparams(),
                         config.module_class, config.model_config,
                         seed=config.seed)

    def compute_loss(self, params, mb):
        out = self.module.forward_train(params, mb["obs"])
        dist = self.module.dist
        inputs = out["action_dist_inputs"]
        logp = dist.log_prob(inputs, mb["actions"])
        ratio = jnp.exp(logp - mb["logp"])
        adv = mb["advantages"]
        surr = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - self._clip, 1 + self._clip) * adv)
        policy_loss = -jnp.mean(surr)
        vf_loss = jnp.mean((out["vf"] - mb["value_targets"]) ** 2)
        entropy = jnp.mean(dist.entropy(inputs))
        loss = (policy_loss + self._vf_coeff * vf_loss
                - self._ent_coeff * entropy)
        return loss, {
            "total_loss": loss, "policy_loss": policy_loss,
            "vf_loss": vf_loss, "entropy": entropy,
            "kl": jnp.mean(mb["logp"] - logp),
        }


class PPO(Algorithm):
    @classmethod
    def default_config(cls) -> PPOConfig:
        return PPOConfig()

    @classmethod
    def build_learner(cls, spec, config) -> PPOLearner:
        return PPOLearner(spec, config)

    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        cfg = self._config
        self._learner_pipeline = connectors.default_learner_pipeline(
            gamma=cfg.gamma, lam=cfg.lambda_,
            normalize_advantages=getattr(cfg, "normalize_advantages", True))

    def training_step(self) -> Dict[str, Any]:
        result = self.env_runner_group.sample()
        train_batch = self._learner_pipeline(result["batch"])
        learner_metrics = self.learner_group.update(train_batch)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        return self._roll_metrics(result["stats"], learner_metrics)
