"""DreamerV3: model-based RL — learn a world model, act in imagination.

Reference parity: rllib/algorithms/dreamerv3 (the reference's TF
implementation of Hafner et al. 2023). Rebuilt TPU-native and compact:

- **World model**: encoder -> RSSM (GRU deterministic state + discrete
  categorical latents with unimix + straight-through sampling) with
  prior/posterior heads, plus decoder / reward / continue heads. All
  predictions in symlog space; KL uses the v3 dyn/rep split with free
  bits.
- **Behavior**: actor-critic trained ENTIRELY in imagination — H-step
  prior rollouts from every posterior state, lambda-returns with
  predicted continues, percentile-EMA return normalization, REINFORCE
  actor (discrete) + entropy. Gradients are partitioned by
  stop-gradient: imagination features are detached for the actor and
  critic losses, so three param groups train under one jitted update
  with per-group learning rates (optax.multi_transform).
- **Acting**: the SAME world model filters observations online — the
  env runner threads recurrent state (h, z, a_prev) through its
  compiled rollout scan and resets it on episode end (the
  module.initial_state hook in env/env_runner.py).
- **Replay**: fragment ring buffer; training samples [B, L] windows
  with is_first flags (cold-start at the window head + on in-window
  episode boundaries), the standard stateless-replay formulation.

Remaining simplification vs the paper (documented, not hidden): no
critic-EMA regularizer. Reward and value use the paper's TWOHOT
discretized regression over symexp-spaced bins (MSE fallback via
twohot_bins=0).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.learner import Learner
from ..core.rl_module import RLModule
from .algorithm import Algorithm, AlgorithmConfig


def symlog(x):
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def twohot_encode(y_symlog, bins):
    """Distribute each symlog-space target over its two neighboring
    bins (Hafner et al. 2023 eq. for discretized regression)."""
    y = jnp.clip(y_symlog, bins[0], bins[-1])
    idx = jnp.clip(jnp.searchsorted(bins, y) - 1, 0, bins.shape[0] - 2)
    lo, hi = bins[idx], bins[idx + 1]
    w_hi = (y - lo) / jnp.maximum(hi - lo, 1e-8)
    onehot_lo = jax.nn.one_hot(idx, bins.shape[0])
    onehot_hi = jax.nn.one_hot(idx + 1, bins.shape[0])
    return onehot_lo * (1 - w_hi)[..., None] + onehot_hi * w_hi[..., None]


def twohot_ce(logits, y_symlog, bins):
    target = twohot_encode(y_symlog, bins)
    return -jnp.sum(target * jax.nn.log_softmax(logits, -1), -1)


def twohot_mean(logits, bins):
    """Expected value in symlog space -> real space."""
    return symexp(jnp.sum(jax.nn.softmax(logits, -1) * bins, -1))


class _MLP(nn.Module):
    hiddens: Sequence[int]
    out: int

    @nn.compact
    def __call__(self, x):
        for h in self.hiddens:
            x = nn.silu(nn.Dense(h)(x))
        return nn.Dense(self.out)(x)


class _SeqCell(nn.Module):
    """h' = GRU(h, f(z, a)) — the RSSM deterministic path."""
    units: int
    deter: int

    @nn.compact
    def __call__(self, h, z_flat, a_onehot):
        x = nn.silu(nn.Dense(self.units)(
            jnp.concatenate([z_flat, a_onehot], -1)))
        h2, _ = nn.GRUCell(self.deter)(h, x)
        return h2


def _unimix_logits(logits, classes, unimix):
    probs = jax.nn.softmax(logits, -1)
    probs = (1.0 - unimix) * probs + unimix / classes
    return jnp.log(probs)


def _sample_latent(logits, key, stoch, classes, unimix):
    """Straight-through categorical sample -> flat one-hot [.., S*C]."""
    lg = _unimix_logits(logits.reshape(logits.shape[:-1] + (stoch, classes)),
                        classes, unimix)
    idx = jax.random.categorical(key, lg, axis=-1)
    onehot = jax.nn.one_hot(idx, classes)
    probs = jax.nn.softmax(lg, -1)
    st = onehot + probs - jax.lax.stop_gradient(probs)
    return st.reshape(st.shape[:-2] + (stoch * classes,))


def _kl_categorical(lp, lq, stoch, classes):
    """KL(p || q) for flat [.., S*C] logits, summed over latent dims."""
    shape = lp.shape[:-1] + (stoch, classes)
    p = jax.nn.softmax(lp.reshape(shape), -1)
    logp = jax.nn.log_softmax(lp.reshape(shape), -1)
    logq = jax.nn.log_softmax(lq.reshape(shape), -1)
    return jnp.sum(p * (logp - logq), axis=(-2, -1))


class DreamerV3Module(RLModule):
    """World model + actor + critic; recurrent acting via
    initial_state/forward_exploration(state)."""

    def __init__(self, spec, deter: int = 256, stoch: int = 8,
                 classes: int = 8, units: int = 128, embed: int = 128,
                 unimix: float = 0.01, twohot_bins: int = 63):
        if not spec.discrete:
            raise ValueError("this DreamerV3 build supports discrete "
                             "action spaces")
        super().__init__(spec)
        self.deter, self.stoch, self.classes = deter, stoch, classes
        self.units, self.unimix = units, unimix
        self.zdim = stoch * classes
        A = spec.num_actions
        D = spec.obs_dim
        feat = deter + self.zdim
        self._enc = _MLP((units, units), embed)
        self._cell = _SeqCell(units, deter)
        self._prior = _MLP((units,), self.zdim)
        self._post = _MLP((units,), self.zdim)
        self._dec = _MLP((units, units), D)
        # twohot discretized regression (paper): symlog-spaced bins;
        # twohot_bins=0 falls back to scalar symlog-MSE heads
        self.nbins = int(twohot_bins)
        if self.nbins == 1:
            raise ValueError(
                "twohot_bins must be 0 (scalar symlog-MSE heads) or "
                ">= 2 — a single bin makes the CE loss identically "
                "zero and the heads untrainable")
        head_out = self.nbins if self.nbins else 1
        self.bins = (jnp.linspace(-20.0, 20.0, self.nbins)
                     if self.nbins else None)
        self._rew = _MLP((units,), head_out)
        self._cont = _MLP((units,), 1)
        self._actor = _MLP((units, units), A)
        self._critic = _MLP((units, units), head_out)
        self._feat = feat

    # ------------------------------------------------------------- params
    def init(self, key):
        ks = jax.random.split(key, 9)
        D, A = self.spec.obs_dim, self.spec.num_actions
        h = jnp.zeros((1, self.deter))
        z = jnp.zeros((1, self.zdim))
        a = jnp.zeros((1, A))
        obs = jnp.zeros((1, D))
        feat = jnp.zeros((1, self._feat))
        fa = jnp.zeros((1, self._feat + A))
        wm = {
            "enc": self._enc.init(ks[0], obs),
            "cell": self._cell.init(ks[1], h, z, a),
            "prior": self._prior.init(ks[2], h),
            "post": self._post.init(
                ks[3], jnp.zeros((1, self.deter + self._enc.out))),
            "dec": self._dec.init(ks[4], feat),
            "rew": self._rew.init(ks[5], fa),
            "cont": self._cont.init(ks[6], fa),
        }
        return {"wm": wm,
                "actor": self._actor.init(ks[7], feat),
                "critic": self._critic.init(ks[8], feat)}

    # ------------------------------------------------------- wm functions
    def _step_h(self, wm, h, z, a_onehot):
        return self._cell.apply(wm["cell"], h, z, a_onehot)

    def _posterior(self, wm, h, obs):
        embed = self._enc.apply(wm["enc"], obs)
        return self._post.apply(
            wm["post"], jnp.concatenate([h, embed], -1))

    def _head_mean(self, pred):
        """Raw head output -> real-space scalar (twohot expectation or
        symexp of the scalar head)."""
        if self.nbins:
            return twohot_mean(pred, self.bins)
        return symexp(pred[..., 0])

    def _head_loss(self, pred, y_symlog):
        """Regression loss of a raw head output toward a symlog-space
        target — ONE definition for reward and critic."""
        if self.nbins:
            return twohot_ce(pred, y_symlog, self.bins)
        return (pred[..., 0] - y_symlog) ** 2

    def _reward(self, wm, feat, a_onehot, raw=False):
        pred = self._rew.apply(
            wm["rew"], jnp.concatenate([feat, a_onehot], -1))
        return pred if raw else self._head_mean(pred)

    def _cont_logit(self, wm, feat, a_onehot):
        return self._cont.apply(
            wm["cont"], jnp.concatenate([feat, a_onehot], -1))[..., 0]

    def _value(self, params, feat, raw=False):
        pred = self._critic.apply(params["critic"], feat)
        return pred if raw else self._head_mean(pred)

    # ----------------------------------------------------- runner protocol
    def initial_state(self, params, batch_size: int):
        return (jnp.zeros((batch_size, self.deter)),
                jnp.zeros((batch_size, self.zdim)),
                jnp.zeros((batch_size, self.spec.num_actions)))

    def forward_exploration(self, params, obs, key, state):
        h, z, a_prev = state
        wm = params["wm"]
        h = self._step_h(wm, h, z, a_prev)
        k1, k2 = jax.random.split(key)
        z = _sample_latent(self._posterior(wm, h, symlog(obs)), k1,
                           self.stoch, self.classes, self.unimix)
        feat = jnp.concatenate([h, z], -1)
        logits = self._actor.apply(params["actor"], feat)
        action = jax.random.categorical(k2, logits, axis=-1)
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, -1), action[:, None], -1)[:, 0]
        vf = self._value(params, feat)
        a_onehot = jax.nn.one_hot(action, self.spec.num_actions)
        return action, logp, vf, (h, z, a_onehot)

    # the stateless hooks exist for runner bookkeeping only (final_vf
    # bootstrap is unused by Dreamer's replay training)
    def apply(self, params, obs):
        b = obs.shape[0]
        return {"action_dist_inputs":
                jnp.zeros((b, self.spec.num_actions)),
                "vf": jnp.zeros((b,))}

    def forward_train(self, params, obs):
        return self.apply(params, obs)


class DreamerV3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__(DreamerV3)
        self.lr_world = 4e-4
        self.lr_actor = 1e-4
        self.lr_critic = 1e-4
        self.grad_clip = 100.0
        self.seq_len = 16
        self.batch_seqs = 16
        self.imagine_horizon = 15
        self.buffer_fragments = 200
        self.num_updates_per_iter = 4
        self.free_bits = 1.0
        self.kl_dyn = 1.0
        self.kl_rep = 0.1
        self.lam = 0.95
        self.entropy = 3e-3
        self.unimix = 0.01
        self.twohot_bins = 63        # 0 = scalar symlog-MSE heads
        self.model_size: Dict[str, int] = {}   # deter/stoch/classes/units


class _FragmentReplay:
    """Ring of rollout fragments; samples [B, L] windows (per-env
    columns) with is_first at the window head + in-window boundaries."""

    def __init__(self, capacity: int, seq_len: int, seed: int = 0):
        self.capacity = capacity
        self.L = seq_len
        self.frags: list = []
        self.rng = np.random.default_rng(seed)

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        frag = {k: np.asarray(batch[k]) for k in
                ("obs", "actions", "rewards", "dones")}
        if frag["obs"].shape[0] < self.L:
            return                      # fragment shorter than a window
        self.frags.append(frag)
        if len(self.frags) > self.capacity:
            self.frags.pop(0)

    def __len__(self):
        return len(self.frags)

    def sample(self, n: int) -> Dict[str, np.ndarray]:
        out = {k: [] for k in ("obs", "actions", "rewards", "dones",
                               "is_first")}
        for _ in range(n):
            f = self.frags[self.rng.integers(len(self.frags))]
            T, B = f["actions"].shape[:2]
            b = int(self.rng.integers(B))
            s = int(self.rng.integers(T - self.L + 1))
            sl = slice(s, s + self.L)
            out["obs"].append(f["obs"][sl, b])
            out["actions"].append(f["actions"][sl, b])
            out["rewards"].append(f["rewards"][sl, b])
            dones = f["dones"][sl, b].astype(np.float32)
            out["dones"].append(dones)
            isf = np.zeros(self.L, np.float32)
            isf[0] = 1.0
            isf[1:] = dones[:-1]
            out["is_first"].append(isf)
        return {k: np.stack(v) for k, v in out.items()}


class DreamerV3Learner(Learner):
    def __init__(self, spec, config: DreamerV3Config):
        self._cfg = config
        super().__init__(spec, config.learner_hyperparams(),
                         config.module_class, config.model_config,
                         seed=config.seed)
        # per-group learning rates over the {wm, actor, critic} pytree
        def make(lr):
            return optax.chain(
                optax.clip_by_global_norm(config.grad_clip),
                optax.adam(lr, eps=1e-8))
        self.optimizer = optax.multi_transform(
            {"wm": make(config.lr_world),
             "actor": make(config.lr_actor),
             "critic": make(config.lr_critic)},
            {"wm": "wm", "actor": "actor", "critic": "critic"})
        self.opt_state = self.optimizer.init(self.params)
        # return-normalization EMA of the 5th..95th percentile spread
        self.ret_scale = 1.0
        self._dreamer_jit = jax.jit(self._build_update())

    def _build_update(self):
        m: DreamerV3Module = self.module
        cfg = self._cfg
        opt = self.optimizer
        A = m.spec.num_actions
        H = cfg.imagine_horizon
        gamma, lam = cfg.gamma, cfg.lam

        def observe(wm, obs, actions, is_first, key):
            """Posterior filtering over one [B, L] sequence batch."""
            B, L = actions.shape
            a_onehot = jax.nn.one_hot(actions, A)
            h0 = jnp.zeros((B, m.deter))
            z0 = jnp.zeros((B, m.zdim))
            aprev0 = jnp.zeros((B, A))
            keys = jax.random.split(key, L)

            def step(carry, t):
                h, z, aprev = carry
                first = is_first[:, t][:, None]
                h = h * (1.0 - first)
                z = z * (1.0 - first)
                aprev = aprev * (1.0 - first)
                h = m._step_h(wm, h, z, aprev)
                prior_lg = m._prior.apply(wm["prior"], h)
                post_lg = m._posterior(wm, h, obs[:, t])
                z = _sample_latent(post_lg, keys[t], m.stoch,
                                   m.classes, m.unimix)
                return (h, z, a_onehot[:, t]), (h, z, prior_lg, post_lg)

            _, (hs, zs, priors, posts) = jax.lax.scan(
                step, (h0, z0, aprev0), jnp.arange(L))
            # [L, B, ...] -> [B, L, ...]
            sw = lambda x: jnp.swapaxes(x, 0, 1)
            return sw(hs), sw(zs), sw(priors), sw(posts)

        def imagine(params, h, z, key):
            """H-step prior rollout from flattened start states."""
            wm = params["wm"]
            keys = jax.random.split(key, H)

            def step(carry, k):
                h, z = carry
                feat = jnp.concatenate([h, z], -1)
                k1, k2 = jax.random.split(k)
                logits = m._actor.apply(
                    params["actor"], jax.lax.stop_gradient(feat))
                a = jax.random.categorical(k1, logits, -1)
                a1 = jax.nn.one_hot(a, A)
                r = m._reward(wm, feat, a1)
                c = jax.nn.sigmoid(m._cont_logit(wm, feat, a1))
                h = m._step_h(wm, h, z, a1)
                z = _sample_latent(m._prior.apply(wm["prior"], h), k2,
                                   m.stoch, m.classes, m.unimix)
                return (h, z), (feat, a, r, c)

            _, (feats, acts, rews, conts) = jax.lax.scan(
                step, (h, z), keys)
            return feats, acts, rews, conts      # [H, N, ...]

        def update(params, opt_state, batch, key, ret_scale):
            k_obs, k_img = jax.random.split(key)

            def loss_fn(p):
                wm = p["wm"]
                obs = symlog(batch["obs"])
                hs, zs, priors, posts = observe(
                    wm, obs, batch["actions"], batch["is_first"], k_obs)
                feat = jnp.concatenate([hs, zs], -1)
                a1 = jax.nn.one_hot(batch["actions"], A)
                # --- world-model losses ---
                recon = m._dec.apply(wm["dec"], feat)
                l_rec = jnp.mean(jnp.sum((recon - obs) ** 2, -1))
                r_pred = m._reward(wm, feat, a1, raw=True)
                l_rew = jnp.mean(m._head_loss(
                    r_pred, symlog(batch["rewards"])))
                c_logit = m._cont_logit(wm, feat, a1)
                cont_t = 1.0 - batch["dones"]
                l_cont = jnp.mean(optax.sigmoid_binary_cross_entropy(
                    c_logit, cont_t))
                # KL over the SAME unimix-mixed distributions the
                # latents are sampled from — raw-logit KL would grow
                # unbounded as the posterior sharpens (the unimix floor
                # caps the log-ratio at ~log(classes/unimix))
                def mix(lg):
                    shaped = lg.reshape(lg.shape[:-1]
                                        + (m.stoch, m.classes))
                    return _unimix_logits(
                        shaped, m.classes, m.unimix).reshape(lg.shape)
                priors_u, posts_u = mix(priors), mix(posts)
                kl_dyn = _kl_categorical(
                    jax.lax.stop_gradient(posts_u), priors_u,
                    m.stoch, m.classes)
                kl_rep = _kl_categorical(
                    posts_u, jax.lax.stop_gradient(priors_u),
                    m.stoch, m.classes)
                l_kl = (cfg.kl_dyn * jnp.mean(
                            jnp.maximum(kl_dyn, cfg.free_bits))
                        + cfg.kl_rep * jnp.mean(
                            jnp.maximum(kl_rep, cfg.free_bits)))
                wm_loss = l_rec + l_rew + l_cont + l_kl

                # --- imagination ---
                B, L = batch["actions"].shape
                h0 = jax.lax.stop_gradient(hs.reshape(B * L, -1))
                z0 = jax.lax.stop_gradient(zs.reshape(B * L, -1))
                feats, acts, rews, conts = imagine(p, h0, z0, k_img)
                feats_sg = jax.lax.stop_gradient(feats)
                v_logits = m._value(p, feats_sg, raw=True)
                values = m._head_mean(v_logits)           # [H, N]
                # lambda-returns: R_t = r_t + gamma*c_t*((1-lam)*V_{t+1}
                # + lam*R_{t+1}); the state after the last imagined
                # action has no feature, so its value self-bootstraps
                # from step H-1 (compact-build approximation)
                disc = gamma * conts
                vnext = jnp.concatenate([values[1:], values[-1:]], 0)

                def back(nxt, t):
                    ret = rews[t] + disc[t] * (
                        (1 - lam) * vnext[t] + lam * nxt)
                    return ret, ret

                _, rets = jax.lax.scan(
                    back, vnext[-1], jnp.arange(H - 1, -1, -1))
                rets = rets[::-1]                         # [H, N]
                rets_sg = jax.lax.stop_gradient(rets)
                # critic regression toward the lambda-returns (same
                # head-loss definition as the reward head)
                l_critic = jnp.mean(m._head_loss(
                    v_logits, symlog(rets_sg)))
                # actor: REINFORCE with percentile-normalized advantage
                logits = m._actor.apply(p["actor"], feats_sg)
                logp_all = jax.nn.log_softmax(logits, -1)
                logp = jnp.take_along_axis(
                    logp_all, acts[..., None], -1)[..., 0]
                adv = (rets_sg - jax.lax.stop_gradient(values)) \
                    / jnp.maximum(ret_scale, 1.0)
                ent = -jnp.sum(jnp.exp(logp_all) * logp_all, -1)
                l_actor = -jnp.mean(logp * adv) \
                    - cfg.entropy * jnp.mean(ent)

                total = wm_loss + l_actor + l_critic
                new_scale = (jnp.percentile(rets_sg, 95)
                             - jnp.percentile(rets_sg, 5))
                aux = {"total_loss": total, "wm_loss": wm_loss,
                       "recon_loss": l_rec, "reward_loss": l_rew,
                       "cont_loss": l_cont, "kl_loss": l_kl,
                       "actor_loss": l_actor, "critic_loss": l_critic,
                       "entropy": jnp.mean(ent),
                       "imag_return_mean": jnp.mean(rets_sg),
                       "ret_spread": new_scale}
                return total, aux

            (_, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, aux

        return update

    def update(self, train_batch: Dict[str, Any]) -> Dict[str, float]:
        batch = {k: jnp.asarray(v) for k, v in train_batch.items()}
        batch["actions"] = batch["actions"].astype(jnp.int32)
        self._key, sub = jax.random.split(self._key)
        self.params, self.opt_state, aux = self._dreamer_jit(
            self.params, self.opt_state, batch, sub,
            jnp.float32(self.ret_scale))
        aux = {k: float(v) for k, v in jax.device_get(aux).items()}
        spread = aux.pop("ret_spread")
        self.ret_scale = 0.99 * self.ret_scale + 0.01 * max(spread, 1e-8)
        return aux

    def get_state(self):
        state = super().get_state()
        state["ret_scale"] = self.ret_scale
        return state

    def set_state(self, state) -> None:
        super().set_state(state)
        self.ret_scale = state.get("ret_scale", 1.0)


class DreamerV3(Algorithm):
    @classmethod
    def default_config(cls) -> DreamerV3Config:
        return DreamerV3Config()

    @classmethod
    def build_learner(cls, spec, config) -> DreamerV3Learner:
        return DreamerV3Learner(spec, config)

    def setup(self, config: Dict[str, Any]) -> None:
        algo_cfg = config.get("_algo_config")
        if algo_cfg is None:
            algo_cfg = type(self).default_config().update_from_dict(config)
        if algo_cfg.num_learners > 1:
            raise ValueError("DreamerV3 supports num_learners <= 1")
        if algo_cfg.module_class is None:
            algo_cfg.module_class = DreamerV3Module
            algo_cfg.model_config = dict(algo_cfg.model_config,
                                         unimix=algo_cfg.unimix,
                                         twohot_bins=algo_cfg.twohot_bins,
                                         **algo_cfg.model_size)
        if algo_cfg.rollout_fragment_length < algo_cfg.seq_len:
            raise ValueError(
                f"rollout_fragment_length "
                f"({algo_cfg.rollout_fragment_length}) must be >= "
                f"seq_len ({algo_cfg.seq_len}) — shorter fragments "
                f"can never yield a training window, and the replay "
                f"would silently stay empty forever")
        super().setup({"_algo_config": algo_cfg})
        self.replay = _FragmentReplay(algo_cfg.buffer_fragments,
                                      algo_cfg.seq_len,
                                      seed=algo_cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self._config
        result = self.env_runner_group.sample()
        self.replay.add(result["batch"])
        learner_metrics: Dict[str, float] = {}
        if len(self.replay) >= 2:
            for _ in range(cfg.num_updates_per_iter):
                learner_metrics = self.learner_group.update(
                    self.replay.sample(cfg.batch_seqs))
            self.env_runner_group.sync_weights(
                self.learner_group.get_weights())
        return self._roll_metrics(result["stats"], learner_metrics)
