"""IMPALA: async sampling + V-trace off-policy correction.

Reference parity: rllib/algorithms/impala/impala.py:599 (async EnvRunner
sampling, aggregation, vtrace learner). V-trace (Espeholt et al. 2018) is
a reverse `lax.scan`, jitted with the loss. Sampling is asynchronous:
the driver keeps one in-flight sample per env-runner actor, consumes
whichever lands first (ray_tpu.wait), updates, and re-arms that runner
with fresh weights — sampling and learning overlap instead of
lock-stepping like PPO.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core.learner import Learner
from .algorithm import Algorithm, AlgorithmConfig


@functools.partial(jax.jit, static_argnames=("gamma", "rho_bar", "c_bar"))
def vtrace(behavior_logp, target_logp, rewards, values, dones, final_value,
           *, gamma: float = 0.99, rho_bar: float = 1.0, c_bar: float = 1.0):
    """All inputs time-major [T, B] (final_value [B]). Returns
    (vs [T, B], pg_advantages [T, B])."""
    not_done = 1.0 - dones.astype(jnp.float32)
    rho = jnp.minimum(jnp.exp(target_logp - behavior_logp), rho_bar)
    c = jnp.minimum(jnp.exp(target_logp - behavior_logp), c_bar)
    next_values = jnp.concatenate([values[1:], final_value[None]], axis=0)
    deltas = rho * (rewards + gamma * next_values * not_done - values)

    def backward(acc, inp):
        delta, c_t, nd = inp
        acc = delta + gamma * nd * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        backward, jnp.zeros_like(final_value), (deltas, c, not_done),
        reverse=True)
    vs = vs_minus_v + values
    next_vs = jnp.concatenate([vs[1:], final_value[None]], axis=0)
    pg_adv = rho * (rewards + gamma * next_vs * not_done - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(IMPALA)
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.rho_bar = 1.0
        self.c_bar = 1.0
        self.num_epochs = 1          # each batch consumed once
        self.minibatch_size = 10 ** 9  # full batch


class IMPALALearner(Learner):
    """Minibatches are env-major [b, T, ...]; the loss transposes to
    time-major and runs the vtrace scan."""

    def __init__(self, spec, config: IMPALAConfig):
        self._cfg = config
        super().__init__(spec, config.learner_hyperparams(),
                         config.module_class, config.model_config,
                         seed=config.seed)

    def compute_loss(self, params, mb):
        cfg = self._cfg
        tm = lambda a: jnp.swapaxes(a, 0, 1)  # [b, T, ...] -> [T, b, ...]
        obs, actions = tm(mb["obs"]), tm(mb["actions"])
        out = self.module.forward_train(params, obs)
        dist = self.module.dist
        inputs = out["action_dist_inputs"]
        target_logp = dist.log_prob(inputs, actions)
        vs, pg_adv = vtrace(
            tm(mb["logp"]), target_logp, tm(mb["rewards"]), out["vf"],
            tm(mb["dones"]), mb["final_vf"], gamma=cfg.gamma,
            rho_bar=cfg.rho_bar, c_bar=cfg.c_bar)
        policy_loss = -jnp.mean(pg_adv * target_logp)
        vf_loss = jnp.mean((out["vf"] - vs) ** 2)
        entropy = jnp.mean(dist.entropy(inputs))
        loss = (policy_loss + cfg.vf_loss_coeff * vf_loss
                - cfg.entropy_coeff * entropy)
        return loss, {"total_loss": loss, "policy_loss": policy_loss,
                      "vf_loss": vf_loss, "entropy": entropy}


def _to_env_major(batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    out = {}
    for k, v in batch.items():
        out[k] = v if k in ("final_vf", "final_obs") \
            else np.swapaxes(v, 0, 1)
    return out


class IMPALA(Algorithm):
    @classmethod
    def default_config(cls) -> IMPALAConfig:
        return IMPALAConfig()

    @classmethod
    def build_learner(cls, spec, config) -> IMPALALearner:
        return IMPALALearner(spec, config)

    def training_step(self) -> Dict[str, Any]:
        result = self.env_runner_group.sample_async_next(
            self.learner_group.get_weights())
        train_batch = _to_env_major(result["batch"])
        learner_metrics = self.learner_group.update(train_batch)
        return self._roll_metrics(result["stats"], learner_metrics)
