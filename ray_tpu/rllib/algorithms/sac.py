"""SAC: soft actor-critic for continuous control.

Reference parity: rllib/algorithms/sac/sac.py + sac_torch_learner (actor,
twin-critic, and entropy-temperature losses; polyak-averaged target
critics). The whole replay update — three losses, three grads, apply,
polyak — is one XLA program.

The policy is a tanh-squashed diagonal Gaussian; alpha is auto-tuned
toward target entropy -action_dim (the standard heuristic).

Like DQN, num_learners > 1 is rejected (target critics live in learner
state, outside the generic allreduce path).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.learner import Learner
from ..core.rl_module import RLModule
from ..utils.replay_buffers import ReplayBuffer
from .algorithm import Algorithm, AlgorithmConfig
from .dqn import _to_transitions

LOG_STD_MIN, LOG_STD_MAX = -10.0, 2.0


class _SACNet(nn.Module):
    """Policy head + twin Q heads in ONE params tree."""

    hiddens: Sequence[int]
    action_dim: int

    def _mlp(self, x, out, name):
        for i, h in enumerate(self.hiddens):
            x = nn.relu(nn.Dense(h, name=f"{name}_{i}")(x))
        return nn.Dense(out, name=f"{name}_out")(x)

    @nn.compact
    def __call__(self, obs, action):
        pi = self._mlp(obs, 2 * self.action_dim, "pi")
        sa = jnp.concatenate([obs, action], axis=-1)
        q1 = self._mlp(sa, 1, "q1")[..., 0]
        q2 = self._mlp(sa, 1, "q2")[..., 0]
        return pi, q1, q2


def _squash(mean, log_std, key):
    """Sample a tanh-squashed gaussian action + its log-prob."""
    std = jnp.exp(jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX))
    eps = jax.random.normal(key, mean.shape)
    pre = mean + std * eps
    action = jnp.tanh(pre)
    logp = (-0.5 * (eps ** 2 + jnp.log(2 * jnp.pi)) - jnp.log(std)
            ).sum(-1)
    # tanh change of variables
    logp -= jnp.log(1 - action ** 2 + 1e-6).sum(-1)
    return action, logp


class SACModule(RLModule):
    def __init__(self, spec, hiddens: Sequence[int] = (64, 64),
                 action_scale: float = 1.0):
        if spec.discrete:
            raise ValueError("SAC requires a continuous action space")
        super().__init__(spec)
        self.action_scale = float(action_scale)
        self._net = _SACNet(tuple(hiddens), spec.action_dim)

    def init(self, key):
        dummy_o = jnp.zeros((1, self.spec.obs_dim), jnp.float32)
        dummy_a = jnp.zeros((1, self.spec.action_dim), jnp.float32)
        return self._net.init(key, dummy_o, dummy_a)

    def pi_and_q(self, params, obs, action):
        return self._net.apply(params, obs, action)

    def apply(self, params, obs):
        dummy_a = jnp.zeros(obs.shape[:-1] + (self.spec.action_dim,),
                            jnp.float32)
        pi, q1, _ = self._net.apply(params, obs, dummy_a)
        return {"action_dist_inputs": pi, "vf": q1}

    def forward_exploration(self, params, obs, key):
        out = self.apply(params, obs)
        mean, log_std = jnp.split(out["action_dist_inputs"], 2, axis=-1)
        action, logp = _squash(mean, log_std, key)
        return action * self.action_scale, logp, out["vf"]

    def forward_inference(self, params, obs):
        out = self.apply(params, obs)
        mean, _ = jnp.split(out["action_dist_inputs"], 2, axis=-1)
        return jnp.tanh(mean) * self.action_scale


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(SAC)
        self.lr = 3e-4
        self.buffer_size = 100_000
        self.train_batch_size = 256
        self.num_updates_per_iter = 8
        self.tau = 0.005                     # polyak for target critics
        self.initial_alpha = 0.2
        self.target_entropy = None           # default: -action_dim
        self.num_steps_before_learning = 1_000
        self.action_scale = 1.0


class SACLearner(Learner):
    def __init__(self, spec, config: SACConfig):
        self._gamma = config.gamma
        self._tau = config.tau
        self._target_entropy = (config.target_entropy
                                if config.target_entropy is not None
                                else -float(spec.action_dim))
        if config.module_class is None:
            config.module_class = SACModule
            config.model_config = dict(
                config.model_config, action_scale=config.action_scale)
        super().__init__(spec, config.learner_hyperparams(),
                         config.module_class, config.model_config,
                         seed=config.seed)
        self.target_params = self.params
        self.log_alpha = jnp.asarray(np.log(config.initial_alpha),
                                     jnp.float32)
        self._alpha_opt = optax.adam(config.lr)
        self._alpha_opt_state = self._alpha_opt.init(self.log_alpha)
        self._sac_jit = jax.jit(self._build_sac_update())

    def _make_critic_penalty(self):
        """Hook: extra critic regularizer (p, batch, key, alpha) ->
        (penalty, aux dict). CQL overrides; plain SAC has none."""
        return None

    def _build_sac_update(self):
        opt, alpha_opt = self.optimizer, self._alpha_opt
        module, gamma, tau = self.module, self._gamma, self._tau
        target_entropy = self._target_entropy
        penalty_fn = self._make_critic_penalty()

        def sac_update(params, target_params, opt_state,
                       log_alpha, alpha_opt_state, batch, key):
            k1, k2, k3 = jax.random.split(key, 3)
            alpha = jnp.exp(log_alpha)

            # --- critic + actor losses share one grad pass over params
            def loss_fn(p):
                pi_n, _, _ = module.pi_and_q(
                    target_params, batch["next_obs"], batch["actions"])
                mean_n, log_std_n = jnp.split(pi_n, 2, axis=-1)
                a_next, logp_next = _squash(mean_n, log_std_n, k1)
                _, tq1, tq2 = module.pi_and_q(
                    target_params, batch["next_obs"], a_next)
                v_next = jnp.minimum(tq1, tq2) - alpha * logp_next
                target = jax.lax.stop_gradient(
                    batch["rewards"]
                    + gamma * (1.0 - batch["dones"]) * v_next)
                _, q1, q2 = module.pi_and_q(
                    p, batch["obs"], batch["actions"])
                critic_loss = (jnp.mean((q1 - target) ** 2)
                               + jnp.mean((q2 - target) ** 2))
                pen_aux = {}
                if penalty_fn is not None:
                    penalty, pen_aux = penalty_fn(p, batch, k3, alpha)
                    critic_loss = critic_loss + penalty

                pi, _, _ = module.pi_and_q(
                    p, batch["obs"], batch["actions"])
                mean, log_std = jnp.split(pi, 2, axis=-1)
                a_pi, logp_pi = _squash(mean, log_std, k2)
                _, q1_pi, q2_pi = module.pi_and_q(p, batch["obs"], a_pi)
                q_pi = jnp.minimum(q1_pi, q2_pi)
                actor_loss = jnp.mean(alpha * logp_pi - q_pi)

                loss = critic_loss + actor_loss
                return loss, (critic_loss, actor_loss, logp_pi, q_pi,
                              pen_aux)

            (_, (critic_loss, actor_loss, logp_pi, q_pi, pen_aux)), \
                grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)

            # --- temperature
            def alpha_loss_fn(la):
                return -jnp.mean(jnp.exp(la) * jax.lax.stop_gradient(
                    logp_pi + target_entropy))

            alpha_loss, alpha_grad = jax.value_and_grad(alpha_loss_fn)(
                log_alpha)
            a_updates, alpha_opt_state = alpha_opt.update(
                alpha_grad, alpha_opt_state)
            log_alpha = log_alpha + a_updates

            # --- polyak target critics
            target_params = jax.tree_util.tree_map(
                lambda t, o: (1 - tau) * t + tau * o,
                target_params, params)
            aux = {"critic_loss": critic_loss, "actor_loss": actor_loss,
                   "alpha": jnp.exp(log_alpha), "alpha_loss": alpha_loss,
                   "q_mean": jnp.mean(q_pi),
                   "entropy": -jnp.mean(logp_pi), **pen_aux}
            return (params, target_params, opt_state, log_alpha,
                    alpha_opt_state, aux)

        return sac_update

    def update(self, train_batch: Dict[str, Any]) -> Dict[str, float]:
        self._key, sub = jax.random.split(self._key)
        batch = {k: jnp.asarray(v) for k, v in train_batch.items()}
        (self.params, self.target_params, self.opt_state, self.log_alpha,
         self._alpha_opt_state, aux) = self._sac_jit(
            self.params, self.target_params, self.opt_state,
            self.log_alpha, self._alpha_opt_state, batch, sub)
        return {k: float(v) for k, v in jax.device_get(aux).items()}

    def get_state(self):
        state = super().get_state()
        state["target_params"] = jax.device_get(self.target_params)
        state["log_alpha"] = float(self.log_alpha)
        return state

    def set_state(self, state) -> None:
        super().set_state(state)
        self.target_params = jax.device_put(
            state.get("target_params", state["params"]))
        if "log_alpha" in state:
            self.log_alpha = jnp.asarray(state["log_alpha"], jnp.float32)


class SAC(Algorithm):
    @classmethod
    def default_config(cls) -> SACConfig:
        return SACConfig()

    @classmethod
    def build_learner(cls, spec, config) -> SACLearner:
        return SACLearner(spec, config)

    def setup(self, config: Dict[str, Any]) -> None:
        algo_cfg = config.get("_algo_config")
        if algo_cfg is None:
            algo_cfg = type(self).default_config().update_from_dict(config)
        if algo_cfg.num_learners > 1:
            raise ValueError("SAC supports num_learners <= 1 (target "
                             "critics live in learner state)")
        if algo_cfg.module_class is None:
            algo_cfg.module_class = SACModule
            algo_cfg.model_config = dict(
                algo_cfg.model_config,
                action_scale=algo_cfg.action_scale)
        super().setup({"_algo_config": algo_cfg})
        self.replay = ReplayBuffer(algo_cfg.buffer_size,
                                   seed=algo_cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self._config
        result = self.env_runner_group.sample()
        self.replay.add_batch(_to_transitions(result["batch"]))
        learner_metrics: Dict[str, float] = {}
        if len(self.replay) >= cfg.num_steps_before_learning:
            for _ in range(cfg.num_updates_per_iter):
                learner_metrics = self.learner_group.update(
                    self.replay.sample(cfg.train_batch_size))
            self.env_runner_group.sync_weights(
                self.learner_group.get_weights())
        return self._roll_metrics(result["stats"], learner_metrics)
