"""APPO: asynchronous PPO — IMPALA's architecture, PPO's loss.

Reference parity: rllib/algorithms/appo/appo.py (APPO = IMPALA async
sampling/aggregation with a clipped-surrogate policy loss over V-trace
advantages instead of the plain importance-weighted PG loss). Everything
async (one in-flight sample per runner, re-armed with fresh weights)
is inherited from IMPALA; only the learner differs.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.learner import Learner
from .impala import IMPALA, IMPALAConfig, vtrace


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = APPO
        self.clip_param = 0.2         # PPO surrogate clip (reference: 0.4
        #                               default for APPO; 0.2 matches our PPO)
        self.num_epochs = 1           # async: each batch consumed once


class APPOLearner(Learner):
    """PPO clipped surrogate over V-trace targets; minibatches are
    env-major [b, T, ...] like IMPALA's."""

    def __init__(self, spec, config: APPOConfig):
        self._cfg = config
        super().__init__(spec, config.learner_hyperparams(),
                         config.module_class, config.model_config,
                         seed=config.seed)

    def compute_loss(self, params, mb):
        cfg = self._cfg
        tm = lambda a: jnp.swapaxes(a, 0, 1)
        obs, actions = tm(mb["obs"]), tm(mb["actions"])
        out = self.module.forward_train(params, obs)
        dist = self.module.dist
        inputs = out["action_dist_inputs"]
        target_logp = dist.log_prob(inputs, actions)
        behavior_logp = tm(mb["logp"])
        vs, pg_adv = vtrace(
            behavior_logp, target_logp, tm(mb["rewards"]), out["vf"],
            tm(mb["dones"]), mb["final_vf"], gamma=cfg.gamma,
            rho_bar=cfg.rho_bar, c_bar=cfg.c_bar)
        adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)
        ratio = jnp.exp(target_logp - behavior_logp)
        clipped = jnp.clip(ratio, 1.0 - cfg.clip_param,
                           1.0 + cfg.clip_param)
        policy_loss = -jnp.mean(jnp.minimum(ratio * adv, clipped * adv))
        vf_loss = jnp.mean((out["vf"] - vs) ** 2)
        entropy = jnp.mean(dist.entropy(inputs))
        loss = (policy_loss + cfg.vf_loss_coeff * vf_loss
                - cfg.entropy_coeff * entropy)
        return loss, {"total_loss": loss, "policy_loss": policy_loss,
                      "vf_loss": vf_loss, "entropy": entropy,
                      "clip_fraction": jnp.mean(
                          (jnp.abs(ratio - 1.0) > cfg.clip_param)
                          .astype(jnp.float32))}


class APPO(IMPALA):
    @classmethod
    def default_config(cls) -> APPOConfig:
        return APPOConfig()

    @classmethod
    def build_learner(cls, spec, config) -> APPOLearner:
        return APPOLearner(spec, config)
