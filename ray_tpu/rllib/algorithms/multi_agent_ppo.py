"""Multi-agent PPO: per-policy learners over a shared compiled rollout.

Reference parity: rllib/algorithms/ppo with
AlgorithmConfig.multi_agent(policies=..., policy_mapping_fn=...)
(algorithm_config.py:2766) and the MultiLearner update path
(core/learner/learner.py update_from_batch with a MultiAgentBatch).
Here each policy gets its own PPOLearner (own optimizer state); the
rollout is one XLA program for all policies (multi_agent_env_runner.py).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from .. import connectors
from ..env.multi_agent_env import make_multi_agent_env
from ..env.multi_agent_env_runner import (MultiAgentEnvRunnerGroup,
                                          call_mapping_fn)
from .algorithm import Algorithm, AlgorithmConfig
from .ppo import PPOConfig, PPOLearner


class MultiAgentPPOConfig(PPOConfig):
    """PPOConfig + the reference's .multi_agent() section."""

    def __init__(self):
        super().__init__()
        self.algo_class = MultiAgentPPO
        self.policies: Dict[str, Optional[dict]] = {}
        self.policy_mapping_fn: Optional[Callable[[str], str]] = None

    def multi_agent(self, *, policies: Optional[Dict[str, Optional[dict]]]
                    = None,
                    policy_mapping_fn: Optional[Callable] = None
                    ) -> "MultiAgentPPOConfig":
        """policies: {policy_id: None | per-policy config overrides
        (module_class / model_config / any training key)}.
        policy_mapping_fn: agent_id -> policy_id (evaluated once per
        agent — see multi_agent_env_runner.py docstring)."""
        if policies is not None:
            self.policies = dict(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def per_policy_config(self, policy_id: str) -> "MultiAgentPPOConfig":
        overrides = self.policies.get(policy_id) or {}
        cfg = self.copy()
        for k, v in overrides.items():
            if not hasattr(cfg, k):
                raise ValueError(
                    f"unknown per-policy override {k!r} for {policy_id!r}")
            setattr(cfg, k, v)
        return cfg


class MultiAgentPPO(Algorithm):
    """PPO over a MultiAgentJaxEnv with N independent policies."""

    @classmethod
    def default_config(cls) -> MultiAgentPPOConfig:
        return MultiAgentPPOConfig()

    def setup(self, config: Dict[str, Any]) -> None:
        algo_cfg = config.get("_algo_config")
        if algo_cfg is None:
            algo_cfg = type(self).default_config().update_from_dict(config)
        self._config = algo_cfg
        cfg = self._config
        if cfg.env is None:
            raise ValueError("no environment configured")
        env = make_multi_agent_env(cfg.env)
        mapping_fn = cfg.policy_mapping_fn
        if mapping_fn is None:
            if cfg.policies:
                raise ValueError(
                    "policies configured but no policy_mapping_fn")
            # default: one policy per agent, named after the agent
            mapping_fn = lambda aid: aid
        module_classes = {}
        model_configs = {}
        for pid in (cfg.policies or
                    {call_mapping_fn(mapping_fn, a): None
                     for a in env.agents}):
            pcfg = cfg.per_policy_config(pid)
            if pcfg.module_class is not None:
                module_classes[pid] = pcfg.module_class
            if pcfg.model_config:
                model_configs[pid] = pcfg.model_config
        if getattr(cfg, "observation_filter", None):
            raise ValueError(
                "observation_filter is not supported by the multi-agent "
                "env runner (per-agent obs spaces would each need their "
                "own running stats); unset it for MultiAgentPPO")
        if getattr(cfg, "framestack", 1) > 1:
            raise ValueError(
                "framestack is not supported by the multi-agent env "
                "runner; unset it for MultiAgentPPO")
        self.env_runner_group = MultiAgentEnvRunnerGroup(
            cfg.env, mapping_fn, num_env_runners=cfg.num_env_runners,
            num_envs_per_runner=cfg.num_envs_per_env_runner,
            rollout_length=cfg.rollout_fragment_length, seed=cfg.seed,
            module_classes=module_classes, model_configs=model_configs)
        # one learner (own optimizer + hyperparams) per policy
        self.learners: Dict[str, PPOLearner] = {}
        self._pipelines = {}
        for pid, spec in self.env_runner_group.module_specs.items():
            pcfg = cfg.per_policy_config(pid)
            if "seed" not in (cfg.policies.get(pid) or {}):
                # distinct init per policy: identical seeds would start
                # same-architecture policies byte-identical (their params
                # overwrite the runner's per-module init at sync time)
                import zlib
                pcfg.seed = cfg.seed + 1 + (
                    zlib.crc32(pid.encode()) % 100003)
            self.learners[pid] = PPOLearner(spec, pcfg)
            self._pipelines[pid] = connectors.default_learner_pipeline(
                gamma=pcfg.gamma, lam=pcfg.lambda_,
                normalize_advantages=getattr(
                    pcfg, "normalize_advantages", True))
        self.env_runner_group.sync_weights(
            {pid: lr.get_weights() for pid, lr in self.learners.items()})
        self._lifetime_env_steps = 0
        self._last_return_mean = float("nan")
        self._last_agent_returns: Dict[str, float] = {}

    def training_step(self) -> Dict[str, Any]:
        result = self.env_runner_group.sample()
        learner_metrics: Dict[str, float] = {}
        for pid, batch in result["batches"].items():
            train_batch = self._pipelines[pid](batch)
            for k, v in self.learners[pid].update(train_batch).items():
                learner_metrics[f"{pid}/{k}"] = v
        self.env_runner_group.sync_weights(
            {pid: lr.get_weights() for pid, lr in self.learners.items()})
        return self._roll_metrics(result["stats"], learner_metrics)

    def _roll_metrics(self, stats, learner_metrics):
        out = super()._roll_metrics(stats, learner_metrics)
        agent_returns = stats.get("agent_episode_returns")
        if stats["num_episodes"] > 0 and agent_returns:
            self._last_agent_returns = dict(agent_returns)
        out["agent_episode_returns"] = dict(self._last_agent_returns)
        out["num_agent_steps_sampled"] = stats.get("agent_steps", 0)
        return out

    # -- Trainable ----------------------------------------------------------
    def save_checkpoint(self) -> Any:
        return {"learners": {pid: lr.get_state()
                             for pid, lr in self.learners.items()},
                "lifetime_env_steps": self._lifetime_env_steps}

    def load_checkpoint(self, state: Any) -> None:
        for pid, lstate in state["learners"].items():
            self.learners[pid].set_state(lstate)
        self._lifetime_env_steps = state.get("lifetime_env_steps", 0)
        self.env_runner_group.sync_weights(
            {pid: lr.get_weights() for pid, lr in self.learners.items()})

    def cleanup(self) -> None:
        self.env_runner_group.stop()
