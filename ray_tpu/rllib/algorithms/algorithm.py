"""Algorithm + AlgorithmConfig: the RLlib-equivalent driver.

Reference parity: rllib/algorithms/algorithm.py:198 (Algorithm is a Tune
Trainable; step :923, training_step :1747) and algorithm_config.py (fluent
builder). An Algorithm owns an EnvRunnerGroup (sampling) and a
LearnerGroup (gradients); `train()` comes from ray_tpu.tune.Trainable so
algorithms run directly under the Tune controller.
"""

from __future__ import annotations

import copy
import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Type

from ray_tpu.tune.trainable import Trainable

from ..core.learner import Learner, LearnerGroup, LearnerHyperparams
from ..env.env_runner_group import EnvRunnerGroup
from ..env.jax_env import make_env


class AlgorithmConfig:
    """Fluent builder; sections mirror the reference's
    (.environment/.env_runners/.training/.learners/.rl_module)."""

    def __init__(self, algo_class: Optional[Type["Algorithm"]] = None):
        self.algo_class = algo_class
        self.env = None
        self.seed = 0
        # env runners
        self.num_env_runners = 0
        self.num_envs_per_env_runner = 8
        self.rollout_fragment_length = 128
        #: None | "mean_std" — running obs normalization inside the
        #: compiled rollout (reference: connectors mean_std_filter)
        self.observation_filter: Optional[str] = None
        #: frames concatenated feature-wise for the module (reference:
        #: connectors frame stacking); 1 = off
        self.framestack: int = 1
        # training
        self.lr = 3e-4
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.grad_clip = 0.5
        self.num_epochs = 4
        self.minibatch_size = 256
        # learners
        self.num_learners = 0
        # module
        self.module_class = None
        self.model_config: Dict[str, Any] = {}

    # -- fluent sections ----------------------------------------------------
    def environment(self, env) -> "AlgorithmConfig":
        self.env = env
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None,
                    observation_filter: Optional[str] = None,
                    framestack: Optional[int] = None
                    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if observation_filter is not None:
            self.observation_filter = observation_filter
        if framestack is not None:
            self.framestack = framestack
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def learners(self, *, num_learners: Optional[int] = None
                 ) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def rl_module(self, *, module_class: Optional[type] = None,
                  model_config: Optional[Dict[str, Any]] = None
                  ) -> "AlgorithmConfig":
        if module_class is not None:
            self.module_class = module_class
        if model_config is not None:
            self.model_config = dict(model_config)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def to_dict(self) -> Dict[str, Any]:
        d = dict(self.__dict__)
        d.pop("algo_class", None)
        return d

    def update_from_dict(self, d: Dict[str, Any]) -> "AlgorithmConfig":
        for k, v in d.items():
            if k.startswith("_"):
                continue
            if not hasattr(self, k):
                # fail loudly: a mistyped hyperparameter silently running
                # with its default is the worst sweep outcome
                raise ValueError(
                    f"unknown config key {k!r}; valid keys: "
                    f"{sorted(a for a in vars(self) if a != 'algo_class')}")
            setattr(self, k, v)
        return self

    def learner_hyperparams(self) -> LearnerHyperparams:
        return LearnerHyperparams(
            lr=self.lr, grad_clip=self.grad_clip,
            num_epochs=self.num_epochs, minibatch_size=self.minibatch_size)

    def build(self) -> "Algorithm":
        if self.algo_class is None:
            raise ValueError("config has no algo_class; use e.g. PPOConfig")
        return self.algo_class(config={"_algo_config": self})


class Algorithm(Trainable):
    """Subclasses define default_config(), build_learner(config) and
    training_step()."""

    _config: AlgorithmConfig

    @classmethod
    def default_config(cls) -> AlgorithmConfig:
        return AlgorithmConfig(cls)

    @classmethod
    def build_learner(cls, spec, config: AlgorithmConfig) -> Learner:
        raise NotImplementedError

    def setup(self, config: Dict[str, Any]) -> None:
        algo_cfg = config.get("_algo_config")
        if algo_cfg is None:
            algo_cfg = type(self).default_config().update_from_dict(config)
        self._config = algo_cfg
        cfg = self._config
        if cfg.env is None:
            raise ValueError("no environment configured")
        from ..env.jax_env import stacked_spec
        # the learner's module must match the runner's stacked width
        spec = stacked_spec(make_env(cfg.env).spec, cfg.framestack)
        self.env_runner_group = EnvRunnerGroup(
            cfg.env, num_env_runners=cfg.num_env_runners,
            num_envs_per_runner=cfg.num_envs_per_env_runner,
            rollout_length=cfg.rollout_fragment_length, seed=cfg.seed,
            module_class=cfg.module_class, model_config=cfg.model_config,
            obs_filter=cfg.observation_filter,
            framestack=getattr(cfg, "framestack", 1))
        cls = type(self)
        self.learner_group = LearnerGroup(
            lambda: cls.build_learner(spec, cfg),
            num_learners=cfg.num_learners)
        # start sampling with the learner's weights
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        self._lifetime_env_steps = 0
        self._last_return_mean = float("nan")

    # -- Trainable ----------------------------------------------------------
    def step(self) -> Dict[str, Any]:
        t0 = time.time()
        metrics = self.training_step()
        metrics.setdefault("time_this_iter_s", time.time() - t0)
        return metrics

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self) -> Any:
        return {"learner": self.learner_group.get_state(),
                "lifetime_env_steps": self._lifetime_env_steps,
                # a restored policy must see obs normalized by the
                # stats its weights were trained against
                "obs_filter": self.env_runner_group.get_filter_state()}

    def load_checkpoint(self, state: Any) -> None:
        self.learner_group.set_state(state["learner"])
        self._lifetime_env_steps = state.get("lifetime_env_steps", 0)
        self.env_runner_group.set_filter_state(state.get("obs_filter"))
        self.env_runner_group.sync_weights(self.learner_group.get_weights())

    def cleanup(self) -> None:
        self.env_runner_group.stop()
        self.learner_group.stop()

    # -- shared metric plumbing --------------------------------------------
    def _roll_metrics(self, stats: Dict[str, Any],
                      learner_metrics: Dict[str, float]) -> Dict[str, Any]:
        self._lifetime_env_steps += stats["env_steps"]
        if stats["num_episodes"] > 0:
            self._last_return_mean = stats["episode_return_mean"]
        out = {
            "episode_return_mean": self._last_return_mean,
            "episode_len_mean": stats.get("episode_len_mean", float("nan")),
            "num_episodes": stats["num_episodes"],
            "num_env_steps_sampled": stats["env_steps"],
            "num_env_steps_sampled_lifetime": self._lifetime_env_steps,
        }
        out.update({f"learner/{k}": v for k, v in learner_metrics.items()})
        return out
