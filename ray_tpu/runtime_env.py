"""Public runtime-env surface: plugin API + context.

Reference parity: python/ray/runtime_env + the RuntimeEnvPlugin
extension point (python/ray/_private/runtime_env/plugin.py:24,118).
Register a plugin in the process hosting the node daemon (or point
RAY_TPU_RUNTIME_ENV_PLUGINS at "module:Class" so every daemon loads it):

    class MyPlugin(ray_tpu.runtime_env.RuntimeEnvPlugin):
        name = "my_key"
        async def create(self, value, ctx, node):
            ctx.env_vars["MY_KEY"] = str(value)

    ray_tpu.runtime_env.register_plugin(MyPlugin())
    ray_tpu.remote(runtime_env={"my_key": 1})(fn)
"""

from ._private.runtime_env import (NodeServices, RuntimeEnvContext,
                                   RuntimeEnvPlugin, URICache,
                                   register_plugin)

__all__ = ["RuntimeEnvPlugin", "RuntimeEnvContext", "NodeServices",
           "URICache", "register_plugin"]
