"""Node providers: how the autoscaler actually adds/removes capacity.

Reference parity: python/ray/autoscaler/node_provider.py (NodeProvider
interface), _private/fake_multi_node/node_provider.py (FakeMultiNode for
tests), _private/gcp/* + tpu_command_runner.py (GCP TPU provisioning).

The TPU-native story: a "node" is a TPU VM (or one worker of a pod
slice). Gang demand for a slice arrives as a placement group whose
bundles carry the slice's per-host resources plus the
`TPU-<type>-head` marker resource (accelerators/tpu.py) — a node type
whose resources include that marker satisfies the gang head.
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
import threading
import uuid
from typing import Dict, List, Optional


@dataclasses.dataclass
class NodeType:
    """A launchable node shape."""

    name: str
    resources: Dict[str, float]
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    max_workers: int = 10

    def covers(self, demand: Dict[str, float]) -> bool:
        return all(self.resources.get(k, 0.0) >= v
                   for k, v in demand.items())


class NodeProvider:
    """Interface. Implementations own the node lifecycle; node identity
    is the ray_tpu node_id once the daemon registers."""

    def create_node(self, node_type: NodeType) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> bool:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class ProcessNodeProvider(NodeProvider):
    """Nodes as real OS processes via the CLI join path
    (cluster_utils.Cluster -> `ray_tpu start --address`): scale-up
    actually execs what a GKE pod or TPU-VM startup script runs, so the
    autoscaler's multi-host slice join story is exercised end to end —
    gang demand on a TPU-...-head marker becomes a separate daemon
    process registering over the wire."""

    def __init__(self):
        self._cluster = None
        self._nodes: Dict[str, NodeType] = {}
        self._lock = threading.Lock()

    def _ensure_cluster(self):
        if self._cluster is None:
            from ..cluster_utils import Cluster
            self._cluster = Cluster()
        return self._cluster

    def create_node(self, node_type: NodeType) -> str:
        cluster = self._ensure_cluster()
        res = dict(node_type.resources)
        cpus = res.pop("CPU", 1.0)
        node_id = cluster.add_node(num_cpus=cpus, resources=res,
                                   labels=dict(node_type.labels))
        with self._lock:
            self._nodes[node_id] = node_type
        return node_id

    def terminate_node(self, node_id: str) -> bool:
        try:
            self._cluster.remove_node(node_id)
        except KeyError:
            pass           # process already gone (idempotent retry)
        except Exception:
            return False   # keep the node listed: the reconciler retries
        with self._lock:
            self._nodes.pop(node_id, None)
        return True

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return list(self._nodes)


class FakeMultiNodeProvider(NodeProvider):
    """In-process provider for tests: each node is a real NodeDaemon with
    real worker subprocesses (the add_fake_node machinery)."""

    def __init__(self):
        self._nodes: Dict[str, NodeType] = {}
        self._lock = threading.Lock()

    def create_node(self, node_type: NodeType) -> str:
        from .._private.worker import add_fake_node
        node_id = add_fake_node(resources=dict(node_type.resources),
                                labels=dict(node_type.labels))
        with self._lock:
            self._nodes[node_id] = node_type
        return node_id

    def terminate_node(self, node_id: str) -> bool:
        from .._private.worker import remove_node
        with self._lock:
            self._nodes.pop(node_id, None)
        return remove_node(node_id)

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return list(self._nodes)


class GcpTpuNodeProvider(NodeProvider):
    """GCE TPU-VM provider: shells out to gcloud. Requires
    RAY_TPU_GCP_PROJECT / RAY_TPU_GCP_ZONE; `accelerator` in the node
    type's labels picks the slice (e.g. v5p-8). Nodes join the cluster by
    running `ray_tpu start --address <head>` via --metadata startup
    script, mirroring the reference's TPUCommandRunner flow."""

    def __init__(self, head_address: str, project: Optional[str] = None,
                 zone: Optional[str] = None,
                 runtime_version: str = "tpu-ubuntu2204-base"):
        self.head_address = head_address
        self.project = project or os.environ.get("RAY_TPU_GCP_PROJECT")
        self.zone = zone or os.environ.get("RAY_TPU_GCP_ZONE")
        self.runtime_version = runtime_version
        self._nodes: Dict[str, str] = {}     # node_id -> tpu vm name
        if not self.project or not self.zone:
            raise RuntimeError(
                "GcpTpuNodeProvider needs RAY_TPU_GCP_PROJECT and "
                "RAY_TPU_GCP_ZONE (or explicit project=/zone=)")

    def _gcloud(self, *args: str) -> str:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", *args,
               f"--project={self.project}", f"--zone={self.zone}",
               "--quiet"]
        out = subprocess.run(cmd, capture_output=True, text=True)
        if out.returncode != 0:
            raise RuntimeError(f"gcloud failed: {out.stderr[-2000:]}")
        return out.stdout

    def create_node(self, node_type: NodeType) -> str:
        import json
        name = f"ray-tpu-{node_type.name}-{uuid.uuid4().hex[:8]}"
        accel = node_type.labels.get("accelerator", "v5litepod-1")
        # The node joins carrying an `autoscaler_node` label equal to the
        # provider id — the reconciler matches it against the controller's
        # node list, since the daemon-generated node_id is only known
        # after registration.
        labels = dict(node_type.labels, autoscaler_node=name)
        startup = (f"python -m ray_tpu start "
                   f"--address {self.head_address} "
                   f"--resources {json.dumps(json.dumps(node_type.resources))} "
                   f"--labels {json.dumps(json.dumps(labels))}")
        self._gcloud("create", name,
                     f"--accelerator-type={accel}",
                     f"--version={self.runtime_version}",
                     f"--metadata=startup-script={startup}")
        self._nodes[name] = name
        return name

    def terminate_node(self, node_id: str) -> bool:
        name = self._nodes.pop(node_id, node_id)
        try:
            self._gcloud("delete", name)
            return True
        except RuntimeError:
            return False

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)
