"""Autoscaler: demand-driven cluster scaling (reference parity:
python/ray/autoscaler/v2 — instance_manager/reconciler.py:53 Reconciler,
_private/gcp/tpu_command_runner.py for the TPU provider story)."""

from .provider import (FakeMultiNodeProvider, GcpTpuNodeProvider,
                       ProcessNodeProvider,
                       NodeProvider, NodeType)
from .reconciler import Autoscaler, AutoscalerConfig, request_resources

__all__ = [
    "Autoscaler", "AutoscalerConfig", "NodeProvider", "NodeType",
    "FakeMultiNodeProvider", "GcpTpuNodeProvider", "ProcessNodeProvider",
    "request_resources",
]
