"""The reconciler: diff (demand, current nodes) -> launch/terminate.

Reference parity: python/ray/autoscaler/v2/instance_manager/
reconciler.py:53-61 (Reconciler.reconcile: sync-from-cloud, then
step_next) and v2/scheduler.py (ResourceDemandScheduler). Simplified to
the TPU-native shape: demand is the controller's pending task resources +
pending PG bundles (gang slice demand rides the `TPU-...-head` marker
resource in a bundle), supply is live nodes plus launches in flight.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .provider import NodeProvider, NodeType

logger = logging.getLogger(__name__)

_DEMAND_KV_KEY = "autoscaler/requested_resources"


@dataclass
class AutoscalerConfig:
    node_types: List[NodeType] = field(default_factory=list)
    idle_timeout_s: float = 60.0
    max_launches_per_round: int = 8
    # nodes never scaled down (the head node's id is added automatically)
    protected_nodes: List[str] = field(default_factory=list)


def request_resources(bundles: List[Dict[str, float]]) -> None:
    """Explicit demand hint (reference: ray.autoscaler.sdk
    request_resources): the autoscaler provisions for these bundles even
    before tasks arrive. Overwrites the previous request; [] clears."""
    import pickle

    from ..experimental.internal_kv import _internal_kv_put
    _internal_kv_put(_DEMAND_KV_KEY, pickle.dumps(list(bundles)))


class Autoscaler:
    """Poll demand, reconcile, repeat. One instance per cluster, usually
    next to the head controller."""

    def __init__(self, provider: NodeProvider, config: AutoscalerConfig,
                 client=None):
        from .._private import state
        self.provider = provider
        self.config = config
        self.client = client or state.current_client()
        self._idle_since: Dict[str, float] = {}
        self._launched: Dict[str, NodeType] = {}   # node_id -> type
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.client.controller_rpc("set_autoscaling", enabled=True)

    # ------------------------------------------------------------- control

    def start(self, interval_s: float = 2.0) -> None:
        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.reconcile_once()
                except Exception:
                    logger.exception("reconcile failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        try:
            self.client.controller_rpc("set_autoscaling", enabled=False)
        except Exception:
            pass

    # ----------------------------------------------------------- reconcile

    def _requested_bundles(self) -> List[Dict[str, float]]:
        import pickle

        from ..experimental.internal_kv import _internal_kv_get
        raw = _internal_kv_get(_DEMAND_KV_KEY)
        return pickle.loads(raw) if raw else []

    def reconcile_once(self) -> Dict[str, int]:
        """One reconcile round. Returns {"launched": n, "terminated": n}."""
        demand = self.client.controller_rpc("pending_demand")
        bundles: List[Dict[str, float]] = []
        bundles.extend(d for d in demand["task_demands"] if d)
        for pg in demand["pg_demands"]:
            bundles.extend(b for b in pg["bundles"] if b)
        bundles.extend(b for b in self._requested_bundles() if b)

        # Keyed by PROVIDER id: providers whose node ids aren't the
        # daemon's node_id (e.g. GCP VM names) report theirs via the
        # `autoscaler_node` label the node joins with.
        nodes = {}
        for n in demand["nodes"]:
            if n["alive"]:
                pid = n.get("labels", {}).get("autoscaler_node",
                                              n["node_id"])
                nodes[pid] = n

        # ----- scale up: fit unmet bundles onto the actual free capacity
        # of live nodes (busy nodes with a backlog still trigger growth)
        # + in-flight launches, launch node types for the rest.
        # draining nodes are excluded: the scheduler won't place work on
        # them, so counting their capacity would suppress needed launches
        free: List[Dict[str, float]] = [
            dict(n["resources_avail"]) for n in nodes.values()
            if not n.get("draining")]
        free += [dict(t.resources) for nid, t in self._launched.items()
                 if nid not in nodes]          # still starting up
        type_counts: Dict[str, int] = {}
        for nid, t in self._launched.items():
            type_counts[t.name] = type_counts.get(t.name, 0) + 1

        to_launch: List[NodeType] = []
        for bundle in bundles:
            if _fit(bundle, free):
                continue
            chosen = None
            for nt in self.config.node_types:
                if nt.covers(bundle) \
                        and type_counts.get(nt.name, 0) < nt.max_workers:
                    chosen = nt
                    break
            if chosen is None:
                logger.warning("no node type covers demand %s", bundle)
                continue
            type_counts[chosen.name] = type_counts.get(chosen.name, 0) + 1
            cap = dict(chosen.resources)
            _fit(bundle, [cap])     # bundle occupies part of the new node
            free.append(cap)        # remainder can absorb later bundles
            to_launch.append(chosen)
            if len(to_launch) >= self.config.max_launches_per_round:
                break

        launched = 0
        for nt in to_launch:
            try:
                node_id = self.provider.create_node(nt)
                self._launched[node_id] = nt
                launched += 1
                logger.info("autoscaler launched %s as %s",
                            nt.name, str(node_id)[:12])
            except Exception:
                logger.exception("launch of %s failed", nt.name)

        # ----- scale down: nodes we launched, idle past the timeout.
        now = time.monotonic()
        terminated = 0
        for node_id, info in nodes.items():
            ours = node_id in self._launched
            busy = (info["num_running"] > 0
                    or info.get("num_pg_bundles", 0) > 0)
            if not ours or busy or node_id in self.config.protected_nodes:
                self._idle_since.pop(node_id, None)
                continue
            first_idle = self._idle_since.setdefault(node_id, now)
            if now - first_idle >= self.config.idle_timeout_s:
                if not info.get("draining"):
                    # Drain first so the scheduler stops placing work;
                    # terminate on a later round once still-idle
                    # (reference: autoscaler v2 drain-before-terminate).
                    self.client.controller_rpc(
                        "drain_node", node_id=info["node_id"])
                elif self.provider.terminate_node(node_id):
                    terminated += 1
                    self._launched.pop(node_id, None)
                    self._idle_since.pop(node_id, None)
                    logger.info("autoscaler terminated idle node %s",
                                node_id[:12])
        return {"launched": launched, "terminated": terminated}


def _fit(bundle: Dict[str, float], capacities: List[Dict[str, float]]
         ) -> bool:
    """First-fit a bundle into one of the capacity dicts (mutating it)."""
    for cap in capacities:
        if all(cap.get(k, 0.0) >= v for k, v in bundle.items()):
            for k, v in bundle.items():
                cap[k] = cap.get(k, 0.0) - v
            return True
    return False
