"""KubeRay/GKE integration: derive TPU slice resources from pod specs.

Reference parity: python/ray/autoscaler/_private/kuberay/
autoscaling_config.py:236-273 (+ utils.py:90 tpu_node_selectors_to_type)
— the GKE story: a RayCluster CR's worker groups carry GKE node
selectors (cloud.google.com/gke-tpu-accelerator + -topology) and a
google.com/tpu container resource; the autoscaler must translate those
into the runtime's resource vocabulary:

    {"CPU": n, "TPU": chips_per_host, "TPU-v5p-16-head": 1}

so pod-slice gang scheduling (util/placement_group.py slice helper) and
scale-up decisions see whole slices, one head resource per replica.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional

# GKE accelerator node-selector value -> TPU generation (reference
# utils.py gke_tpu_accelerator_to_generation)
GKE_TPU_GENERATIONS: Dict[str, str] = {
    "tpu-v4-podslice": "v4",
    "tpu-v5-lite-device": "v5e",
    "tpu-v5-lite-podslice": "v5e",
    "tpu-v5p-slice": "v5p",
    "tpu-v6e-slice": "v6e",
}
# generations with 2 TensorCores per chip: the accelerator_type counts
# CORES (v4-8 = 4 chips), matching GCE machine naming
_TWO_CORE_GENERATIONS = ("v4", "v5p")

TOPOLOGY_SELECTOR = "cloud.google.com/gke-tpu-topology"
ACCELERATOR_SELECTOR = "cloud.google.com/gke-tpu-accelerator"
K8S_TPU_RESOURCE = "google.com/tpu"


def tpu_node_selectors_to_type(topology: Optional[str],
                               accelerator: Optional[str]
                               ) -> Optional[str]:
    """("2x2x2", "tpu-v4-podslice") -> "v4-16" (cores, not chips)."""
    if not topology or not accelerator:
        return None
    generation = GKE_TPU_GENERATIONS.get(accelerator)
    if generation is None:
        raise ValueError(
            f"unknown GKE TPU accelerator {accelerator!r} "
            f"(known: {sorted(GKE_TPU_GENERATIONS)})")
    if not re.fullmatch(r"\d+(x\d+)*", topology):
        raise ValueError(f"malformed TPU topology {topology!r}")
    num_chips = math.prod(int(d) for d in topology.split("x"))
    cores_per_chip = 2 if generation in _TWO_CORE_GENERATIONS else 1
    return f"{generation}-{num_chips * cores_per_chip}"


def _k8s_quantity_to_int(q: Any) -> int:
    """K8s resource quantity -> int (ceiling), e.g. "4", 4, "4000m"."""
    if isinstance(q, (int, float)):
        return int(math.ceil(q))
    s = str(q)
    if s.endswith("m"):
        return int(math.ceil(int(s[:-1]) / 1000))
    return int(math.ceil(float(s)))


def worker_group_resources(group_spec: Dict[str, Any],
                           host_index: int = 0) -> Dict[str, float]:
    """Ray resources for pod `host_index` of a RayCluster worker group
    replica.

    group_spec follows the KubeRay CR shape: template.spec.nodeSelector
    + template.spec.containers[0].resources.{limits,requests}, optional
    rayStartParams.resources overrides (highest priority). Matches what
    a live node's TPUAcceleratorManager.autodetect_resources() would
    advertise: generic "TPU", the typed per-chip "TPU-{accel_type}"
    (what slice gang bundles demand, util/placement_group.py), and —
    ONLY on worker 0 of each replica — the "TPU-{accel_type}-head" gang
    anchor (accelerators/tpu.py:101-110: one anchor per slice)."""
    import json
    pod = group_spec.get("template", {}).get("spec", {})
    selectors = pod.get("nodeSelector", {}) or {}
    containers = pod.get("containers") or [{}]
    k8s_resources = containers[0].get("resources", {}) or {}
    start_params = group_spec.get("rayStartParams", {}) or {}
    custom = start_params.get("resources")
    custom = json.loads(custom) if isinstance(custom, str) else (custom or {})

    resources: Dict[str, float] = {}
    for typ in ("limits", "requests"):
        cpu = k8s_resources.get(typ, {}).get("cpu")
        if cpu is not None and "CPU" not in resources:
            resources["CPU"] = float(_k8s_quantity_to_int(cpu))

    num_tpus: Optional[int] = None
    if "TPU" in custom:
        num_tpus = int(custom["TPU"])
    else:
        for typ in ("limits", "requests"):
            q = k8s_resources.get(typ, {}).get(K8S_TPU_RESOURCE)
            if q is not None:
                num_tpus = _k8s_quantity_to_int(q)
                break
    if num_tpus is not None:
        resources["TPU"] = float(num_tpus)
        accel_type = tpu_node_selectors_to_type(
            selectors.get(TOPOLOGY_SELECTOR),
            selectors.get(ACCELERATOR_SELECTOR))
        if accel_type:
            resources[f"TPU-{accel_type}"] = float(num_tpus)
            if host_index == 0:
                resources[f"TPU-{accel_type}-head"] = 1.0
    for k, v in custom.items():
        resources[k] = float(v)
    return resources


def autoscaling_config_from_ray_cluster(cr: Dict[str, Any]
                                        ) -> Dict[str, Any]:
    """RayCluster CR dict -> a plain summary of the cluster's groups:
    per-pod resources (worker-0 vs other hosts), min/max worker counts,
    slice replica accounting (NumOfHosts hosts per replica). Feed into
    the reconciler via `node_types_from_ray_cluster`."""
    spec = cr.get("spec", cr)
    groups: List[Dict[str, Any]] = []
    for g in spec.get("workerGroupSpecs", []) or []:
        hosts_per_replica = int(g.get("numOfHosts", 1))
        groups.append({
            "name": g.get("groupName", "worker"),
            "worker0_resources": worker_group_resources(g, host_index=0),
            "resources": worker_group_resources(g, host_index=1),
            "min_workers": int(g.get("minReplicas", 0)) * hosts_per_replica,
            "max_workers": int(g.get("maxReplicas", 1)) * hosts_per_replica,
            "hosts_per_replica": hosts_per_replica,
        })
    head = spec.get("headGroupSpec")
    head_resources = (worker_group_resources(head)
                      if head is not None else {"CPU": 1.0})
    return {"head_resources": head_resources, "worker_groups": groups}


def node_types_from_ray_cluster(cr: Dict[str, Any]) -> List[Any]:
    """RayCluster CR -> the reconciler's NodeType list
    (autoscaler/provider.py NodeType(name, resources, labels,
    max_workers)). Multi-host groups contribute TWO node types per
    group — the worker-0 shape carrying the slice-head anchor and the
    other-hosts shape — so demand that rides the -head marker launches
    exactly one anchor per replica."""
    from .provider import NodeType

    cfg = autoscaling_config_from_ray_cluster(cr)
    out: List[Any] = []
    for g in cfg["worker_groups"]:
        hosts = g["hosts_per_replica"]
        replicas = g["max_workers"] // max(hosts, 1)
        if replicas <= 0:
            continue          # CR caps this group at zero: not launchable
        if hosts > 1:
            out.append(NodeType(
                name=f"{g['name']}-worker0",
                resources=g["worker0_resources"],
                labels={"kuberay-group": g["name"], "slice-host": "0"},
                max_workers=replicas))
            out.append(NodeType(
                name=g["name"],
                resources=g["resources"],
                labels={"kuberay-group": g["name"]},
                max_workers=replicas * (hosts - 1)))
        else:
            out.append(NodeType(
                name=g["name"],
                resources=g["worker0_resources"],
                labels={"kuberay-group": g["name"]},
                max_workers=g["max_workers"]))
    return out
