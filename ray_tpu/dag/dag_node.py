"""DAG node types for compiled graphs.

Reference parity: python/ray/dag/dag_node.py (DAGNode,
experimental_compile :265), input_node.py (InputNode context manager),
class_node.py (ClassMethodNode via actor_method.bind), and
output_node.py (MultiOutputNode).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple


class DAGNode:
    def experimental_compile(self, **kwargs):
        from .compiled_dag import CompiledDAG
        return CompiledDAG(self, **kwargs)

    def _upstream(self) -> List["DAGNode"]:
        return []


class InputNode(DAGNode):
    """`with InputNode() as inp:` — the per-execute input placeholder."""

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def __repr__(self):
        return "InputNode()"


class FunctionNode(DAGNode):
    """One bound remote-function call in a task DAG (reference parity:
    python/ray/dag/function_node.py — `fn.bind(...)`). Used by
    ray_tpu.workflow for durable execution."""

    def __init__(self, remote_fn, args: Tuple[Any, ...],
                 kwargs: Optional[dict] = None,
                 workflow_options: Optional[dict] = None):
        self.remote_fn = remote_fn
        self.args = args
        self.kwargs = kwargs or {}
        # per-step workflow options (reference parity: workflow step
        # options, python/ray/workflow/api.py options(**step_options) —
        # max_retries / catch_exceptions)
        self.workflow_options = dict(workflow_options or {})

    def options(self, **workflow_options) -> "FunctionNode":
        """Per-step options for workflow execution, e.g.
        .options(max_retries=3, catch_exceptions=True)."""
        merged = {**self.workflow_options, **workflow_options}
        return FunctionNode(self.remote_fn, self.args, self.kwargs,
                            workflow_options=merged)

    @property
    def name(self) -> str:
        return getattr(self.remote_fn, "__name__", "fn")

    def _upstream(self) -> List[DAGNode]:
        ups = [a for a in self.args if isinstance(a, DAGNode)]
        ups += [v for v in self.kwargs.values() if isinstance(v, DAGNode)]
        return ups

    def __repr__(self):
        return f"FunctionNode({self.name})"


class ClassMethodNode(DAGNode):
    """One bound actor-method call in the graph."""

    def __init__(self, actor_handle, method_name: str,
                 args: Tuple[Any, ...]):
        self.actor = actor_handle
        self.method_name = method_name
        self.args = args

    def _upstream(self) -> List[DAGNode]:
        return [a for a in self.args if isinstance(a, DAGNode)]

    def __repr__(self):
        return f"ClassMethodNode({self.method_name})"


class MultiOutputNode(DAGNode):
    """Graph with several leaf outputs; execute() returns a list."""

    def __init__(self, outputs: List[DAGNode]):
        self.outputs = list(outputs)

    def _upstream(self) -> List[DAGNode]:
        return list(self.outputs)

    def __repr__(self):
        return f"MultiOutputNode({len(self.outputs)})"
