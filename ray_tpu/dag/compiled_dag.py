"""CompiledDAG: channel-wired actor pipelines.

Reference parity: python/ray/dag/compiled_dag_node.py:805 (CompiledDAG,
execute :2552). Compilation wires one shared-memory channel
(experimental/channel) per produced value; every participating actor
starts ONE long-running loop (`__rtpu_compiled_loop__`, dispatched by the
worker runtime) that each iteration reads its nodes' input channels,
runs the bound methods, and writes output channels. execute() writes the
input channel and hands back a ref that reads the output channel — after
the first iteration the control plane is out of the picture entirely:
data moves through shared memory with writer/reader semaphores, which is
what makes a compiled graph faster than per-call task submission.

Errors: a failing method writes a _DagError envelope downstream; pass-
through nodes forward it and ref.get() re-raises at the driver.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional

from ..experimental.channel import Channel, ChannelClosedError
from .dag_node import ClassMethodNode, DAGNode, InputNode, MultiOutputNode

class _DagError:
    def __init__(self, tb: str):
        self.tb = tb


class DagExecutionError(Exception):
    pass


# --------------------------------------------------------------- actor side

def run_actor_loop(instance, specs: List[Dict[str, Any]]) -> None:
    """Runs inside the actor worker (see worker_main rpc_call_actor).

    specs: [{"method": str, "inputs": [("chan", Channel) | ("const", v)],
             "output": Channel | None}] in topological order.

    Each distinct EXTERNAL input channel is read exactly once per
    iteration (pickle memoizes Channel objects, so two specs consuming
    the same value share ONE cursor — reading twice would deadlock);
    the value fans out to every consuming spec. Channels produced by
    this actor's own specs are served from the iteration's local values,
    not read back (this actor isn't a registered reader of them). The
    first read of an iteration tolerates idle timeouts (a compiled
    pipeline may sit unused between executes); only channel closure —
    teardown — terminates the loop.
    """
    import traceback

    def read_retry(ch: Channel):
        # timeouts are NOT fatal (a pipeline may idle arbitrarily long
        # between executes, or a peer may stall); only channel closure —
        # teardown — terminates the loop
        while True:
            try:
                return ch.read(timeout=60.0)
            except TimeoutError:
                continue

    def write_retry(ch: Channel, value) -> None:
        while True:
            try:
                ch.write(value, timeout=60.0)
                return
            except TimeoutError:
                continue          # driver not draining yet; keep waiting

    while True:
        values: Dict[int, Any] = {}
        try:
            for spec in specs:
                args = []
                err: Optional[_DagError] = None
                for kind, src in spec["inputs"]:
                    if kind == "chan":
                        if id(src) not in values:
                            # lazy per-spec reads (NOT all up front):
                            # this actor may need to produce a value a
                            # peer is waiting on before its own later
                            # inputs become available
                            values[id(src)] = read_retry(src)
                        val = values[id(src)]
                        if isinstance(val, _DagError) and err is None:
                            err = val
                        args.append(val)
                    else:
                        args.append(src)
                if err is not None:
                    result = err          # pass the failure through
                else:
                    try:
                        result = getattr(instance, spec["method"])(*args)
                    except Exception:
                        result = _DagError(traceback.format_exc())
                if spec["output"] is not None:
                    values[id(spec["output"])] = result
                    write_retry(spec["output"], result)
        except ChannelClosedError:
            return


# -------------------------------------------------------------- driver side

class CompiledDAGRef:
    """Result handle for one execute(); get() reads the output
    channel(s) in execution order."""

    def __init__(self, dag: "CompiledDAG", index: int):
        self._dag = dag
        self._index = index
        self._value: Any = None
        self._fetched = False

    def get(self, timeout: Optional[float] = None):
        if not self._fetched:
            self._value = self._dag._fetch(
                self._index, 120.0 if timeout is None else timeout)
            self._fetched = True
        if isinstance(self._value, _DagError):
            raise DagExecutionError(self._value.tb)
        if isinstance(self._value, list) and any(
                isinstance(v, _DagError) for v in self._value):
            raise DagExecutionError(
                "\n".join(v.tb for v in self._value
                          if isinstance(v, _DagError)))
        return self._value


class CompiledDAG:
    def __init__(self, root: DAGNode, buffer_size: int = 4 << 20):
        self._buffer_size = buffer_size
        self._channels: List[Channel] = []
        self._torn_down = False
        self._exec_count = 0
        self._fetch_count = 0
        self._results: Dict[int, Any] = {}
        self._partial: Dict[int, Any] = {}   # channel idx -> value
        self._lock = threading.Lock()
        self._compile(root)

    # -- compilation --------------------------------------------------------
    def _compile(self, root: DAGNode) -> None:
        if isinstance(root, MultiOutputNode):
            leaves = root.outputs
        else:
            leaves = [root]
        for leaf in leaves:
            if not isinstance(leaf, ClassMethodNode):
                raise TypeError(
                    "compiled DAG outputs must be actor method calls")

        # collect nodes (post-order) + the input node
        order: List[ClassMethodNode] = []
        seen: set = set()
        self._input_node: Optional[InputNode] = None

        def visit(node: DAGNode):
            if id(node) in seen:
                return
            seen.add(id(node))
            if isinstance(node, InputNode):
                self._input_node = node
                return
            for up in node._upstream():
                visit(up)
            if isinstance(node, ClassMethodNode):
                order.append(node)

        for leaf in leaves:
            visit(leaf)
        self._order = order

        # reader counts per produced value = DISTINCT consuming actors
        # other than the producer (same-actor consumers use the loop's
        # local value, not the channel), plus the driver for leaves
        def producer_of(value_node) -> Optional[str]:
            if isinstance(value_node, ClassMethodNode):
                return value_node.actor._actor_id
            return None                    # InputNode: driver produces

        reader_actors: Dict[int, set] = {}
        for node in order:
            for a in node.args:
                if isinstance(a, (InputNode, ClassMethodNode)):
                    if node.actor._actor_id != producer_of(a):
                        reader_actors.setdefault(id(a), set()).add(
                            node.actor._actor_id)
        consumers: Dict[int, int] = {
            key: len(actors) for key, actors in reader_actors.items()}
        for leaf in leaves:
            consumers[id(leaf)] = consumers.get(id(leaf), 0) + 1  # driver

        # distinct ack-bitmask slot per reader endpoint of each channel:
        # consuming actors in sorted order, the driver (for leaves) last
        reader_slots: Dict[int, Dict[str, int]] = {
            key: {aid: i for i, aid in enumerate(sorted(actors))}
            for key, actors in reader_actors.items()}

        def make_channel(n_readers: int) -> Channel:
            ch = Channel.create(num_readers=n_readers,
                                capacity=self._buffer_size,
                                name=f"rtpu_dag_{uuid.uuid4().hex[:12]}")
            self._channels.append(ch)
            return ch

        node_out: Dict[int, Channel] = {}
        if self._input_node is not None:
            self._input_channel = make_channel(
                max(consumers.get(id(self._input_node), 1), 1))
            node_out[id(self._input_node)] = self._input_channel
        else:
            self._input_channel = None
        for node in order:
            # 0 readers is legal: a value consumed only by its own
            # actor's later specs never crosses the channel
            node_out[id(node)] = make_channel(consumers.get(id(node), 0))
        # the driver reads leaves through its own slot (after all actors)
        self._output_channels = [
            node_out[id(leaf)].for_reader(
                len(reader_actors.get(id(leaf), ())))
            for leaf in leaves]
        self._multi_output = isinstance(root, MultiOutputNode)

        # group node specs per actor, preserving topo order. Each actor
        # gets its OWN copy of every channel it touches, carrying that
        # actor's reader slot; the copy is memoized per (actor, node) so
        # a producer spec's output and same-actor consumer inputs stay
        # one object (run_actor_loop dedups reads by object identity).
        reader_copies: Dict[Any, Channel] = {}

        def chan_for(actor_id: str, value_node) -> Channel:
            memo_key = (actor_id, id(value_node))
            ch = reader_copies.get(memo_key)
            if ch is None:
                slot = reader_slots.get(id(value_node), {}).get(actor_id, 0)
                ch = node_out[id(value_node)].for_reader(slot)
                reader_copies[memo_key] = ch
            return ch

        per_actor: Dict[str, Dict[str, Any]] = {}
        for node in order:
            aid = node.actor._actor_id
            entry = per_actor.setdefault(
                aid, {"actor": node.actor, "specs": []})
            inputs = []
            for a in node.args:
                if isinstance(a, (InputNode, ClassMethodNode)):
                    inputs.append(("chan", chan_for(aid, a)))
                else:
                    inputs.append(("const", a))
            entry["specs"].append({"method": node.method_name,
                                   "inputs": inputs,
                                   "output": chan_for(aid, node)})

        # launch the per-actor loops (long-running actor tasks)
        self._loop_refs = []
        for entry in per_actor.values():
            actor = entry["actor"]
            from ..actor import ActorMethod
            ref = ActorMethod(actor, "__rtpu_compiled_loop__").remote(
                entry["specs"])
            self._loop_refs.append(ref)

    # -- execution ----------------------------------------------------------
    def execute(self, *args) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        if self._input_channel is not None:
            if len(args) != 1:
                raise TypeError("compiled DAG takes exactly one input")
            self._input_channel.write(args[0], timeout=120.0)
        idx = self._exec_count
        self._exec_count += 1
        return CompiledDAGRef(self, idx)

    def _fetch(self, index: int, timeout: float):
        with self._lock:
            # results must be drained in order; channels serialize
            # versions. _partial keeps per-channel reads across a timeout
            # so a retried get() never re-reads an already-acked channel
            # (its cursor has advanced — re-reading would hang).
            while self._fetch_count <= index:
                for i, ch in enumerate(self._output_channels):
                    if i not in self._partial:
                        self._partial[i] = ch.read(timeout=timeout)
                vals = [self._partial[i]
                        for i in range(len(self._output_channels))]
                self._partial.clear()
                self._results[self._fetch_count] = (
                    vals if self._multi_output else vals[0])
                self._fetch_count += 1
            return self._results.pop(index)

    # -- teardown -----------------------------------------------------------
    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        for ch in self._channels:
            try:
                ch.close()
            except Exception:
                pass
        # loops exit on ChannelClosedError; then remove the segments
        import ray_tpu
        try:
            ray_tpu.wait(self._loop_refs,
                         num_returns=len(self._loop_refs), timeout=10)
        except Exception:
            pass
        for ch in self._channels:
            try:
                ch.destroy()
            except Exception:
                pass

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass
