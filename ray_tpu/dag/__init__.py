"""ray_tpu.dag: compiled graphs (ADAG-equivalent).

Reference parity: python/ray/dag — bind actor methods into a DAG,
experimental_compile wires shared-memory channels between the actors,
execute() streams through them without per-call task submission.
"""

from .._private.usage import record_library_usage as _rlu
_rlu("dag")
del _rlu


from .compiled_dag import CompiledDAG, CompiledDAGRef, DagExecutionError
from .dag_node import (ClassMethodNode, DAGNode, InputNode,
                       MultiOutputNode)

__all__ = ["InputNode", "MultiOutputNode", "DAGNode", "ClassMethodNode",
           "CompiledDAG", "CompiledDAGRef", "DagExecutionError"]
