"""Cluster-wide KV on the control plane.

Reference parity: python/ray/experimental/internal_kv.py:34 (GCS-backed
_internal_kv_get/put/del/list/exists).
"""

from __future__ import annotations

from typing import List, Optional

from .._private import state as _state


def _client():
    return _state.current_client()


def _internal_kv_initialized() -> bool:
    return _state.current_client_or_none() is not None


def _internal_kv_put(key, value, overwrite: bool = True,
                     namespace: Optional[str] = None) -> bool:
    key = _ns(key, namespace)
    value = value if isinstance(value, bytes) else str(value).encode()
    return _client().kv_put(key, value, overwrite=overwrite)


def _internal_kv_get(key, namespace: Optional[str] = None
                     ) -> Optional[bytes]:
    return _client().kv_get(_ns(key, namespace))


def _internal_kv_exists(key, namespace: Optional[str] = None) -> bool:
    return _internal_kv_get(key, namespace) is not None


def _internal_kv_del(key, namespace: Optional[str] = None) -> bool:
    return _client().controller_rpc("kv_del", key=_ns(key, namespace))


def _internal_kv_list(prefix, namespace: Optional[str] = None
                      ) -> List[bytes]:
    keys = _client().controller_rpc("kv_keys",
                                    prefix=_ns(prefix, namespace))
    return [k.encode() if isinstance(k, str) else k for k in keys]


def _ns(key, namespace: Optional[str]) -> str:
    if isinstance(key, bytes):
        key = key.decode()
    return f"{namespace}:{key}" if namespace else key
