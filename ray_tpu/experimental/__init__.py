"""Experimental APIs (reference parity: ray.experimental)."""

from __future__ import annotations

from typing import Optional


def set_resource(resource_name: str, capacity: float,
                 node_id: Optional[str] = None) -> None:
    """Dynamically set a custom resource's capacity on a node
    (reference parity: ray.experimental.set_resource — dynamic custom
    resources). capacity <= 0 deletes the resource.

    Routed over the controller's heartbeat command channel to the
    daemon, which applies it locally and gossips the new totals back
    (ray_syncer RESOURCE_VIEW path), so scheduling sees it within one
    heartbeat round-trip (~1 s).
    """
    import ray_tpu
    from .._private import state as _state
    client = _state.current_client()
    if node_id is None:
        # inside a worker: default to the local node (reference
        # semantics); drivers fall back to the head node
        node_id = ray_tpu.get_runtime_context().get_node_id()
        if node_id is None:   # driver: first alive node (the head)
            nodes = client.controller_rpc("list_nodes")
            alive = [n for n in nodes if n["alive"]]
            if not alive:
                raise RuntimeError("no alive node to set the resource on")
            node_id = alive[0]["node_id"]
    reply = client.controller_rpc("set_node_resource", node_id=node_id,
                                  name=resource_name,
                                  capacity=float(capacity))
    if reply.get("status") != "queued":
        raise RuntimeError(
            f"set_resource failed for node {node_id[:12]}: {reply}")
