"""Typed channels for compiled graphs.

Reference parity: python/ray/experimental/channel/ — shared-memory
mutable-object channels (shared_memory_channel.py) with writer/reader
semaphores. The native primitive is src/shm_channel.cc; this wrapper
adds (de)serialization. Channels REQUIRE the native lib (g++ build):
compiled graphs are a performance feature with no slow-path fallback.
"""

from .shared_memory_channel import Channel, ChannelClosedError

__all__ = ["Channel", "ChannelClosedError"]
