"""Typed channels for compiled graphs.

Reference parity: python/ray/experimental/channel/ — shared-memory
mutable-object channels (shared_memory_channel.py) with writer/reader
semaphores. The native primitive is src/shm_channel.cc; this wrapper
adds (de)serialization and a pure-Python fallback channel for
environments without the native lib.
"""

from .shared_memory_channel import Channel, ChannelClosedError

__all__ = ["Channel", "ChannelClosedError"]
