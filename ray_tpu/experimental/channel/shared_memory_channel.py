"""Shared-memory channel: single writer, N readers, one mutable slot.

Reference parity: python/ray/experimental/channel/shared_memory_channel.py
(796 LoC over C++ mutable objects — here over src/shm_channel.cc).
A Channel handle pickles by name+role metadata, so it travels inside
compiled-DAG specs to the actors at either end.
"""

from __future__ import annotations

import ctypes
import pickle
import uuid
from typing import Any, Optional

from ..._private.serialization import SerializedObject, serialize

DEFAULT_CAPACITY = 4 << 20


class ChannelClosedError(Exception):
    pass


def _lib():
    from ..._native import load_library
    lib = load_library("libshm_channel", "shm_channel.cc")
    if lib is None:
        return None
    if not getattr(lib, "_chan_configured", False):
        u64, vp, cp, dbl = (ctypes.c_uint64, ctypes.c_void_p,
                            ctypes.c_char_p, ctypes.c_double)
        lib.chan_create.restype = vp
        lib.chan_create.argtypes = [cp, u64, u64]
        lib.chan_attach.restype = vp
        lib.chan_attach.argtypes = [cp]
        lib.chan_write.restype = ctypes.c_int
        lib.chan_write.argtypes = [vp, cp, u64, dbl]
        lib.chan_read.restype = ctypes.c_int
        lib.chan_read.argtypes = [vp, u64, u64, ctypes.c_char_p, u64,
                                  ctypes.POINTER(u64), ctypes.POINTER(u64),
                                  dbl]
        lib.chan_capacity.restype = u64
        lib.chan_capacity.argtypes = [vp]
        lib.chan_close.argtypes = [vp]
        lib.chan_detach.argtypes = [vp]
        lib.chan_unlink.argtypes = [cp]
        lib._chan_configured = True
    return lib


class Channel:
    """create() on the driver; endpoints attach lazily on first use."""

    def __init__(self, name: str, capacity: int, num_readers: int,
                 reader_slot: int = 0, _creator: bool = False):
        self.name = name
        self.capacity = capacity
        self.num_readers = num_readers
        # Identity of THIS endpoint among the channel's readers (bit index
        # in the native ack bitmask). Distinct readers must hold distinct
        # slots or the writer may overwrite before all of them consumed.
        self.reader_slot = reader_slot
        self._h = None
        self._creator = _creator
        self._version = 0          # reader cursor
        self._closed = False

    # -- construction -------------------------------------------------------
    @classmethod
    def create(cls, num_readers: int = 1,
               capacity: int = DEFAULT_CAPACITY,
               name: Optional[str] = None) -> "Channel":
        lib = _lib()
        if lib is None:
            raise RuntimeError(
                "native channel lib unavailable (g++ build failed)")
        if num_readers > 64:
            raise ValueError(
                f"channels support at most 64 readers (got {num_readers}): "
                "reader acks live in one 64-bit bitmask; fan wider via a "
                "tree of channels or the object store")
        name = name or f"rtpu_chan_{uuid.uuid4().hex[:16]}"
        h = lib.chan_create(name.encode(), capacity, num_readers)
        if not h:
            raise RuntimeError(f"chan_create({name}) failed")
        ch = cls(name, capacity, num_readers, _creator=True)
        ch._h = h
        return ch

    def for_reader(self, slot: int) -> "Channel":
        """A handle for reader endpoint *slot* (0 <= slot < num_readers)."""
        if not 0 <= slot < max(self.num_readers, 1):
            raise ValueError(
                f"reader slot {slot} out of range for "
                f"{self.num_readers}-reader channel {self.name}")
        return Channel(self.name, self.capacity, self.num_readers,
                       reader_slot=slot)

    def _handle(self):
        if self._h is None:
            lib = _lib()
            h = lib.chan_attach(self.name.encode())
            if not h:
                raise ChannelClosedError(
                    f"channel {self.name} is gone")
            self._h = h
        return self._h

    # -- data plane ---------------------------------------------------------
    def write(self, value: Any, timeout: float = 30.0) -> None:
        lib = _lib()
        blob = serialize(value).to_flat()
        rc = lib.chan_write(self._handle(), blob, len(blob), timeout)
        if rc == -32:                      # -EPIPE
            raise ChannelClosedError(self.name)
        if rc == -110:                     # -ETIMEDOUT
            raise TimeoutError(
                f"write to {self.name} timed out ({timeout}s); readers "
                f"have not consumed the previous value")
        if rc == -90:                      # -EMSGSIZE
            raise ValueError(
                f"message of {len(blob)} bytes exceeds channel capacity "
                f"{self.capacity}")
        if rc != 0:
            raise RuntimeError(f"chan_write rc={rc}")

    def read(self, timeout: float = 30.0) -> Any:
        lib = _lib()
        # reuse one read buffer: allocating+zeroing `capacity` bytes per
        # read dominates latency for multi-MB channels
        buf = getattr(self, "_read_buf", None)
        if buf is None:
            buf = self._read_buf = ctypes.create_string_buffer(
                self.capacity)
        out_len = ctypes.c_uint64()
        out_ver = ctypes.c_uint64()
        rc = lib.chan_read(self._handle(), self.reader_slot, self._version,
                           buf, self.capacity, ctypes.byref(out_len),
                           ctypes.byref(out_ver), timeout)
        if rc == -32:
            raise ChannelClosedError(self.name)
        if rc == -110:
            raise TimeoutError(f"read from {self.name} timed out "
                               f"({timeout}s)")
        if rc != 0:
            raise RuntimeError(f"chan_read rc={rc}")
        self._version = out_ver.value
        # Copy the payload out of the reused read buffer before
        # deserializing: zero-copy views into `buf` would be silently
        # overwritten by the next read on this channel, corrupting any
        # numpy arrays still held by the caller.
        payload = bytes(memoryview(buf)[: out_len.value])
        return SerializedObject.from_flat(payload).deserialize()

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            lib = _lib()
            lib.chan_close(self._handle())
        except Exception:
            pass

    def destroy(self) -> None:
        self.close()
        lib = _lib()
        if self._h is not None:
            lib.chan_detach(self._h)
            self._h = None
        lib.chan_unlink(self.name.encode())

    # -- pickling: handle travels, mapping re-attaches ----------------------
    def __reduce__(self):
        return (Channel, (self.name, self.capacity, self.num_readers,
                          self.reader_slot))

    def __repr__(self):
        return (f"Channel({self.name}, cap={self.capacity}, "
                f"readers={self.num_readers})")
