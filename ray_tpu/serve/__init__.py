"""ray_tpu.serve: model serving with replica autoscaling.

Reference parity: python/ray/serve — controller-reconciled deployments
(serve/_private/controller.py:84), power-of-two routing
(pow_2_scheduler.py:52), HTTP ingress proxy (proxy.py:534), batching,
model multiplexing, request-driven autoscaling.
"""

from .._private.usage import record_library_usage as _rlu
_rlu("serve")
del _rlu


from .api import (Application, Deployment, delete, deploy_config,
                  deployment, start_grpc,
                  get_app_handle, get_deployment_handle, run, shutdown,
                  start, status)
from .batching import batch
from .config import AutoscalingConfig, DeploymentConfig, HTTPOptions
from .schema import (DeploymentSchema, ServeApplicationSchema,
                     ServeDeploySchema)
from .handle import (DeploymentHandle, DeploymentResponse,
                     DeploymentResponseGenerator)
from .multiplex import get_multiplexed_model_id, multiplexed
from ._private.proxy import Request, Response, StreamingHint
from .asgi import ingress

__all__ = [
    "deployment", "Deployment", "Application", "run", "start",
    "start_grpc", "shutdown",
    "delete", "deploy_config", "status", "get_app_handle",
    "get_deployment_handle",
    "ServeDeploySchema", "ServeApplicationSchema", "DeploymentSchema",
    "DeploymentHandle", "DeploymentResponse",
    "DeploymentResponseGenerator", "StreamingHint",
    "AutoscalingConfig",
    "DeploymentConfig", "HTTPOptions", "batch", "multiplexed",
    "get_multiplexed_model_id", "Request", "Response", "ingress",
]


def __getattr__(name):
    # serve.llm namespace (reference: python/ray/serve/llm), loaded
    # lazily: the llm packages pull in jax + the model stack, which
    # non-LLM serve processes (controller, proxy) must not pay for.
    # Since ISSUE 6 this is the REAL serve/llm subpackage (fleet
    # deployments, router, admission, autoscaling); it re-exports the
    # single-model surface from ray_tpu.llm, so serve.llm.LLMConfig
    # etc. keep working.
    if name == "llm":
        import importlib
        return importlib.import_module(".llm", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
