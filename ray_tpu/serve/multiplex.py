"""Model multiplexing: many models per replica with per-replica LRU.

Reference parity: serve/multiplex.py (_ModelMultiplexWrapper) and
serve/api.py get_multiplexed_model_id. A handle tagged with
.options(multiplexed_model_id=...) carries the id in request metadata;
inside the replica, the @serve.multiplexed loader resolves/loads the
model, evicting least-recently-used ones beyond the cap.
"""

from __future__ import annotations

import asyncio
import collections
import functools
import inspect
from typing import Any, Callable, Optional

from ._private.replica import current_request_context


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id the current request was tagged
    with (empty string if untagged)."""
    ctx = current_request_context()
    if isinstance(ctx, dict):
        return ctx.get("multiplexed_model_id") or ""
    return ""


class _ModelCache:
    def __init__(self, loader: Callable, max_models: int):
        self.loader = loader
        self.max_models = max_models
        self.cache: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self._loads: dict = {}

    async def load(self, instance, model_id: str) -> Any:
        if model_id in self.cache:
            self.cache.move_to_end(model_id)
            return self.cache[model_id]
        pending = self._loads.get(model_id)
        if pending is not None:
            return await pending

        async def _load():
            if instance is not None:
                model = self.loader(instance, model_id)
            else:
                model = self.loader(model_id)
            if inspect.isawaitable(model):
                model = await model
            while len(self.cache) >= self.max_models:
                old_id, old = self.cache.popitem(last=False)
                del_fn = getattr(old, "__del__", None)
                del old
            self.cache[model_id] = model
            return model

        task = asyncio.ensure_future(_load())
        self._loads[model_id] = task
        try:
            return await task
        finally:
            self._loads.pop(model_id, None)


def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3):
    """Decorator on an async loader `(self, model_id) -> model`."""

    def wrap(fn):
        attr = f"__serve_multiplex_cache_{fn.__name__}"

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:
                instance, model_id = args
                cache = getattr(instance, attr, None)
                if cache is None:
                    cache = _ModelCache(fn, max_num_models_per_replica)
                    setattr(instance, attr, cache)
                return await cache.load(instance, model_id)
            (model_id,) = args
            cache = getattr(wrapper, "_cache", None)
            if cache is None:
                cache = wrapper._cache = _ModelCache(
                    fn, max_num_models_per_replica)
            return await cache.load(None, model_id)

        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
