"""Declarative Serve config: the YAML deploy surface.

Reference parity: python/ray/serve/schema.py (ServeDeploySchema /
ServeApplicationSchema / DeploymentSchema — pydantic there, plain
dataclasses here) consumed by `serve deploy config.yaml` and
`serve.run_config()`. An application is named by an import path to a
bound Application (module:attr or dotted), with per-deployment option
overrides applied on top of the code's own @deployment options
(reference: serve/_private/build_app.py override semantics).
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Dict, List, Optional

from .config import AutoscalingConfig


@dataclasses.dataclass
class DeploymentSchema:
    """Option overrides for one deployment (reference schema.py:281)."""

    name: str
    num_replicas: Optional[int] = None
    max_ongoing_requests: Optional[int] = None
    user_config: Any = None
    autoscaling_config: Optional[Dict[str, Any]] = None
    health_check_period_s: Optional[float] = None
    graceful_shutdown_timeout_s: Optional[float] = None
    ray_actor_options: Optional[Dict[str, Any]] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DeploymentSchema":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown deployment option(s) {sorted(unknown)} "
                f"(known: {sorted(known)})")
        if "name" not in d:
            raise ValueError("every deployment override needs a 'name'")
        return cls(**d)


@dataclasses.dataclass
class ServeApplicationSchema:
    """One application (reference schema.py:496)."""

    import_path: str
    name: str = "default"
    route_prefix: Optional[str] = "/"
    runtime_env: Dict[str, Any] = dataclasses.field(default_factory=dict)
    deployments: List[DeploymentSchema] = dataclasses.field(
        default_factory=list)
    args: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServeApplicationSchema":
        d = dict(d)
        if "import_path" not in d:
            raise ValueError("application config needs an 'import_path'")
        deps = [DeploymentSchema.from_dict(x)
                for x in d.pop("deployments", [])]
        known = {f.name for f in dataclasses.fields(cls)} - {"deployments"}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown application field(s) {sorted(unknown)}")
        return cls(deployments=deps, **d)


@dataclasses.dataclass
class ServeDeploySchema:
    """The whole config file (reference schema.py:709)."""

    applications: List[ServeApplicationSchema]
    http_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    grpc_options: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServeDeploySchema":
        d = dict(d)
        apps = d.pop("applications", None)
        if apps is None:
            # single-application form: the file IS one application
            return cls(applications=[ServeApplicationSchema.from_dict(d)])
        names = [a.get("name", "default") for a in apps]
        if len(names) != len(set(names)):
            raise ValueError(f"duplicate application names in {names}")
        real_prefixes = [p for p in
                         (a.get("route_prefix", "/") for a in apps) if p]
        if len(real_prefixes) != len(set(real_prefixes)):
            raise ValueError(f"duplicate route_prefix in {real_prefixes}")
        unknown = set(d) - {"http_options", "grpc_options"}
        if unknown:
            raise ValueError(f"unknown top-level field(s) {sorted(unknown)}")
        return cls(
            applications=[ServeApplicationSchema.from_dict(a) for a in apps],
            http_options=d.get("http_options", {}),
            grpc_options=d.get("grpc_options", {}))

    @classmethod
    def from_yaml(cls, path: str) -> "ServeDeploySchema":
        import yaml
        with open(path) as f:
            data = yaml.safe_load(f)
        if not isinstance(data, dict):
            raise ValueError(f"{path} is not a YAML mapping")
        return cls.from_dict(data)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def import_attr(import_path: str):
    """'pkg.mod:attr' (preferred) or 'pkg.mod.attr' -> the attribute."""
    if ":" in import_path:
        module_name, attr = import_path.split(":", 1)
    else:
        module_name, _, attr = import_path.rpartition(".")
        if not module_name:
            raise ValueError(
                f"import_path {import_path!r} must be 'module:attr' "
                f"or 'module.attr'")
    module = importlib.import_module(module_name)
    obj = module
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def build_app_from_schema(schema: ServeApplicationSchema):
    """Import the target and apply the schema's deployment overrides.

    The target may be a bound Application or a builder function taking
    the schema's `args` dict (reference: build_app.py + `args` field).
    Returns the Application with per-deployment config overridden.
    """
    from .api import Application

    target = import_attr(schema.import_path)
    if callable(target) and not isinstance(target, Application):
        target = target(schema.args) if schema.args else target()
    if not isinstance(target, Application):
        raise TypeError(
            f"{schema.import_path!r} resolved to {type(target).__name__}, "
            f"expected a bound Application")
    overrides = {d.name: d for d in schema.deployments}
    if overrides:
        target = _apply_overrides(target, overrides)
    return target


def _apply_overrides(root, overrides: Dict[str, DeploymentSchema]):
    """Rebuild the bind graph with per-deployment schema overrides.

    Raises if an override names a deployment that is not in the graph —
    a silently ignored override (typo'd name) deploys with defaults
    (reference: serve build_app validates override names)."""
    from .api import map_deployments

    consumed: set = set()

    def apply(dep):
        ov = overrides.get(dep.name)
        if ov is None:
            return dep
        consumed.add(dep.name)
        opts = {
            "num_replicas": ov.num_replicas,
            "max_ongoing_requests": ov.max_ongoing_requests,
            "user_config": ov.user_config,
            "health_check_period_s": ov.health_check_period_s,
            "graceful_shutdown_timeout_s": ov.graceful_shutdown_timeout_s,
            "ray_actor_options": ov.ray_actor_options,
        }
        if ov.autoscaling_config is not None:
            opts["autoscaling_config"] = AutoscalingConfig(
                **ov.autoscaling_config)
        return dep.options(
            **{k: v for k, v in opts.items() if v is not None})

    result = map_deployments(root, apply)
    unused = set(overrides) - consumed
    if unused:
        raise ValueError(
            f"deployment override(s) {sorted(unused)} match no deployment "
            f"in the application graph")
    return result
