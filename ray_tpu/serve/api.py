"""Serve public API: @deployment, bind graphs, run/shutdown/status.

Reference parity: serve/api.py + serve/deployment.py (Deployment.bind →
Application graph), build_app.py (graph → per-deployment specs), and
serve.run's deploy-and-wait semantics.
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu

from .config import AutoscalingConfig, DeploymentConfig, HTTPOptions
from .handle import DeploymentHandle, _HandlePlaceholder
from ._private.common import (ApplicationStatus, CONTROLLER_NAME,
                              GRPC_PROXY_NAME, PROXY_NAME)


class Application:
    """A bound deployment graph node (reference: serve Application)."""

    def __init__(self, deployment: "Deployment", args: tuple,
                 kwargs: dict):
        self._deployment = deployment
        self._args = args
        self._kwargs = kwargs


class Deployment:
    def __init__(self, func_or_class: Union[type, Callable], name: str,
                 config: DeploymentConfig):
        self.func_or_class = func_or_class
        self.name = name
        self.config = config

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[int] = None,
                max_ongoing_requests: Optional[int] = None,
                user_config: Any = None,
                autoscaling_config: Optional[
                    Union[AutoscalingConfig, Dict[str, Any]]] = None,
                health_check_period_s: Optional[float] = None,
                graceful_shutdown_timeout_s: Optional[float] = None,
                ray_actor_options: Optional[Dict[str, Any]] = None
                ) -> "Deployment":
        import copy
        cfg = copy.deepcopy(self.config)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if user_config is not None:
            cfg.user_config = user_config
        if autoscaling_config is not None:
            cfg.autoscaling_config = (
                autoscaling_config
                if isinstance(autoscaling_config, AutoscalingConfig)
                else AutoscalingConfig(**autoscaling_config))
        if health_check_period_s is not None:
            cfg.health_check_period_s = health_check_period_s
        if graceful_shutdown_timeout_s is not None:
            cfg.graceful_shutdown_timeout_s = graceful_shutdown_timeout_s
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        return Deployment(self.func_or_class, name or self.name, cfg)


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: Optional[Union[int, str]] = None,
               max_ongoing_requests: int = 8,
               user_config: Any = None,
               autoscaling_config: Optional[
                   Union[AutoscalingConfig, Dict[str, Any]]] = None,
               health_check_period_s: float = 2.0,
               graceful_shutdown_timeout_s: float = 5.0,
               ray_actor_options: Optional[Dict[str, Any]] = None):
    """@serve.deployment decorator (bare or with options)."""

    def build(target) -> Deployment:
        cfg = DeploymentConfig(
            num_replicas=(num_replicas
                          if isinstance(num_replicas, int) else 1),
            max_ongoing_requests=max_ongoing_requests,
            user_config=user_config,
            health_check_period_s=health_check_period_s,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
            ray_actor_options=dict(ray_actor_options or {}))
        auto = autoscaling_config
        if num_replicas == "auto" and auto is None:
            auto = AutoscalingConfig()
        if auto is not None:
            cfg.autoscaling_config = (
                auto if isinstance(auto, AutoscalingConfig)
                else AutoscalingConfig(**auto))
        return Deployment(target, name or target.__name__, cfg)

    if _func_or_class is not None:
        return build(_func_or_class)
    return build


# ---------------------------------------------------------------- app build

def map_deployments(root: Application,
                    fn: Callable[["Deployment"], "Deployment"]
                    ) -> Application:
    """Rebuild the bind graph with each node's Deployment mapped through
    `fn`. The single graph walker shared by schema overrides and
    runtime-env folding — handles Applications nested inside
    tuple/list/dict args exactly like _build_app_specs.sub()."""
    seen: Dict[int, Application] = {}

    def sub(obj):
        if isinstance(obj, Application):
            return visit(obj)
        if isinstance(obj, tuple):
            return tuple(sub(x) for x in obj)
        if isinstance(obj, list):
            return [sub(x) for x in obj]
        if isinstance(obj, dict):
            return {k: sub(v) for k, v in obj.items()}
        return obj

    def visit(node: Application) -> Application:
        if id(node) in seen:
            return seen[id(node)]
        new = Application(
            fn(node._deployment),
            tuple(sub(a) for a in node._args),
            {k: sub(v) for k, v in node._kwargs.items()})
        seen[id(node)] = new
        return new

    return visit(root)


def _build_app_specs(root: Application, app_name: str
                     ) -> (str, List[Dict[str, Any]]):
    """Walk the bind graph; one spec per unique Application node, nested
    nodes replaced by handle placeholders in the parent's init args."""
    from ._private.serialization_helpers import (serialize_args,
                                                 serialize_callable)

    names: Dict[int, str] = {}
    specs: List[Dict[str, Any]] = []
    used: Dict[str, int] = {}

    def assign_name(node: Application) -> str:
        if id(node) in names:
            return names[id(node)]
        base = node._deployment.name
        n = used.get(base, 0)
        used[base] = n + 1
        name = base if n == 0 else f"{base}_{n}"
        names[id(node)] = name
        return name

    def sub(obj):
        if isinstance(obj, Application):
            child = visit(obj)
            return _HandlePlaceholder(child, app_name)
        if isinstance(obj, tuple):
            return tuple(sub(x) for x in obj)
        if isinstance(obj, list):
            return [sub(x) for x in obj]
        if isinstance(obj, dict):
            return {k: sub(v) for k, v in obj.items()}
        return obj

    visited: Dict[int, str] = {}

    def visit(node: Application) -> str:
        if id(node) in visited:
            return visited[id(node)]
        name = assign_name(node)
        visited[id(node)] = name
        args = sub(node._args)
        kwargs = sub(node._kwargs)
        callable_blob = serialize_callable(node._deployment.func_or_class)
        init_args_blob = serialize_args(args, kwargs)
        cfg = node._deployment.config
        version = hashlib.sha1(
            callable_blob + init_args_blob
            + repr(cfg.user_config).encode()).hexdigest()[:16]
        specs.append({
            "name": name,
            "callable_blob": callable_blob,
            "init_args_blob": init_args_blob,
            "config": cfg,
            "version": version,
        })
        return name

    ingress = visit(root)
    return ingress, specs


# ---------------------------------------------------------------- lifecycle

def _get_controller(start: bool = True):
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        if not start:
            raise
    from ._private.controller import ServeController
    cls = ray_tpu.remote(num_cpus=0)(ServeController)
    controller = cls.options(name=CONTROLLER_NAME, lifetime="detached",
                             max_concurrency=64).remote()
    ray_tpu.get(controller.start_loop.remote(), timeout=60)
    return controller


def start(http_options: Optional[Union[HTTPOptions, Dict[str, Any]]] = None,
          **_compat) -> None:
    """Start Serve system actors (controller + HTTP proxy)."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    controller = _get_controller()
    if http_options is None:
        http_options = HTTPOptions()
    elif isinstance(http_options, dict):
        http_options = HTTPOptions(**http_options)
    try:
        ray_tpu.get_actor(PROXY_NAME)
    except ValueError:
        from ._private.proxy import ProxyActor
        cls = ray_tpu.remote(num_cpus=0)(ProxyActor)
        proxy = cls.options(name=PROXY_NAME, lifetime="detached",
                            max_concurrency=256).remote(
            http_options.host, http_options.port)
        ray_tpu.get(proxy.ready.remote(), timeout=60)
    return controller


def start_grpc(host: str = "127.0.0.1", port: int = 9000) -> int:
    """Start the gRPC ingress proxy (reference parity: the reference's
    gRPCProxy runs beside the HTTP proxy). Returns the bound port.
    Service raytpu.serve.Serve: Predict (unary bytes) / PredictStream
    (server-streaming bytes), app selected by 'application' metadata."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    _get_controller()
    try:
        proxy = ray_tpu.get_actor(GRPC_PROXY_NAME)
    except ValueError:
        from ._private.grpc_proxy import GrpcProxyActor
        cls = ray_tpu.remote(num_cpus=0)(GrpcProxyActor)
        proxy = cls.options(name=GRPC_PROXY_NAME, lifetime="detached",
                            max_concurrency=256).remote(host, port)
    return ray_tpu.get(proxy.ready.remote(), timeout=60)


def run(target: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/", blocking: bool = False,
        _start_http: bool = True,
        http_options: Optional[HTTPOptions] = None,
        timeout_s: float = 120.0,
        local_testing_mode: bool = False) -> DeploymentHandle:
    """Deploy an application and wait until it is RUNNING; returns the
    ingress handle.

    local_testing_mode=True runs every deployment in THIS process with
    no cluster, controller, or proxy (reference parity:
    serve/_private/local_testing_mode.py) — handle calls go straight to
    in-process replicas; constructors run eagerly so init errors raise
    here."""
    if not isinstance(target, Application):
        raise TypeError("serve.run expects a bound Application "
                        "(use MyDeployment.bind(...))")
    if local_testing_mode:
        from ._private import local_testing
        ingress, specs = _build_app_specs(target, name)
        local_testing.clear(name)
        local_testing.deploy_local(name, ingress, specs)
        return DeploymentHandle(ingress, name)
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    if _start_http:
        start(http_options)
    controller = _get_controller()
    ingress, specs = _build_app_specs(target, name)
    ray_tpu.get(controller.deploy_application.remote(
        name, route_prefix or f"/{name}", ingress, specs), timeout=60)
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        st = ray_tpu.get(controller.status.remote(), timeout=30)
        app = st["applications"].get(name)
        if app and app["status"] == ApplicationStatus.RUNNING:
            break
        time.sleep(0.2)
    else:
        raise TimeoutError(
            f"application {name!r} not RUNNING after {timeout_s}s: "
            f"{ray_tpu.get(controller.status.remote())}")
    handle = DeploymentHandle(ingress, name)
    if blocking:
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
    return handle


def deploy_config(config: Union[str, Dict[str, Any], "Any"], *,
                  timeout_s: float = 120.0
                  ) -> Dict[str, DeploymentHandle]:
    """Declarative deploy (the `serve deploy app.yaml` path).

    `config` is a YAML file path, a dict, or a ServeDeploySchema. Each
    application's import_path is resolved, per-deployment overrides
    applied, and the app deployed through the normal controller
    reconcile; returns {app_name: ingress handle}. Reference parity:
    serve/scripts.py `serve deploy` + schema.py ServeDeploySchema."""
    from .schema import ServeDeploySchema, build_app_from_schema
    if isinstance(config, str):
        schema = ServeDeploySchema.from_yaml(config)
    elif isinstance(config, dict):
        schema = ServeDeploySchema.from_dict(config)
    else:
        schema = config
    http = (HTTPOptions(**schema.http_options)
            if schema.http_options else None)
    handles: Dict[str, DeploymentHandle] = {}
    for app in schema.applications:
        target = build_app_from_schema(app)
        if app.runtime_env:
            target = _fold_runtime_env(target, app.runtime_env)
        handles[app.name] = run(
            target, name=app.name, route_prefix=app.route_prefix,
            http_options=http, timeout_s=timeout_s)
    return handles


def _fold_runtime_env(root: Application, runtime_env: Dict[str, Any]
                      ) -> Application:
    """App-level runtime_env becomes the default for every deployment's
    replica actors (per-deployment ray_actor_options.runtime_env wins)."""
    def fold(dep: Deployment) -> Deployment:
        opts = dict(dep.config.ray_actor_options)
        if "runtime_env" in opts:
            return dep
        opts["runtime_env"] = dict(runtime_env)
        return dep.options(ray_actor_options=opts)

    return map_deployments(root, fold)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = _get_controller(start=False)
    ingress = ray_tpu.get(controller.get_app_ingress.remote(name),
                          timeout=30)
    if ingress is None:
        raise ValueError(f"no application named {name!r}")
    return DeploymentHandle(ingress, name)


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def status() -> Dict[str, Any]:
    controller = _get_controller(start=False)
    return ray_tpu.get(controller.status.remote(), timeout=30)


def delete(name: str, _blocking: bool = True) -> None:
    from ._private import local_testing
    if local_testing.has_app(name):
        local_testing.clear(name)
        return
    controller = _get_controller(start=False)
    ray_tpu.get(controller.delete_application.remote(name), timeout=60)


def shutdown() -> None:
    from ._private import local_testing
    local_testing.clear()
    if not ray_tpu.is_initialized():
        return
    try:
        controller = _get_controller(start=False)
    except ValueError:
        return
    try:
        ray_tpu.get(controller.shutdown.remote(), timeout=60)
    except Exception:
        pass
    for actor_name in (PROXY_NAME, GRPC_PROXY_NAME, CONTROLLER_NAME):
        try:
            actor = ray_tpu.get_actor(actor_name)
            if actor_name in (PROXY_NAME, GRPC_PROXY_NAME):
                try:
                    ray_tpu.get(actor.shutdown.remote(), timeout=10)
                except Exception:
                    pass
            ray_tpu.kill(actor)
        except Exception:
            pass
    from . import handle as _handle_mod
    with _handle_mod._routers_lock:
        _handle_mod._routers.clear()
