"""@serve.batch: transparent request batching inside a replica.

Reference parity: serve/batching.py — callers invoke the wrapped method
with single items; a background flusher gathers up to max_batch_size
items (or waits batch_wait_timeout_s) and invokes the underlying
function ONCE with the list; per-item results resolve each caller's
future. On TPU replicas this is what keeps the MXU fed: many small HTTP
requests fuse into one batched forward pass.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = batch_wait_timeout_s
        self.queue: List = []        # (item, future)
        self._flush_task: Optional[asyncio.Task] = None

    async def submit(self, instance, item) -> Any:
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self.queue.append((item, fut))
        if len(self.queue) >= self.max_batch_size:
            self._do_flush(instance)
        elif self._flush_task is None:
            self._flush_task = loop.create_task(
                self._delayed_flush(instance))
        return await fut

    async def _delayed_flush(self, instance):
        await asyncio.sleep(self.timeout_s)
        self._do_flush(instance)

    def _do_flush(self, instance) -> None:
        if self._flush_task is not None:
            self._flush_task.cancel()
            self._flush_task = None
        batch, self.queue = self.queue, []
        if batch:
            asyncio.ensure_future(self._run_batch(instance, batch))

    async def _run_batch(self, instance, batch) -> None:
        items = [b[0] for b in batch]
        futures = [b[1] for b in batch]
        try:
            if instance is not None:
                results = await self.fn(instance, items)
            else:
                results = await self.fn(items)
            if not isinstance(results, list) or len(results) != len(items):
                raise TypeError(
                    f"@serve.batch function must return a list of "
                    f"{len(items)} results, got {type(results).__name__}")
        except Exception as e:
            for fut in futures:
                if not fut.done():
                    fut.set_exception(e)
            return
        for fut, res in zip(futures, results):
            if not fut.done():
                fut.set_result(res)


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator for async methods/functions taking a list of items."""

    def wrap(fn):
        if not asyncio.iscoroutinefunction(fn):
            raise TypeError("@serve.batch requires an async function")
        attr = f"__serve_batch_queue_{fn.__name__}"

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:          # bound method: (self, item)
                instance, item = args
                q = getattr(instance, attr, None)
                if q is None:
                    q = _BatchQueue(fn, max_batch_size,
                                    batch_wait_timeout_s)
                    setattr(instance, attr, q)
                return await q.submit(instance, item)
            (item,) = args              # free function
            q = getattr(wrapper, "_queue", None)
            if q is None:
                q = wrapper._queue = _BatchQueue(
                    fn, max_batch_size, batch_wait_timeout_s)
            return await q.submit(None, item)

        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
