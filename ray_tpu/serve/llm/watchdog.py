"""Multi-window SLO burn-rate watchdog for the serving fleet.

ISSUE 7: PR 5 gave every engine monotone SLO totals and PR 6 put a
control loop over them, but nothing WATCHED the error budget — the
autoscaler reacted to means, so by the time a breach showed up the SLO
was already blown. This watchdog implements the SRE-book multi-window
burn-rate alert over the fleet's summed `EngineTelemetry.slo_totals()`
(now carrying `*_bad` violation counts per SLO target):

    burn = (bad / total in window) / (1 - objective)

i.e. how many times faster than "allowed" the fleet is consuming its
error budget. A burn of 1.0 exactly spends the budget; sustained burn
over `page_burn_rate` in BOTH the short and long windows pages. Two
windows make the alert both fast (the short window reacts in seconds)
and flap-proof (the long window ignores a single bad tick); recovery
requires the short window to cool below `warn_burn_rate`, so a page
doesn't clear on one good second.

Consumers, wired by FleetManager:
- `slo_burn_rate{slo,window}` gauges + `slo_alerts_total{slo}` counter
  in this process's Prometheus registry (rides the fleet /metrics);
- an `slo_alert` flight-recorder event on every page transition
  (plus `slo_clear` on recovery);
- `paging` — the pre-emptive signal: the autoscaler treats it as an
  instant breach (scale up BEFORE the SLO is blown) and admission
  engages brownout (shed early, shed cheap) while it holds.

Pure host-side control-plane math on snapshots the fleet already
collects: zero engine involvement, zero device syncs.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Callable, Deque, Dict, Optional, Tuple

from ...util import metrics as metrics_api

# slo name -> (observation-count key, violation-count key) in the
# summed slo_totals() dict
_SLO_KEYS = {
    "ttft": ("ttft_n", "ttft_bad"),
    "queue_wait": ("queue_n", "queue_bad"),
    "e2e": ("e2e_n", "e2e_bad"),
}


@dataclasses.dataclass
class WatchdogConfig:
    enabled: bool = True
    # fraction of requests that must meet each SLO target (the error
    # budget is 1 - objective)
    objective: float = 0.9
    # multi-window lengths: short reacts, long de-flaps
    short_window_s: float = 30.0
    long_window_s: float = 300.0
    # burn thresholds: BOTH windows over page_burn_rate -> page;
    # recovery needs the short window back under warn_burn_rate
    page_burn_rate: float = 2.0
    warn_burn_rate: float = 1.0
    # windows with fewer observations than this are judged quiet
    # (burn 0) — two bad requests out of three must not page a fleet
    min_observations: int = 8
    slos: Tuple[str, ...] = ("ttft", "queue_wait", "e2e")
    # KV page pressure (ISSUE 10): (device pages used + parked host
    # pages) / usable, max over active replicas. Sustained demand
    # past `high` flags pressure_state="high" (alert + gauge);
    # recovery needs it back under `warn` (hysteresis). Whether high
    # pressure also BROWNOUTS the front door depends on spillability:
    # FleetManager sheds only when the pressured replicas cannot
    # spill — pages short but spillable is a latency tier the
    # admission queue absorbs, not overload (ISSUE 10 satellite).
    page_pressure_high: float = 1.5
    page_pressure_warn: float = 1.0
    # consecutive observations over `high` before flagging (one
    # bursty probe must not alert)
    page_pressure_count: int = 2
    # Tick-anomaly page PRECURSOR (ISSUE 13): max recent anomaly rate
    # over active replicas (from each engine's TickAnomalyDetector).
    # Sustained rate past `high` flags anomaly_state="high" — an
    # early-warning alert BEFORE SLO burn shows up (a stalling/
    # recompiling replica goes anomalous ticks before it goes slow
    # enough to burn budget); recovery needs it back under `warn`.
    # Watch-only: it never brownouts the front door on its own.
    anomaly_rate_high: float = 0.25
    anomaly_rate_warn: float = 0.10
    anomaly_count: int = 2


class SLOBurnWatchdog:
    """Feed `observe()` monotone fleet-summed slo_totals; read
    `paging` / `state` / `last`. Injectable `now` for tests."""

    def __init__(self, config: Optional[WatchdogConfig] = None,
                 recorder: Any = None,
                 clock: Optional[Callable[[], float]] = None):
        self.config = config or WatchdogConfig()
        # injectable clock (ISSUE 14): burn windows are pure deltas
        # over whatever monotone time source drives observe() — the
        # fleet simulator passes its virtual clock here
        self._clock = clock if clock is not None else time.monotonic
        unknown = set(self.config.slos) - set(_SLO_KEYS)
        if unknown:
            # fail at fleet build, not as a KeyError on every control-
            # loop tick (slos is wire-exposed through FleetConfig)
            raise ValueError(
                f"unknown watchdog slo(s) {sorted(unknown)}; "
                f"tracked: {sorted(_SLO_KEYS)}")
        self.recorder = recorder           # FlightRecorder-compatible
        self._snaps: Deque[Tuple[float, Dict[str, float]]] = \
            collections.deque()
        self.state: Dict[str, str] = {s: "ok" for s in self.config.slos}
        self.last: Dict[str, Any] = {}
        self.paging = False
        self.max_burn = 0.0
        self.alerts_total = 0
        self._burn_gauge = metrics_api.Gauge(
            "ray_tpu_llm_slo_burn_rate",
            "error-budget burn rate per SLO and window "
            "(1.0 = spending exactly the budget)",
            ("slo", "window"))
        self._alerts = metrics_api.Counter(
            "ray_tpu_llm_slo_alerts_total",
            "watchdog page transitions per SLO", ("slo",))
        # KV page-pressure monitor (ISSUE 10)
        self.pressure_state = "ok"
        self.last_pressure = 0.0
        self._pressure_over = 0
        self._pressure_gauge = metrics_api.Gauge(
            "ray_tpu_llm_fleet_page_pressure",
            "max KV page pressure over active replicas "
            "((used + parked host pages) / usable; > 1 = "
            "oversubscribed)")
        # tick-anomaly page precursor (ISSUE 13)
        self.anomaly_state = "ok"
        self.last_anomaly_rate = 0.0
        self._anomaly_over = 0
        self._anomaly_gauge = metrics_api.Gauge(
            "ray_tpu_llm_fleet_anomaly_rate",
            "max recent tick-anomaly rate over active replicas "
            "(the SLO-page precursor signal)")

    # -- burn math -----------------------------------------------------
    def _window_delta(self, horizon: float, cur: Dict[str, float],
                      n_key: str, bad_key: str) -> Tuple[float, float]:
        """Delta of (observations, violations) since the newest
        snapshot at or before `horizon` (falling back to the oldest —
        a young watchdog judges over its whole history)."""
        base: Optional[Dict[str, float]] = None
        for ts, totals in self._snaps:
            if ts <= horizon:
                base = totals
            else:
                break
        if base is None and self._snaps:
            base = self._snaps[0][1]
        if base is None:
            return 0.0, 0.0
        return (max(cur.get(n_key, 0.0) - base.get(n_key, 0.0), 0.0),
                max(cur.get(bad_key, 0.0) - base.get(bad_key, 0.0),
                    0.0))

    def _burn(self, horizon: float, cur: Dict[str, float],
              n_key: str, bad_key: str) -> "Tuple[float, float]":
        """(burn rate, observations) for the window; a window below
        min_observations judges burn 0 — but the caller still needs n
        to distinguish 'healthy' from 'no evidence' (a stalled fleet
        must not read as recovered)."""
        n, bad = self._window_delta(horizon, cur, n_key, bad_key)
        if n < self.config.min_observations:
            return 0.0, n
        budget = max(1.0 - self.config.objective, 1e-6)
        return (bad / n) / budget, n

    # -- page pressure (ISSUE 10) --------------------------------------
    def observe_pressure(self, pressure: float) -> bool:
        """One page-pressure observation (fleet max). Sets the gauge,
        drives the hysteretic ok/high state, records alert/clear
        flight-recorder events. Returns True when the state changed.
        The caller (FleetManager) decides the brownout reaction using
        fleet spillability — this monitor only watches."""
        cfg = self.config
        self.last_pressure = float(pressure)
        self._pressure_gauge.set(round(self.last_pressure, 4))
        prev = self.pressure_state
        if self.last_pressure >= cfg.page_pressure_high:
            self._pressure_over += 1
            if self._pressure_over >= cfg.page_pressure_count:
                self.pressure_state = "high"
        elif self.last_pressure < cfg.page_pressure_warn:
            self._pressure_over = 0
            self.pressure_state = "ok"
        else:
            self._pressure_over = 0      # warn band: hold state
        changed = self.pressure_state != prev
        if changed and self.recorder is not None:
            self.recorder.record(
                "page_pressure_alert" if self.pressure_state == "high"
                else "page_pressure_clear",
                pressure=round(self.last_pressure, 4),
                high=cfg.page_pressure_high)
        return changed

    # -- tick-anomaly precursor (ISSUE 13) -----------------------------
    def observe_anomaly(self, rate: float) -> bool:
        """One fleet-max anomaly-rate observation. Same hysteretic
        shape as observe_pressure: consecutive readings over `high`
        flag, recovery under `warn` clears, alert/clear land in the
        flight recorder. Watch-only — the point is a page PRECURSOR:
        the alert fires while the SLO budget is still intact, so an
        operator (or the postmortem reader) sees the anomaly storm
        that preceded the burn. Returns True on a state change."""
        cfg = self.config
        self.last_anomaly_rate = float(rate)
        self._anomaly_gauge.set(round(self.last_anomaly_rate, 4))
        prev = self.anomaly_state
        if self.last_anomaly_rate >= cfg.anomaly_rate_high:
            self._anomaly_over += 1
            if self._anomaly_over >= cfg.anomaly_count:
                self.anomaly_state = "high"
        elif self.last_anomaly_rate < cfg.anomaly_rate_warn:
            self._anomaly_over = 0
            self.anomaly_state = "ok"
        else:
            self._anomaly_over = 0       # warn band: hold state
        changed = self.anomaly_state != prev
        if changed and self.recorder is not None:
            self.recorder.record(
                "anomaly_rate_alert" if self.anomaly_state == "high"
                else "anomaly_rate_clear",
                rate=round(self.last_anomaly_rate, 4),
                high=cfg.anomaly_rate_high)
        return changed

    # -- the tick ------------------------------------------------------
    def observe(self, totals: Dict[str, float],
                now: Optional[float] = None,
                idle: bool = False) -> Dict[str, Any]:
        """One watchdog evaluation over the fleet-summed monotone
        totals. Returns (and stores in .last) the per-SLO report.

        `idle=True` asserts the caller sees NO interactive demand
        anywhere (front door empty, zero interactive requests queued
        or decoding on any replica): an empty short window then means
        a healthy trough, and a held page clears. Without it, zero
        observations under a page read as a total stall — requests
        arriving but nothing completing — and the page holds (ISSUE
        14: a post-burst page latched through an idle trough wedged
        brownout shut and starved the batch lane forever)."""
        cfg = self.config
        if not cfg.enabled:
            return {}
        now = self._clock() if now is None else now
        report: Dict[str, Any] = {}
        for slo in cfg.slos:
            n_key, bad_key = _SLO_KEYS[slo]
            short, short_n = self._burn(now - cfg.short_window_s,
                                        totals, n_key, bad_key)
            long_, _ = self._burn(now - cfg.long_window_s, totals,
                                  n_key, bad_key)
            self._burn_gauge.set(round(short, 4),
                                 {"slo": slo, "window": "short"})
            self._burn_gauge.set(round(long_, 4),
                                 {"slo": slo, "window": "long"})
            prev = self.state[slo]
            if min(short, long_) >= cfg.page_burn_rate:
                state = "page"
            elif prev == "page" and (
                    short >= cfg.warn_burn_rate
                    or (short_n < cfg.min_observations
                        and not idle)):
                # hysteresis: recovery needs EVIDENCE — a cooled short
                # window with enough observations. A totally stalled
                # fleet (zero new requests) is the outage at its
                # worst, not recovery; hold the page until traffic
                # flows again. EXCEPT when the caller vouches the
                # fleet is demand-idle (`idle=True`): an empty window
                # over an empty fleet is a trough, and holding the
                # page there would latch brownout with nobody left to
                # shed.
                state = "page"
            elif min(short, long_) >= cfg.warn_burn_rate:
                state = "warn"
            else:
                state = "ok"
            if state == "page" and prev != "page":
                self.alerts_total += 1
                self._alerts.inc(1, {"slo": slo})
                if self.recorder is not None:
                    self.recorder.record(
                        "slo_alert", slo=slo,
                        burn_short=round(short, 3),
                        burn_long=round(long_, 3),
                        objective=cfg.objective)
            elif state != "page" and prev == "page" \
                    and self.recorder is not None:
                self.recorder.record("slo_clear", slo=slo,
                                     burn_short=round(short, 3))
            self.state[slo] = state
            report[slo] = {"burn_short": round(short, 4),
                           "burn_long": round(long_, 4),
                           "state": state}
        # retain one snapshot older than the long window as the
        # baseline, prune the rest
        self._snaps.append((now, dict(totals)))
        horizon = now - cfg.long_window_s
        while len(self._snaps) > 1 and self._snaps[1][0] <= horizon:
            self._snaps.popleft()
        self.paging = any(st == "page" for st in self.state.values())
        self.max_burn = max(
            (min(r["burn_short"], r["burn_long"])
             for r in report.values()), default=0.0)
        self.last = report
        return report


__all__ = ["WatchdogConfig", "SLOBurnWatchdog"]
