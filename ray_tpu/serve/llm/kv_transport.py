"""Fleet KV transport: KV pages as a fleet-level currency (ISSUE 12).

PR 10 made KV pages *movable* (`ParkedSequence`: position/last_token/
seed + host page arrays) but they never left a replica. This module is
the shipping layer that ROADMAP item 2 scopes on top of it — ONE
versioned, checksummed wire format plus the fleet-side policy objects,
with three consumers layered on the same transport:

(a) **Disaggregated prefill/decode** — `FleetConfig.replica_roles`
    marks replicas `prefill` / `decode` / `mixed`; the fleet relay
    sends long prompts to a prefill replica (`prefill_export`), which
    runs the prompt, parks the session via the PR 10 spill path, and
    hands the pages to a decode replica that resumes via
    `resume_stream_tokens` → `engine.import_session` →
    `_restore_parked`. Token-exact vs a single-engine oracle (the
    per-request sampling key is fold_in(seed, absolute index), and
    restored pages are bit-exact copies), so long-prompt bursts stop
    inflating decode ITL without any correctness tax (the
    DistServe-style split; Gemma-on-TPU serving study, PAPERS.md).

(b) **Live session migration** — drain-before-downscale ships parked
    sessions instead of replaying tokens (`FleetManager.
    _migrate_sessions_off`), and PR 9's failover-by-replay gains a
    failover-by-restore fast path: when a failing replica can still
    export the session (its pages were already spilled, or only the
    stream — not the engine — is wedged), the fleet restores on a
    healthy replica instead of re-prefilling the whole transcript.

(c) **Fleet prefix store** — `FleetPrefixStore` promotes the
    per-replica prefix cache to a fleet-shared tier keyed by prefix
    fingerprint: a system prompt prefilled ONCE is exported
    (`export_prefix`) into the store and seeded into every replica
    that later serves the prefix (`import_prefix` →
    `allocator.register_prefix`), multiplying PR 6's per-replica
    prefix-cache hit rate by fleet size.

Wire format (`encode_session`/`decode_session`, `encode_prefix`/
`decode_prefix` — both ride `_encode_frame`):

    b"RTKV" | u16 version | u32 header_len | header JSON |
    raw array bytes (C order, concatenated) | u32 crc32

The crc32 covers every byte before it; arrays round-trip BYTE-exact
(dtype + shape recorded in the header, bfloat16 et al. resolved via
ml_dtypes). A corrupted or truncated payload raises
`TransportChecksumError` / `TransportError` — consumers treat that as
"this ship failed" and fall back to the PR 9 replay path, never as a
crash (the serialization property test drives both).

Wire v2 (ISSUE 16): quantized engines ship pages AS STORED — int8/fp8
value arrays plus the per-(row, head) f32 scale arrays — so
quantize-on-ship falls out of the page format (a v2 int8 session frame
is ~1/4 the bytes of its f32 twin). The header meta carries `kv_dtype`
(the `ops/kv_quant.py` kind) and the scale arrays ride beside k/v as
`k_scales`/`v_scales`. v1 frames (no kv_dtype, no scales) still decode
as f32. The DECODER validates frame self-consistency (a quantized
frame missing scales, or scale shapes that disagree with the pages, is
a bad payload); kind compatibility with the RECEIVING engine is the
import surface's job — `ship_kind_compatible` raises TransportError on
mismatch so consumers hit the same replay fallback, because narrow
pages must never be reinterpreted across storage kinds.

Everything here is host-side: numpy + stdlib, no jax, no device work
(the dispatch-guard suite runs with the transport active). The engine
side (`export_session` / `import_session` / `export_prefix` /
`import_prefix`, built on `preempt()` / `_restore_parked`) lives in
llm/_internal/engine.py; the HTTP surface in llm/_internal/server.py;
the orchestration in fleet.py.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import struct
import zlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...util import metrics as metrics_api

MAGIC = b"RTKV"
# v2 (ISSUE 16): kv_dtype in meta + per-(row, head) scale arrays for
# quantized pages. v1 frames (implicitly f32, no scales) still decode.
WIRE_VERSION = 2
SUPPORTED_WIRE_VERSIONS = (1, 2)


class TransportError(RuntimeError):
    """A payload that cannot be decoded (bad magic, truncation,
    unknown version, malformed header). The consumer falls back to
    token replay — this is a failed SHIP, never a crash."""


class TransportChecksumError(TransportError):
    """The payload's crc32 does not match its content: corruption in
    flight. Same fallback contract as TransportError."""


def _dtype(name: str) -> np.dtype:
    """Resolve a recorded dtype name, including the ml_dtypes family
    (bfloat16, float8_*) numpy alone cannot name."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        try:
            return np.dtype(getattr(ml_dtypes, name))
        except AttributeError:
            raise TransportError(f"unknown array dtype {name!r}")


def _encode_frame(kind: str, meta: Dict[str, Any],
                  arrays: Sequence[Tuple[str, np.ndarray]]) -> bytes:
    """One wire frame. The header is pure JSON (kind, meta, and per-
    array name/dtype/shape/nbytes); array payloads follow in header
    order as raw C-contiguous bytes; the trailing crc32 covers every
    byte before it."""
    blobs: List[bytes] = []
    adesc: List[Dict[str, Any]] = []
    for name, arr in arrays:
        arr = np.ascontiguousarray(arr)
        raw = arr.tobytes()
        adesc.append({"name": name, "dtype": arr.dtype.name,
                      "shape": list(arr.shape), "nbytes": len(raw)})
        blobs.append(raw)
    header = json.dumps({"kind": kind, "meta": meta,
                         "arrays": adesc},
                        sort_keys=True).encode("utf-8")
    body = (MAGIC + struct.pack("<HI", WIRE_VERSION, len(header))
            + header + b"".join(blobs))
    return body + struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF)


def _decode_frame(blob: bytes, expect_kind: Optional[str] = None
                  ) -> Tuple[str, Dict[str, Any],
                             Dict[str, np.ndarray]]:
    if not isinstance(blob, (bytes, bytearray, memoryview)):
        raise TransportError(
            f"payload must be bytes, got {type(blob).__name__}")
    blob = bytes(blob)
    if len(blob) < len(MAGIC) + 6 + 4:
        raise TransportError("payload truncated (shorter than the "
                             "fixed frame header)")
    if blob[:4] != MAGIC:
        raise TransportError("bad magic (not a KV transport frame)")
    body, (crc,) = blob[:-4], struct.unpack("<I", blob[-4:])
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise TransportChecksumError(
            "payload checksum mismatch (corrupted in flight)")
    version, hlen = struct.unpack("<HI", blob[4:10])
    if version not in SUPPORTED_WIRE_VERSIONS:
        raise TransportError(
            f"unsupported wire version {version} "
            f"(this build speaks {SUPPORTED_WIRE_VERSIONS})")
    if 10 + hlen > len(body):
        raise TransportError("payload truncated (header)")
    try:
        header = json.loads(body[10:10 + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise TransportError(f"malformed frame header: {e!r}")
    kind = header.get("kind")
    if expect_kind is not None and kind != expect_kind:
        raise TransportError(
            f"frame kind {kind!r}, expected {expect_kind!r}")
    arrays: Dict[str, np.ndarray] = {}
    off = 10 + hlen
    for d in header.get("arrays") or []:
        try:
            dt, n = _dtype(str(d["dtype"])), int(d["nbytes"])
            if off + n > len(body):
                raise TransportError("payload truncated (array body)")
            arrays[str(d["name"])] = np.frombuffer(
                body[off:off + n], dtype=dt).reshape(
                    [int(x) for x in d["shape"]])
        except TransportError:
            raise
        except (ValueError, TypeError, KeyError) as e:
            # a crc-valid frame whose header lies about its arrays
            # (nbytes not a dtype multiple, shape/size mismatch,
            # missing fields) is still a BAD PAYLOAD — consumers key
            # their fall-back-to-replay contract on TransportError
            raise TransportError(f"malformed array descriptor: {e!r}")
        off += n
    if off != len(body):
        raise TransportError("payload has trailing bytes past the "
                             "declared arrays")
    return str(kind), dict(header.get("meta") or {}), arrays


# -- session payloads ---------------------------------------------------

_SESSION_META_KEYS = (
    "request_id", "prompt_tokens", "output_tokens", "params", "lora",
    "priority", "tenant", "restarts", "trace", "deadline_epoch",
    "seed", "position", "last_token", "n_pages")


def _check_quant_arrays(kind: str, arrays: Dict[str, np.ndarray],
                        what: str) -> None:
    """Frame self-consistency for quantized payloads: a quantized
    frame with pages must carry BOTH scale arrays, each shaped like
    its page array minus the trailing head_dim axis; an f32 frame must
    carry none. Violations are bad payloads (TransportError), not
    crashes."""
    have_k = arrays.get("k") is not None
    ks, vs = arrays.get("k_scales"), arrays.get("v_scales")
    if kind == "f32":
        if ks is not None or vs is not None:
            raise TransportError(
                f"f32 {what} frame carries quant scale arrays")
        return
    if not have_k:
        return                      # cold session: no pages, no scales
    if ks is None or vs is None:
        raise TransportError(
            f"quantized ({kind}) {what} frame is missing its scale "
            f"arrays")
    for name, s in (("k_scales", ks), ("v_scales", vs)):
        page = arrays["k" if name[0] == "k" else "v"]
        if tuple(s.shape) != tuple(page.shape[:-1]):
            raise TransportError(
                f"{what} frame {name} shape {tuple(s.shape)} does not "
                f"match pages {tuple(page.shape)}")


def ship_kind_compatible(frame_kind: Optional[str],
                         engine_kind: str) -> str:
    """Gate an import against the RECEIVING engine's storage kind.
    Narrow pages are meaningless under a different kind, so a mismatch
    is a failed SHIP (TransportError → the consumer's replay
    fallback), never a reinterpretation. Returns the resolved frame
    kind (v1 frames carry none → f32)."""
    fk = str(frame_kind or "f32")
    if fk != engine_kind:
        raise TransportError(
            f"KV dtype mismatch: frame pages are {fk!r}, the "
            f"receiving engine serves {engine_kind!r} (fall back to "
            f"token replay)")
    return fk


def encode_session(state: Dict[str, Any]) -> bytes:
    """engine.export_session state dict → wire bytes. The KV arrays
    (and, for quantized engines, their scale arrays) ride raw;
    everything else (identity, sampling params, decode invariant,
    storage kind) is JSON metadata."""
    meta = {k: state.get(k) for k in _SESSION_META_KEYS}
    meta["kv_dtype"] = str(state.get("kv_dtype") or "f32")
    arrays: List[Tuple[str, np.ndarray]] = []
    if state.get("k") is not None:
        arrays = [("k", state["k"]), ("v", state["v"])]
        if state.get("k_scales") is not None:
            arrays += [("k_scales", state["k_scales"]),
                       ("v_scales", state["v_scales"])]
    return _encode_frame("session", meta, arrays)


def decode_session(blob: bytes) -> Dict[str, Any]:
    """Wire bytes → the state dict engine.import_session consumes.
    Raises TransportError/TransportChecksumError on a bad payload.
    v1 frames decode as f32 with no scales."""
    _, meta, arrays = _decode_frame(blob, expect_kind="session")
    state = dict(meta)
    state["k"] = arrays.get("k")
    state["v"] = arrays.get("v")
    if (state["k"] is None) != (state["v"] is None):
        raise TransportError("session frame carries only one of k/v")
    if int(state.get("n_pages") or 0) > 0 and state["k"] is None:
        raise TransportError("warm session frame is missing its KV "
                             "page arrays")
    state["kv_dtype"] = str(meta.get("kv_dtype") or "f32")
    _check_quant_arrays(state["kv_dtype"], arrays, "session")
    state["k_scales"] = arrays.get("k_scales")
    state["v_scales"] = arrays.get("v_scales")
    return state


def encode_prefix(tokens: Sequence[int], k: np.ndarray,
                  v: np.ndarray,
                  k_scales: Optional[np.ndarray] = None,
                  v_scales: Optional[np.ndarray] = None,
                  kv_dtype: str = "f32") -> bytes:
    """engine.export_prefix output → wire bytes (the fleet prefix
    store's stored value). Quantized prefixes ship their scale arrays
    beside the narrow pages."""
    arrays: List[Tuple[str, np.ndarray]] = [("k", k), ("v", v)]
    if k_scales is not None:
        arrays += [("k_scales", k_scales), ("v_scales", v_scales)]
    return _encode_frame(
        "prefix", {"tokens": [int(t) for t in tokens],
                   "kv_dtype": str(kv_dtype or "f32")}, arrays)


def decode_prefix(blob: bytes) -> Dict[str, Any]:
    """Wire bytes → {tokens, k, v, k_scales, v_scales, kv_dtype}
    (scales None / kv_dtype "f32" for v1 and f32 frames)."""
    _, meta, arrays = _decode_frame(blob, expect_kind="prefix")
    if "k" not in arrays or "v" not in arrays:
        raise TransportError("prefix frame is missing its KV arrays")
    kind = str(meta.get("kv_dtype") or "f32")
    _check_quant_arrays(kind, arrays, "prefix")
    return {"tokens": [int(t) for t in meta.get("tokens") or []],
            "k": arrays["k"], "v": arrays["v"],
            "k_scales": arrays.get("k_scales"),
            "v_scales": arrays.get("v_scales"),
            "kv_dtype": kind}


def to_b64(blob: bytes) -> str:
    """Payloads cross replica boundaries inside JSON-ish bodies; b64
    keeps them transport-safe on every client flavor."""
    return base64.b64encode(blob).decode("ascii")


def from_b64(payload: str) -> bytes:
    try:
        return base64.b64decode(payload, validate=True)
    except Exception as e:
        raise TransportError(f"payload is not valid base64: {e!r}")


def prompt_char_len(body: Dict[str, Any]) -> int:
    """Prompt length in characters — the disaggregation trigger reads
    the same canonical text prefix_fingerprint hashes (prompt string,
    or the role-tagged chat rendering)."""
    if body.get("prompt") is not None:
        return len(str(body["prompt"]))
    return sum(len(str(m.get("role", ""))) + len(str(m.get("content",
                                                           "")))
               for m in (body.get("messages") or []))


# -- fleet policy -------------------------------------------------------

ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_MIXED = "mixed"
REPLICA_ROLES = (ROLE_PREFILL, ROLE_DECODE, ROLE_MIXED)


@dataclasses.dataclass
class TransportConfig:
    """Fleet KV-shipping policy (FleetConfig.transport; None = the
    transport is off and the fleet behaves exactly like PR 11)."""
    # (a) disaggregated prefill/decode: prompts at least this many
    # characters long are prefilled on a `prefill`-role replica and
    # handed to a decode replica (no-op without prefill replicas)
    enable_disagg: bool = True
    disagg_prompt_chars: int = 256
    # (b) live migration: drain-before-downscale ships parked
    # sessions instead of replaying, and stream failover tries an
    # export-restore fast path before falling back to PR 9 replay
    enable_migration: bool = True
    # (c) fleet prefix store: prompts whose ROUTER-DEPTH prefix is at
    # least this long are published once and seeded into every
    # replica that serves the prefix
    enable_prefix_store: bool = True
    prefix_min_chars: int = 64
    prefix_store_bytes: int = 256 << 20
    # bound on every export/import control call (a wedged replica
    # must not stall a drain or a failover decision)
    ship_timeout_s: float = 10.0


@dataclasses.dataclass
class _PrefixEntry:
    payload: str                 # b64 wire frame (encode_prefix)
    nbytes: int
    tokens: int                  # full-page token count stored
    publisher: str               # replica that exported it
    seeded: set = dataclasses.field(default_factory=set)
    hits: int = 0                # lookups that found this entry
    last_seq: int = 0            # recency stamp (store-wide counter)


class FleetPrefixStore:
    """Fleet-shared prefix tier: prefix fingerprint → serialized full
    prompt pages, byte-bounded. Lives in the ingress process (one per
    FleetManager); replicas are SEEDED lazily — the first time the
    router lands a stored prefix on a replica that has not seen it,
    the fleet imports the pages there before dispatching, so the
    replica's own prefix cache hits exactly as if it had prefilled
    the prompt itself.

    Eviction is HIT-FREQUENCY-WEIGHTED, not LRU-by-bytes (ROADMAP
    item 2 "REMAINS"): under byte pressure the victim is the entry
    with the lowest hits-per-byte score (ties broken
    least-recently-used). A hot small system prompt — the store's
    whole reason to exist — therefore outlives a cold large prefix
    that happens to have arrived later, where pure LRU would churn
    the hot entry out the moment a burst of large cold prefixes
    passed through."""

    def __init__(self, capacity_bytes: int = 256 << 20):
        self.capacity_bytes = int(capacity_bytes)
        self._entries: "OrderedDict[str, _PrefixEntry]" = OrderedDict()
        self._seq = 0
        self.bytes_used = 0
        self.publishes = 0
        self.hits = 0                # imports that seeded a replica
        self.evictions = 0

    def __contains__(self, fp: str) -> bool:
        return fp in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, fp: str) -> Optional[_PrefixEntry]:
        ent = self._entries.get(fp)
        if ent is not None:
            self._seq += 1
            ent.hits += 1
            ent.last_seq = self._seq
        return ent

    @staticmethod
    def _score(ent: _PrefixEntry) -> "Tuple[float, int]":
        """Eviction priority, LOWEST evicted first: hit frequency per
        byte (a hot small entry scores far above a cold large one),
        recency as the tie-break. New entries start at 0 hits — they
        must earn their residency."""
        return (ent.hits / max(ent.nbytes, 1), ent.last_seq)

    def put(self, fp: str, payload: str, tokens: int,
            publisher: str) -> Optional[_PrefixEntry]:
        """Store one published prefix (publisher counts as seeded).
        Oversized payloads are refused rather than thrashing the
        whole store."""
        if fp in self._entries:
            return self._entries[fp]
        nbytes = len(payload)
        if nbytes > self.capacity_bytes:
            return None
        while self.bytes_used + nbytes > self.capacity_bytes \
                and self._entries:
            victim = min(self._entries,
                         key=lambda k: self._score(self._entries[k]))
            old = self._entries.pop(victim)
            self.bytes_used -= old.nbytes
            self.evictions += 1
        self._seq += 1
        ent = _PrefixEntry(payload=payload, nbytes=nbytes,
                           tokens=tokens, publisher=publisher,
                           seeded={publisher}, last_seq=self._seq)
        self._entries[fp] = ent
        self.bytes_used += nbytes
        self.publishes += 1
        return ent

    def stats(self) -> Dict[str, Any]:
        return {
            "entries": len(self._entries),
            "bytes_used": self.bytes_used,
            "capacity_bytes": self.capacity_bytes,
            "policy": "hit-frequency-weighted",
            "publishes": self.publishes,
            "hits": self.hits,
            "evictions": self.evictions,
            "seeded_replicas": sorted(
                {r for e in self._entries.values() for r in e.seeded}),
        }


def transport_metrics() -> Dict[str, Any]:
    """The fleet transport metric families, registered idempotently
    in the ingress process registry (same pattern as the failure
    plane's fleet_metrics)."""
    C = metrics_api.Counter
    return {
        "sessions_shipped": C(
            "ray_tpu_llm_kv_sessions_shipped_total",
            "parked sessions shipped between replicas, by consumer "
            "(disagg | migration | restore)", ("model", "kind")),
        "ship_bytes": C(
            "ray_tpu_llm_kv_ship_bytes_total",
            "serialized KV transport bytes, by direction (export = "
            "off a replica, import = onto one)",
            ("model", "direction")),
        "prefix_store_hits": C(
            "ray_tpu_llm_prefix_store_hits_total",
            "fleet prefix-store entries seeded into a replica that "
            "had not prefilled the prefix itself", ("model",)),
    }


__all__ = [
    "TransportError", "TransportChecksumError", "TransportConfig",
    "FleetPrefixStore", "transport_metrics",
    "encode_session", "decode_session", "encode_prefix",
    "decode_prefix", "ship_kind_compatible", "to_b64", "from_b64",
    "prompt_char_len",
    "WIRE_VERSION", "SUPPORTED_WIRE_VERSIONS", "MAGIC",
    "ROLE_PREFILL", "ROLE_DECODE", "ROLE_MIXED", "REPLICA_ROLES",
]
