"""Continuous-batching-aware replica routing for LLM fleets.

ISSUE 6: round-robin (or pow-2 over request counts, serve/handle.py)
is the wrong policy for a paged-attention engine fleet — at production
concurrency the binding constraint is KV pages, not request counts
(Ragged Paged Attention, PAPERS.md), and a request whose prompt prefix
is already resident in some replica's prefix cache costs a fraction of
a cold prefill there. So replica choice is:

1. **Prefix affinity**: the request's prompt-prefix fingerprint maps
   onto a consistent-hash ring over the active replicas. Identical
   prefixes land on the same replica, so its hash-consed prompt pages
   (llm/_internal/kv_cache.py) keep getting hit; replica add/remove
   moves only the keys adjacent to the changed vnodes.
2. **Load-based spillover**: when the affinity target is saturated
   (KV-page occupancy or waiting-queue depth past the spill
   thresholds), the walk continues around the ring — the SECOND
   choice for a prefix is also sticky, so a hot prefix warms a
   deterministic small set of replicas instead of spraying everywhere.
3. **Scored fallback**: if every replica is past the spill thresholds
   the least-loaded one wins by score (see `score()` — the formula is
   documented in BENCH_CORE.md "Serving fleet anatomy").

The router consumes each replica's existing stats surface (PR 5's
KV-occupancy / queue-depth / prefix-hit gauges via
`LLMServerImpl.fleet_stats()`); it never touches the engine.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import itertools
import json
import time
from typing import Any, Callable, Dict, List, Optional, Sequence


def _h(key: str) -> int:
    """Stable 64-bit point on the ring (sha1; hash() is salted)."""
    return int.from_bytes(
        hashlib.sha1(key.encode()).digest()[:8], "big")


def prefix_fingerprint(body: Dict[str, Any], depth: int = 256) -> str:
    """Fingerprint of the request's prompt PREFIX (first `depth`
    characters of the canonical prompt text) — requests sharing it
    route to the same replica. Character depth approximates the
    page-aligned token prefix the KV cache actually shares: two
    prompts identical for 256 chars share their leading prompt pages
    for any tokenizer in this repo. Chat requests canonicalize to the
    same role-tagged rendering the server's chat template consumes, so
    a shared system prompt + history is a shared fingerprint even as
    the final user turn varies beyond `depth`."""
    if body.get("prompt") is not None:
        text = str(body["prompt"])
    else:
        text = "\x1e".join(
            f"{m.get('role', '')}\x1f{m.get('content', '')}"
            for m in (body.get("messages") or []))
        if not text:
            text = json.dumps(body, sort_keys=True, default=str)
    return hashlib.sha1(text[:depth].encode()).hexdigest()


class HashRing:
    """Consistent-hash ring with virtual nodes.

    `preferred(key)` returns every live node, deduplicated, in ring
    order starting from the key's hash point — the router's spillover
    walk. Removing a node only remaps keys whose nearest vnode was
    the removed node's (the classic minimal-disruption property; the
    fleet tests assert it)."""

    # walk orderings memoized per key between membership changes: a
    # production fleet routes thousands of requests (and the traffic
    # simulator millions — ISSUE 14) over repeating prefix
    # fingerprints while the ring stays put, and the walk is the
    # expensive part of a pick. Bounded; cleared on add/remove.
    _CACHE_MAX = 4096

    def __init__(self, vnodes: int = 64):
        self.vnodes = vnodes
        self._points: List[int] = []        # sorted vnode hashes
        self._owner: Dict[int, str] = {}    # vnode hash -> node
        self._nodes: set = set()
        self._walks: Dict[str, List[str]] = {}

    def add(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        self._walks.clear()
        for i in range(self.vnodes):
            p = _h(f"{node}#{i}")
            # vnode collisions across nodes are astronomically rare;
            # keep the first owner so add/remove stays symmetric
            if p in self._owner:
                continue
            self._owner[p] = node
            bisect.insort(self._points, p)

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        self._walks.clear()
        dead = [p for p, n in self._owner.items() if n == node]
        for p in dead:
            del self._owner[p]
            self._points.pop(bisect.bisect_left(self._points, p))

    def nodes(self) -> List[str]:
        return sorted(self._nodes)

    def __len__(self) -> int:
        return len(self._nodes)

    def preferred(self, key: str) -> List[str]:
        """All nodes in ring-walk order from `key`'s point. The
        returned list is a cache entry — callers read, never mutate."""
        if not self._points:
            return []
        hit = self._walks.get(key)
        if hit is not None:
            return hit
        out: List[str] = []
        seen = set()
        start = bisect.bisect_left(self._points, _h(key))
        n = len(self._points)
        for off in range(n):
            node = self._owner[self._points[(start + off) % n]]
            if node not in seen:
                seen.add(node)
                out.append(node)
                if len(out) == len(self._nodes):
                    break
        if len(self._walks) >= self._CACHE_MAX:
            self._walks.clear()
        self._walks[key] = out
        return out


@dataclasses.dataclass
class ReplicaSnapshot:
    """One replica's routing inputs (from LLMServerImpl.fleet_stats)."""
    replica: str
    active: int = 0                  # requests holding a decode slot
    waiting: int = 0                 # engine admission queue depth
    # slice topology (ISSUE 17): chips this replica's engine mesh
    # occupies (tp-sharded engines on pod slices report >1) — /fleet
    # rows show it and the fleet's capacity accounting is chip-, not
    # replica-, denominated. Per-chip MFU: the engine's PerfAccountant
    # already divides by mesh size, so `mfu` here is per chip.
    chips: int = 1
    # batch lane (ISSUE 14): how much of `waiting`/`active` is
    # priority-0 batch-lane work — the autoscaler/watchdog plane
    # subtracts it from its overload signals (a deep queue of
    # preemptible bulk jobs is harvested idle capacity, not overload)
    waiting_batch: int = 0
    active_batch: int = 0
    # fraction of the usable KV pool held by batch-lane slots: the
    # autoscaler's idle check reads occupancy MINUS this (a fleet
    # soaked to 85% with displaceable bulk work must still scale
    # down when interactive traffic leaves)
    kv_occupancy_batch: float = 0.0
    kv_occupancy: float = 0.0        # used / usable KV pages
    free_pages: int = 0
    cache_hit_rate: float = 0.0      # cumulative prefix-cache hit rate
    last_tick_age_s: Optional[float] = None
    # KV memory hierarchy (ISSUE 10): demand on the device pool
    # ((used + parked host pages) / usable; > 1 = oversubscribed),
    # parked session count, and whether the replica can ABSORB page
    # pressure by spilling (host tier on) — pages short on a spillable
    # replica is a latency tier, not saturation
    page_pressure: float = 0.0
    parked: int = 0
    spillable: bool = False
    # ISSUE 12 satellite: host-tier BYTE occupancy beside the page
    # count — migration / prefix-store byte pressure surfaces in the
    # /fleet rows before page counts saturate
    kv_host_bytes: int = 0
    # per-dispatch perf accounting (ISSUE 11): the replica's recent
    # MFU/MBU against its hardware envelope, phase goodput, and which
    # roof binds — surfaced in /fleet rows and the fleet gauges
    mfu: float = 0.0
    mbu: float = 0.0
    decode_tps: float = 0.0
    prefill_tps: float = 0.0
    roof: str = ""
    # tick-anomaly analyzer (ISSUE 13): the replica's recent anomaly
    # rate + lifetime count — surfaced in /fleet rows; the fleet
    # watchdog reads the max rate as a page precursor
    anomaly_rate: float = 0.0
    anomalies_total: int = 0
    anomaly_last_kind: str = ""
    ts: float = dataclasses.field(default_factory=time.time)
    # MONOTONIC stamp of when this snapshot was taken (ISSUE 9): a
    # replica whose probes keep failing keeps its LAST snapshot, so
    # the router must know how old the numbers it scores are (an NTP
    # step must not fake freshness — hence not `ts`)
    mono_ts: float = dataclasses.field(default_factory=time.monotonic)

    def age_s(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        return max(now - self.mono_ts, 0.0)

    def displaceable_waiting(self) -> int:
        """Engine queue depth MINUS the batch lane (ISSUE 14): queued
        priority-0 bulk jobs are displaceable — an interactive
        request routed here jumps them (and preempts their running
        peers) — so every consumer of "how loaded is this replica
        with INTERACTIVE work" (router saturation/score, autoscaler
        window, batch soak governor) reads this ONE definition."""
        return max(self.waiting - self.waiting_batch, 0)

    def interactive_occupancy(self) -> float:
        """KV occupancy minus the batch-lane share (ISSUE 14): the
        autoscaler's scale-down signal — pages held by displaceable
        bulk work must not keep a fleet pinned at size after its
        interactive traffic leaves."""
        return max(self.kv_occupancy - self.kv_occupancy_batch, 0.0)

    @classmethod
    def from_stats(cls, stats: Dict[str, Any]) -> "ReplicaSnapshot":
        perf = stats.get("perf") or {}
        anom = stats.get("anomaly") or {}
        return cls(
            replica=stats.get("replica", ""),
            active=int(stats.get("active", 0)),
            waiting=int(stats.get("waiting", 0)),
            chips=max(int(stats.get("chips", 1)), 1),
            waiting_batch=int(stats.get("waiting_batch", 0)),
            active_batch=int(stats.get("active_batch", 0)),
            kv_occupancy_batch=float(
                stats.get("kv_occupancy_batch", 0.0)),
            kv_occupancy=float(stats.get("kv_occupancy", 0.0)),
            free_pages=int(stats.get("free_pages", 0)),
            cache_hit_rate=float(stats.get("cache_hit_rate", 0.0)),
            last_tick_age_s=stats.get("last_tick_age_s"),
            page_pressure=float(stats.get("page_pressure", 0.0)),
            parked=int(stats.get("parked_sessions", 0)),
            spillable=bool(stats.get("kv_offload", False)),
            kv_host_bytes=int(stats.get("kv_host_bytes_used", 0)),
            mfu=float(perf.get("mfu", 0.0)),
            mbu=float(perf.get("mbu", 0.0)),
            decode_tps=float(perf.get("decode_tokens_per_s", 0.0)),
            prefill_tps=float(perf.get("prefill_tokens_per_s", 0.0)),
            roof=str(perf.get("roof", "")),
            anomaly_rate=float(anom.get("rate", 0.0)),
            anomalies_total=int(anom.get("total", 0)),
            anomaly_last_kind=str(anom.get("last_kind") or ""))


@dataclasses.dataclass
class RouterConfig:
    # "affinity" is the real policy; "round_robin" exists for the
    # bench A/B (bench_llm --fleet) and as the degenerate baseline
    policy: str = "affinity"
    vnodes: int = 64
    prefix_depth: int = 256
    # spillover thresholds: the affinity target is "saturated" when
    # EITHER trips (pages are the binding constraint; a deep engine
    # queue means admission there would stall regardless of pages)
    spill_occupancy: float = 0.85
    spill_waiting: int = 4
    # score weights for the all-saturated fallback
    w_occupancy: float = 4.0
    w_waiting: float = 1.0
    w_inflight: float = 0.5
    # snapshot staleness (ISSUE 9): a snapshot older than this is
    # routing on fiction — the replica's probes have been failing for
    # multiple refresh cycles. The affinity walk treats it like a
    # saturated target (spill to the ring successor, whose numbers are
    # real) and the scored fallback penalizes it by w_stale.
    snapshot_stale_s: float = 10.0
    w_stale: float = 4.0


class FleetRouter:
    """Scores replicas by live engine state; sticky on prompt prefix.

    The caller owns the snapshot map (FleetManager refreshes it off
    each replica's fleet_stats) and the in-flight counts (updated at
    dispatch/completion — the only zero-lag load signal)."""

    def __init__(self, config: Optional[RouterConfig] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.config = config or RouterConfig()
        # injectable clock (ISSUE 14): snapshot-staleness judgments
        # compare against this time source — virtual in the simulator,
        # time.monotonic in a real fleet (matching mono_ts stamps)
        self._clock = clock if clock is not None else time.monotonic
        self.ring = HashRing(vnodes=self.config.vnodes)
        self._rr = itertools.count()
        # routing telemetry (served at GET /fleet)
        self.picks = 0
        self.affinity_hits = 0       # primary target taken
        self.spills = 0              # ring-walk past a saturated node
        self.scored_fallbacks = 0    # every node saturated

    # -- membership (FleetManager: activate/drain) ----------------------
    def set_replicas(self, replica_ids: Sequence[str]) -> None:
        want = set(replica_ids)
        for rid in list(self.ring.nodes()):
            if rid not in want:
                self.ring.remove(rid)
        for rid in want:
            self.ring.add(rid)

    # -- scoring --------------------------------------------------------
    def score(self, snap: ReplicaSnapshot, inflight: int) -> float:
        """Lower is better. Documented in BENCH_CORE.md ("Serving
        fleet anatomy"): occupancy dominates (pages are the binding
        constraint), engine queue depth next, then the router's own
        not-yet-visible in-flight count; a stale snapshot (probes
        failing — ISSUE 9) adds a flat deprioritization penalty."""
        c = self.config
        return (c.w_occupancy * snap.kv_occupancy
                + c.w_waiting * (snap.displaceable_waiting()
                                 + snap.active * 0.25)
                + c.w_inflight * inflight
                + (c.w_stale
                   if snap.age_s(self._clock()) > c.snapshot_stale_s
                   else 0.0))

    def _saturated(self, snap: ReplicaSnapshot, inflight: int) -> bool:
        # batch-lane depth is displaceable load (ISSUE 14): a replica
        # soaking bulk work must not repel its affinity traffic as if
        # it were saturated — neither its queued batch requests nor
        # the KV pages its batch slots hold (they spill on demand)
        c = self.config
        return (snap.interactive_occupancy() >= c.spill_occupancy
                or snap.displaceable_waiting() + inflight
                >= c.spill_waiting
                # stale numbers are no basis for an affinity hit:
                # walk on to a replica whose state is known
                or snap.age_s(self._clock()) > c.snapshot_stale_s)

    # -- the pick -------------------------------------------------------
    def pick(self, fingerprint: str,
             snapshots: Dict[str, ReplicaSnapshot],
             inflight: Dict[str, int]) -> Optional[str]:
        """Choose a replica for a request with this prefix
        fingerprint. None only when the ring is empty."""
        return self.pick_ex(fingerprint, snapshots, inflight)[0]

    def pick_ex(self, fingerprint: str,
                snapshots: Dict[str, ReplicaSnapshot],
                inflight: Dict[str, int]
                ) -> "tuple[Optional[str], str]":
        """pick() plus the decision OUTCOME ("affinity" | "spill" |
        "scored" | "round_robin" | "none") — the routing-decision
        trace span's payload (ISSUE 7), so a merged fleet trace shows
        WHY a request landed where it did, not just where."""
        nodes = self.ring.nodes()
        if not nodes:
            return None, "none"
        self.picks += 1
        if self.config.policy == "round_robin":
            # skip the ring walk entirely: preferred() hashes the key
            # and walks up to vnodes*replicas points for an ordering
            # round-robin would discard
            return nodes[next(self._rr) % len(nodes)], "round_robin"
        order = self.ring.preferred(fingerprint)

        def _snap(rid: str) -> ReplicaSnapshot:
            return snapshots.get(rid) or ReplicaSnapshot(replica=rid)

        for rank, rid in enumerate(order):
            if not self._saturated(_snap(rid), inflight.get(rid, 0)):
                if rank == 0:
                    self.affinity_hits += 1
                    return rid, "affinity"
                self.spills += 1
                return rid, "spill"
        # every replica saturated: degrade gracefully to pure load
        self.scored_fallbacks += 1
        return min(order, key=lambda rid: self.score(
            _snap(rid), inflight.get(rid, 0))), "scored"

    def stats(self) -> Dict[str, Any]:
        return {
            "policy": self.config.policy,
            "replicas": self.ring.nodes(),
            "picks": self.picks,
            "affinity_hits": self.affinity_hits,
            "spills": self.spills,
            "scored_fallbacks": self.scored_fallbacks,
        }


__all__ = ["FleetRouter", "RouterConfig", "ReplicaSnapshot", "HashRing",
           "prefix_fingerprint"]
