"""FleetManager: N engine replicas behind one router + front door.

The composition layer of ISSUE 6. A fleet is a set of `LLMServerImpl`
replicas reached through a small client interface (so the SAME manager
runs over in-process servers in tier-1 tests and benches, over
local-testing-mode deployment handles, and over real replica actors),
plus the three policy objects:

- `FleetRouter` (router.py): prefix-affine, occupancy-aware pick;
- `AdmissionController` (admission.py): bounded queue + 429 shed;
- `FleetAutoscaler` (autoscaler.py): TTFT/queue-wait-driven target.

Replica lifecycle: ACTIVE (in the ring) -> DRAINING (out of the ring,
finishing in-flight work) -> STANDBY (idle, instantly re-activatable).
The fleet provisions `max_replicas` up front and moves them between
these states — scale-down never drops a stream: the victim leaves the
ring first, the router's in-flight count reaches zero only when every
stream it was serving has completed, and only then does the engine's
own idle check (`has_work`) retire it to standby.

Single-event-loop discipline: every mutation of fleet state happens on
the loop the ingress serves from (the manager is created there); the
blocking engine work stays inside each replica's own executor pump.
"""

from __future__ import annotations

import asyncio
import collections
import time
import uuid
from typing import Any, AsyncIterator, Deque, Dict, List, Optional, \
    Sequence

from ...llm._internal.telemetry import FlightRecorder
from ...util import tracing
from .admission import (AdmissionConfig, AdmissionController,
                        AdmissionRejected)
from .autoscaler import AutoscaleConfig, FleetAutoscaler, FleetMetrics
from .router import (FleetRouter, ReplicaSnapshot, RouterConfig,
                     prefix_fingerprint)
from .tracemerge import IngressTraceBuffer, request_events
from .watchdog import SLOBurnWatchdog, WatchdogConfig

# monotone SLO-total keys the watchdog accumulates fleet-wide
_WATCH_KEYS = ("ttft_n", "ttft_bad", "queue_n", "queue_bad",
               "e2e_n", "e2e_bad")

ACTIVE = "ACTIVE"
DRAINING = "DRAINING"
STANDBY = "STANDBY"


class LocalReplicaClient:
    """Direct in-process LLMServerImpl (tier-1 tests, bench --fleet)."""

    shares_registry = True

    def __init__(self, replica_id: str, server: Any):
        self.replica_id = replica_id
        self.server = server

    async def call(self, method: str, *args) -> Any:
        return await getattr(self.server, method)(*args)

    def stream(self, method: str, body: Dict[str, Any]):
        return getattr(self.server, method)(body)


class HandleReplicaClient:
    """A serve DeploymentHandle to an LLMServer deployment. In
    local_testing_mode every handle resolves to an in-process replica
    sharing this process's metric registry; across real replica
    actors each process has its own registry (shares_registry drives
    the /metrics merge strategy — see metrics_text())."""

    def __init__(self, replica_id: str, handle: Any,
                 shares_registry: bool = False):
        self.replica_id = replica_id
        self.handle = handle
        self.shares_registry = shares_registry

    async def call(self, method: str, *args) -> Any:
        return await getattr(self.handle, method).remote(*args)

    def stream(self, method: str, body: Dict[str, Any]):
        return getattr(self.handle, method).options(
            stream=True).remote(body)


class _ReplicaState:
    def __init__(self, client: Any, status: str):
        self.client = client
        self.status = status
        self.inflight = 0            # router-side, zero-lag
        self.requests_total = 0
        self.snapshot: Optional[ReplicaSnapshot] = None
        self.slo_totals: Dict[str, float] = {}
        self.drain_task: Optional[asyncio.Task] = None


class FleetManager:
    def __init__(self, clients: Sequence[Any],
                 router: Optional[RouterConfig] = None,
                 admission: Optional[AdmissionConfig] = None,
                 autoscale: Optional[AutoscaleConfig] = None,
                 refresh_period_s: float = 0.5,
                 autoscale_period_s: float = 2.0,
                 watchdog: Optional[WatchdogConfig] = None,
                 enable_tracing: bool = True):
        if not clients:
            raise ValueError("a fleet needs at least one replica")
        auto = autoscale or AutoscaleConfig(
            min_replicas=len(clients), max_replicas=len(clients))
        if auto.max_replicas > len(clients):
            raise ValueError(
                f"max_replicas={auto.max_replicas} but only "
                f"{len(clients)} replicas are provisioned")
        if not 1 <= auto.min_replicas <= auto.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas={auto.min_replicas} "
                f"<= max_replicas={auto.max_replicas}")
        self.router = FleetRouter(router)
        self.admission = AdmissionController(admission)
        self.autoscaler = FleetAutoscaler(auto)
        self.refresh_period_s = refresh_period_s
        self.autoscale_period_s = autoscale_period_s
        self.replicas: Dict[str, _ReplicaState] = {}
        for i, c in enumerate(clients):
            status = ACTIVE if i < auto.min_replicas else STANDBY
            self.replicas[c.replica_id] = _ReplicaState(c, status)
        self.router.set_replicas(self._ids(ACTIVE))
        self._prev_slo: Dict[str, Dict[str, float]] = {}
        self._prev_shed = 0
        self._scale_events: Deque[Dict[str, Any]] = \
            collections.deque(maxlen=256)
        self._loop_task: Optional[asyncio.Task] = None
        # -- ISSUE 7 observability layer --------------------------------
        # fleet-level flight recorder: slo_alert/slo_clear, brownout
        # transitions, postmortem dump triggers (GET /fleet/debug/events
        # merges it with every replica's ring)
        self.recorder = FlightRecorder(capacity=512)
        self.watchdog = SLOBurnWatchdog(watchdog or WatchdogConfig(),
                                        recorder=self.recorder)
        # distributed tracing: every request gets a trace context at
        # ingress; the ingress's own spans land here and merge with
        # the replicas' lifecycle traces at GET /fleet/debug/trace
        self.enable_tracing = enable_tracing
        self.trace = IngressTraceBuffer()
        # watchdog accumulation state: per-replica clamped deltas into
        # fleet-monotone totals (membership changes / engine restarts
        # must not produce negative or replayed windows)
        self._watch_prev: Dict[str, Dict[str, float]] = {}
        self._watch_accum: Dict[str, float] = \
            {k: 0.0 for k in _WATCH_KEYS}
        self._page_dump_task: Optional[asyncio.Task] = None

    # -- membership helpers --------------------------------------------
    def _ids(self, *statuses: str) -> List[str]:
        return [rid for rid, st in self.replicas.items()
                if st.status in statuses]

    def _inflight_map(self) -> Dict[str, int]:
        return {rid: st.inflight for rid, st in self.replicas.items()}

    def _snapshots(self) -> Dict[str, ReplicaSnapshot]:
        return {rid: st.snapshot for rid, st in self.replicas.items()
                if st.snapshot is not None}

    # -- request path ---------------------------------------------------
    def _route(self, body: Dict[str, Any]
               ) -> "tuple[_ReplicaState, str]":
        fp = prefix_fingerprint(body, self.router.config.prefix_depth)
        rid, outcome = self.router.pick_ex(fp, self._snapshots(),
                                           self._inflight_map())
        if rid is None:
            raise AdmissionRejected("no_active_replicas",
                                    self.admission.retry_after())
        return self.replicas[rid], outcome

    @staticmethod
    def tenant_of(body: Dict[str, Any]) -> str:
        # OpenAI bodies carry the end-user id in "user"; fall back to
        # a header-injected hint if the ingress put one in the body
        return str(body.get("user") or body.get("tenant") or "default")

    # -- distributed tracing (ISSUE 7) ----------------------------------
    def _trace_begin(self, method: str, body: Dict[str, Any]):
        """Mint the request's trace context at fleet ingress: one
        request id and one trace id that follow it across admission,
        routing, and the replica's engine lifecycle (the context rides
        the body; LLMServerImpl pops it onto the engine Request).
        Returns (body', rec) — body' is a COPY carrying the plumbing
        keys, rec the in-progress ingress span record."""
        if not self.enable_tracing:
            # the plumbing keys are internal even when tracing is off:
            # never forward client-supplied values to the replica
            if "_request_id" in body or "_trace" in body:
                body = {k: v for k, v in body.items()
                        if k not in ("_request_id", "_trace")}
            return body, None
        body = dict(body)
        # ALWAYS mint — `_request_id` doubles as the engine request id
        # downstream, so honoring a client-supplied value would let a
        # replayed id collide with (and abort/starve) another tenant's
        # in-flight request
        rid = uuid.uuid4().hex[:16]
        trace = {"trace_id": tracing.new_span_id(),
                 "span_id": tracing.new_span_id(),
                 "flow_id": tracing.new_span_id()}
        body["_request_id"] = rid
        body["_trace"] = trace
        return body, {
            "rid": rid, "trace": trace, "method": method,
            "tenant": self.tenant_of(body), "t0": time.monotonic(),
            "t_admit": None, "t_route": None, "replica": None,
            "outcome": None, "status": "ok", "done": False}

    def _trace_end(self, rec: Optional[Dict[str, Any]],
                   status: Optional[str] = None) -> None:
        """Close the ingress span set and publish it to the buffer
        (idempotent: the happy path and the error paths both reach
        here exactly once through the dispatch finally)."""
        if rec is None or rec["done"]:
            return
        rec["done"] = True
        if status is not None:
            rec["status"] = status
        self.trace.add(*request_events(
            self.trace.next_tid(), rec["rid"], rec["trace"],
            rec["t0"], rec["t_admit"], rec["t_route"],
            time.monotonic(), rec["replica"], rec["outcome"],
            rec["method"], rec["tenant"], rec["status"]))

    async def dispatch(self, method: str, body: Dict[str, Any]) -> Any:
        """Unary request through admission + routing (trace-minted)."""
        body, rec = self._trace_begin(method, body)
        try:
            await self.admission.acquire(self.tenant_of(body))
        except AdmissionRejected as e:
            self._trace_end(rec, f"rejected:{e.reason}")
            raise
        if rec is not None:
            rec["t_admit"] = time.monotonic()
        try:
            st, outcome = self._route(body)
            if rec is not None:
                rec["t_route"] = time.monotonic()
                rec["replica"] = st.client.replica_id
                rec["outcome"] = outcome
            st.inflight += 1
            st.requests_total += 1
            try:
                return await st.client.call(method, body)
            finally:
                st.inflight -= 1
        except AdmissionRejected as e:
            if rec is not None:
                rec["status"] = f"rejected:{e.reason}"
            raise
        except BaseException:
            if rec is not None:
                rec["status"] = "error"
            raise
        finally:
            self.admission.release()
            self._trace_end(rec)

    async def dispatch_stream(self, method: str, body: Dict[str, Any]
                              ) -> AsyncIterator[Any]:
        """Streaming request: admission + routing hold for the WHOLE
        stream (a live stream occupies a decode slot, so it must keep
        weighing in both the router's in-flight counts and the
        admission concurrency bound until it completes)."""
        body, rec = self._trace_begin(method, body)
        try:
            await self.admission.acquire(self.tenant_of(body))
        except AdmissionRejected as e:
            self._trace_end(rec, f"rejected:{e.reason}")
            raise
        if rec is not None:
            rec["t_admit"] = time.monotonic()
        try:
            st, outcome = self._route(body)
            if rec is not None:
                rec["t_route"] = time.monotonic()
                rec["replica"] = st.client.replica_id
                rec["outcome"] = outcome
            st.inflight += 1
            st.requests_total += 1
            try:
                async for chunk in st.client.stream(method, body):
                    yield chunk
            finally:
                st.inflight -= 1
        except AdmissionRejected as e:
            if rec is not None:
                rec["status"] = f"rejected:{e.reason}"
            raise
        except GeneratorExit:
            if rec is not None:
                rec["status"] = "abandoned"
            raise
        except BaseException:
            if rec is not None:
                rec["status"] = "error"
            raise
        finally:
            self.admission.release()
            self._trace_end(rec)

    # -- stats refresh --------------------------------------------------
    async def refresh(self) -> None:
        """Pull fleet_stats from every non-standby replica."""
        ids = self._ids(ACTIVE, DRAINING)

        async def one(rid: str):
            st = self.replicas[rid]
            try:
                stats = await asyncio.wait_for(
                    st.client.call("fleet_stats"), timeout=5.0)
            except Exception:
                return                       # keep the stale snapshot
            snap = ReplicaSnapshot.from_stats(stats)
            snap.replica = rid
            st.snapshot = snap
            st.slo_totals = dict(stats.get("slo_totals") or {})

        await asyncio.gather(*(one(rid) for rid in ids))

    # -- autoscaling ----------------------------------------------------
    def _window_metrics(self) -> FleetMetrics:
        """Fleet aggregates over the window since the last call:
        deltas of the cumulative TTFT/queue-wait sums each replica's
        telemetry summary exports (PR 5), plus live queue depths and
        the admission shed delta. Deltas are tracked PER REPLICA ID,
        not on a fleet sum over the changing ACTIVE/DRAINING set — a
        replica parking to STANDBY must not show up as a negative
        window, and a reactivated one must contribute only its growth
        since last seen, not its lifetime totals."""
        keys = ("ttft_s", "ttft_n", "queue_s", "queue_n")
        d = {k: 0.0 for k in keys}
        waiting = 0
        occ: List[float] = []
        for rid, st in self.replicas.items():
            if st.slo_totals:
                prev = self._prev_slo.get(rid, {})
                cur = {k: float(st.slo_totals.get(k, 0.0))
                       for k in keys}
                for k in keys:
                    # clamped: an engine restart resets its counters
                    d[k] += max(0.0, cur[k] - prev.get(k, 0.0))
                self._prev_slo[rid] = cur
            if st.snapshot is not None and st.status == ACTIVE:
                waiting += st.snapshot.waiting
                occ.append(st.snapshot.kv_occupancy)
        shed = (self.admission.shed_total
                + self.admission.rejected["queue_full"]
                + self.admission.rejected["brownout"])
        shed_delta = shed - self._prev_shed
        self._prev_shed = shed
        return FleetMetrics(
            ttft_ms=(d["ttft_s"] / d["ttft_n"] * 1e3
                     if d["ttft_n"] > 0 else 0.0),
            queue_wait_ms=(d["queue_s"] / d["queue_n"] * 1e3
                           if d["queue_n"] > 0 else 0.0),
            waiting=waiting,
            occupancy=(sum(occ) / len(occ) if occ else 0.0),
            shed_delta=shed_delta,
            slo_page=self.watchdog.paging,
            slo_burn=self.watchdog.max_burn)

    # -- SLO burn-rate watchdog (ISSUE 7) -------------------------------
    def _watchdog_totals(self) -> Dict[str, float]:
        """Fleet-summed monotone SLO totals, accumulated per replica
        id with clamped deltas (same reasoning as _window_metrics:
        replica restarts and membership changes must not produce
        negative or replayed burn windows)."""
        for rid, st in self.replicas.items():
            if not st.slo_totals:
                continue
            prev = self._watch_prev.get(rid, {})
            cur = {k: float(st.slo_totals.get(k, 0.0))
                   for k in _WATCH_KEYS}
            for k in _WATCH_KEYS:
                self._watch_accum[k] += max(
                    0.0, cur[k] - prev.get(k, 0.0))
            self._watch_prev[rid] = cur
        return dict(self._watch_accum)

    def watchdog_tick(self, now: Optional[float] = None) -> None:
        """One watchdog evaluation over the freshly-refreshed replica
        totals, plus the reactions: brownout the front door while
        paging (shed before the SLO is blown) and black-box every
        replica on the page transition (the postmortem wants the
        fleet's state AT the breach, not after the restart)."""
        if not self.watchdog.config.enabled:
            return
        was_paging = self.watchdog.paging
        self.watchdog.observe(self._watchdog_totals(), now)
        paging = self.watchdog.paging
        if self.admission.set_brownout(paging):
            self.recorder.record(
                "brownout_on" if paging else "brownout_off",
                burn=round(self.watchdog.max_burn, 3))
        if paging and not was_paging:
            try:
                self._page_dump_task = \
                    asyncio.get_running_loop().create_task(
                        self.debug_dump_all("slo_page"))
            except RuntimeError:
                pass     # no running loop (sync test driver)

    async def debug_dump_all(self, cause: str) -> Dict[str, Any]:
        """Ask every non-standby replica to snapshot a postmortem
        black-box bundle (watchdog page / POST /debug/dump)."""
        ids = self._ids(ACTIVE, DRAINING)

        async def one(rid: str):
            try:
                return rid, await asyncio.wait_for(
                    self.replicas[rid].client.call(
                        "debug_dump", {"cause": cause}),
                    timeout=10.0)
            except Exception as e:
                return rid, {"error": repr(e)}

        out = dict(await asyncio.gather(*(one(rid) for rid in ids)))
        self.recorder.record("postmortem_dump", cause=cause,
                             replicas=sorted(out))
        return out

    async def autoscale_tick(self, now: Optional[float] = None) -> int:
        """One control-loop iteration: refresh → watchdog → decide →
        apply. Returns the applied target (also at GET /fleet)."""
        await self.refresh()
        self.watchdog_tick(now)
        active = len(self._ids(ACTIVE))
        target = self.autoscaler.decide(self._window_metrics(),
                                        active, now)
        if target != active:
            self._apply_target(target)
        return target

    def _apply_target(self, target: int) -> None:
        active = self._ids(ACTIVE)
        if target > len(active):
            for rid in self._ids(STANDBY)[:target - len(active)]:
                self.replicas[rid].status = ACTIVE
                self._scale_events.append(
                    {"ts": time.time(), "event": "activate",
                     "replica": rid})
        elif target < len(active):
            # drain the emptiest replicas first: least in-flight work,
            # then least KV occupancy (cheapest caches to lose)
            def cost(rid: str):
                st = self.replicas[rid]
                occ = (st.snapshot.kv_occupancy
                       if st.snapshot is not None else 0.0)
                return (st.inflight, occ)

            for rid in sorted(active, key=cost)[:len(active) - target]:
                self._begin_drain(rid)
        self.router.set_replicas(self._ids(ACTIVE))

    def _begin_drain(self, rid: str) -> None:
        st = self.replicas[rid]
        st.status = DRAINING
        self._scale_events.append(
            {"ts": time.time(), "event": "drain_begin", "replica": rid})
        st.drain_task = asyncio.get_running_loop().create_task(
            self._drain_to_standby(rid))

    async def _drain_to_standby(self, rid: str,
                                timeout_s: float = 120.0) -> None:
        """Out of the ring already; wait for the router-side in-flight
        count to hit zero (every stream completed), then for the
        engine itself to run dry (the replica's drain() polls
        has_work(), which counts in-flight pipelined ticks and pending
        folds), then park."""
        st = self.replicas[rid]
        attempt = 0
        while True:
            deadline = time.monotonic() + timeout_s
            while st.inflight > 0 and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            drained = True
            try:
                rep = await st.client.call("drain", timeout_s)
                drained = bool((rep or {}).get("drained", True))
            except Exception:
                pass    # best-effort: the replica may not expose drain
            if st.inflight == 0 and drained:
                break
            # wedged: STAY DRAINING — out of the ring and ineligible
            # for reactivation (_apply_target only activates STANDBY)
            # — and retry; parking dirty would hand a replica known
            # unable to finish work back to the router on scale-up
            attempt += 1
            self._scale_events.append(
                {"ts": time.time(), "event": "drain_retry",
                 "replica": rid, "attempt": attempt})
            await asyncio.sleep(min(30.0, 2.0 * attempt))
        st.status = STANDBY
        self._scale_events.append(
            {"ts": time.time(), "event": "drain_done", "replica": rid,
             "clean": attempt == 0})

    # -- background control loop ---------------------------------------
    def start(self) -> None:
        """Start the refresh + autoscale loop on the current event
        loop (idempotent). Separate cadences: stats refresh keeps the
        router's view fresh; autoscale decisions run slower."""
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.get_running_loop().create_task(
                self._control_loop())

    async def stop(self) -> None:
        if self._loop_task is not None:
            self._loop_task.cancel()
            try:
                await self._loop_task
            except (asyncio.CancelledError, Exception):
                pass
            self._loop_task = None

    async def _control_loop(self) -> None:
        last_autoscale = 0.0
        while True:
            try:
                await self.refresh()
                self.watchdog_tick()
                now = time.monotonic()
                if now - last_autoscale >= self.autoscale_period_s:
                    last_autoscale = now
                    active = len(self._ids(ACTIVE))
                    target = self.autoscaler.decide(
                        self._window_metrics(), active)
                    if target != active:
                        self._apply_target(target)
            except asyncio.CancelledError:
                raise
            except Exception:
                import logging
                logging.getLogger(__name__).exception(
                    "fleet control loop iteration failed")
            await asyncio.sleep(self.refresh_period_s)

    # -- observability --------------------------------------------------
    async def metrics_text(self) -> str:
        """ONE valid Prometheus exposition for the whole fleet.

        Two registry topologies (the ISSUE 6 satellite):
        - shared registry (in-process replicas / local testing): every
          scrape renders the same process registry; each replica's
          engine tags its own series with its replica id, so the fleet
          scrapes every replica (each refreshes its own gauges) and
          keeps the LAST rendering — by then every replica's gauges
          are fresh in the shared registry.
        - separate registries (real replica actors): each exposition
          is scraped independently and relabeled with replica=<id> so
          identical series from different replicas cannot collide or
          silently sum in the merged document.
        """
        from ...util.metrics import (export_prometheus,
                                     merge_expositions,
                                     relabel_exposition)

        ids = self._ids(ACTIVE, DRAINING)

        async def one(rid: str):
            st = self.replicas[rid]
            try:
                return (rid, st.client, await asyncio.wait_for(
                    st.client.call("metrics_text"), timeout=5.0))
            except Exception:
                return None     # a wedged replica can't black out
                                # the whole fleet's scrape

        texts = [t for t in await asyncio.gather(
            *(one(rid) for rid in ids)) if t is not None]
        if not texts:
            return export_prometheus()
        if all(c.shares_registry for _, c, _ in texts):
            return texts[-1][2]
        # separate registries: the ingress's own series (watchdog
        # burn-rate gauges, alert counters) live in THIS process's
        # registry — merge them in unrelabeled (they are fleet-scoped,
        # not per-replica)
        return merge_expositions(
            [relabel_exposition(t, {"replica": rid})
             for rid, _, t in texts] + [export_prometheus()])

    async def status(self) -> Dict[str, Any]:
        """The GET /fleet document: routing inputs per replica,
        router/admission counters, last autoscale decision."""
        reps: Dict[str, Any] = {}
        for rid, st in self.replicas.items():
            snap = st.snapshot
            reps[rid] = {
                "status": st.status,
                "inflight": st.inflight,
                "requests_total": st.requests_total,
                **({} if snap is None else {
                    "active": snap.active,
                    "waiting": snap.waiting,
                    "kv_occupancy": round(snap.kv_occupancy, 4),
                    "free_pages": snap.free_pages,
                    "prefix_cache_hit_rate": round(
                        snap.cache_hit_rate, 4),
                    "last_tick_age_s": snap.last_tick_age_s,
                }),
            }
        return {
            "replicas": reps,
            "router": self.router.stats(),
            "admission": self.admission.stats(),
            "watchdog": {
                "enabled": self.watchdog.config.enabled,
                "paging": self.watchdog.paging,
                "state": dict(self.watchdog.state),
                "burn": self.watchdog.last,
                "alerts_total": self.watchdog.alerts_total,
                "objective": self.watchdog.config.objective,
            },
            "tracing": {
                "enabled": self.enable_tracing,
                "ingress_buffer": self.trace.stats(),
            },
            "recorder": self.recorder.stats(),
            "autoscale": {
                "min_replicas": self.autoscaler.config.min_replicas,
                "max_replicas": self.autoscaler.config.max_replicas,
                "active": len(self._ids(ACTIVE)),
                "draining": len(self._ids(DRAINING)),
                "standby": len(self._ids(STANDBY)),
                "last_decision": self.autoscaler.last_decision,
                "events": list(self._scale_events)[-32:],
            },
        }


__all__ = ["FleetManager", "LocalReplicaClient", "HandleReplicaClient",
           "ACTIVE", "DRAINING", "STANDBY"]
