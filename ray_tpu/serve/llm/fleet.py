"""FleetManager: N engine replicas behind one router + front door.

The composition layer of ISSUE 6. A fleet is a set of `LLMServerImpl`
replicas reached through a small client interface (so the SAME manager
runs over in-process servers in tier-1 tests and benches, over
local-testing-mode deployment handles, and over real replica actors),
plus the three policy objects:

- `FleetRouter` (router.py): prefix-affine, occupancy-aware pick;
- `AdmissionController` (admission.py): bounded queue + 429 shed;
- `FleetAutoscaler` (autoscaler.py): TTFT/queue-wait-driven target.

Replica lifecycle: ACTIVE (in the ring) -> DRAINING (out of the ring,
finishing in-flight work) -> STANDBY (idle, instantly re-activatable).
The fleet provisions `max_replicas` up front and moves them between
these states — scale-down never drops a stream: the victim leaves the
ring first, the router's in-flight count reaches zero only when every
stream it was serving has completed, and only then does the engine's
own idle check (`has_work`) retire it to standby.

Single-event-loop discipline: every mutation of fleet state happens on
the loop the ingress serves from (the manager is created there); the
blocking engine work stays inside each replica's own executor pump.
"""

from __future__ import annotations

import asyncio
import collections
import time
import uuid
from typing import Any, AsyncIterator, Deque, Dict, List, Optional, \
    Sequence

from ...llm._internal.telemetry import FlightRecorder
from ...util import tracing
from . import failover, kv_transport
from .admission import (AdmissionConfig, AdmissionController,
                        AdmissionRejected)
from .autoscaler import AutoscaleConfig, FleetAutoscaler, FleetMetrics
from .batch import (BATCH_PRIORITY, INTERACTIVE_PRIORITY, BatchLane,
                    BatchLaneConfig)
from .failover import CircuitBreaker, HealthConfig
from .kv_transport import (FleetPrefixStore, TransportConfig,
                           TransportError)
from .router import (FleetRouter, ReplicaSnapshot, RouterConfig,
                     prefix_fingerprint)
from .tracemerge import IngressTraceBuffer, request_events
from .trafficlog import TrafficRecorder, sampling_brief
from .watchdog import SLOBurnWatchdog, WatchdogConfig

# monotone SLO-total keys the watchdog accumulates fleet-wide
_WATCH_KEYS = ("ttft_n", "ttft_bad", "queue_n", "queue_bad",
               "e2e_n", "e2e_bad")

ACTIVE = "ACTIVE"
DRAINING = "DRAINING"
STANDBY = "STANDBY"
# ISSUE 9: evicted by the health state machine — out of the router
# ring, ineligible for autoscale activation; only the breaker's
# half-open probes re-admit it
UNHEALTHY = "UNHEALTHY"

# plumbing keys the fleet mints itself: client-supplied values are
# stripped at ingress (a forged `_continue_tokens` would inject raw
# token ids, a forged `_deadline_epoch` would bypass deadline_s).
# ONE canonical list, owned by the server module that pops them.
from ...llm._internal.server import \
    INTERNAL_BODY_KEYS as _INTERNAL_BODY_KEYS  # noqa: E402


class LocalReplicaClient:
    """Direct in-process LLMServerImpl (tier-1 tests, bench --fleet)."""

    shares_registry = True

    def __init__(self, replica_id: str, server: Any):
        self.replica_id = replica_id
        self.server = server

    async def call(self, method: str, *args) -> Any:
        return await getattr(self.server, method)(*args)

    def stream(self, method: str, body: Dict[str, Any]):
        return getattr(self.server, method)(body)


class HandleReplicaClient:
    """A serve DeploymentHandle to an LLMServer deployment. In
    local_testing_mode every handle resolves to an in-process replica
    sharing this process's metric registry; across real replica
    actors each process has its own registry (shares_registry drives
    the /metrics merge strategy — see metrics_text())."""

    def __init__(self, replica_id: str, handle: Any,
                 shares_registry: bool = False):
        self.replica_id = replica_id
        self.handle = handle
        self.shares_registry = shares_registry

    async def call(self, method: str, *args) -> Any:
        return await getattr(self.handle, method).remote(*args)

    def stream(self, method: str, body: Dict[str, Any]):
        return getattr(self.handle, method).options(
            stream=True).remote(body)


class _ReplicaState:
    def __init__(self, client: Any, status: str,
                 health: Optional[HealthConfig] = None,
                 role: str = kv_transport.ROLE_MIXED):
        self.client = client
        self.status = status
        # disaggregated prefill/decode (ISSUE 12): `prefill` replicas
        # never join the router ring — they only take long-prompt
        # prefill handoffs; `decode`/`mixed` take ring traffic
        self.role = role
        self.inflight = 0            # router-side, zero-lag
        self.requests_total = 0
        self.snapshot: Optional[ReplicaSnapshot] = None
        self.slo_totals: Dict[str, float] = {}
        self.drain_task: Optional[asyncio.Task] = None
        # ISSUE 9 health state machine: closed -> open (evicted) ->
        # half-open (probation probes) -> closed (re-admitted)
        self.breaker = CircuitBreaker(health)


class FleetManager:
    def __init__(self, clients: Sequence[Any],
                 router: Optional[RouterConfig] = None,
                 admission: Optional[AdmissionConfig] = None,
                 autoscale: Optional[AutoscaleConfig] = None,
                 refresh_period_s: float = 0.5,
                 autoscale_period_s: float = 2.0,
                 watchdog: Optional[WatchdogConfig] = None,
                 enable_tracing: bool = True,
                 health: Optional[HealthConfig] = None,
                 model_id: str = "default",
                 probe_timeout_s: float = 5.0,
                 dispatch_timeout_s: float = 10.0,
                 drain_timeout_s: float = 120.0,
                 roles: Optional[Sequence[str]] = None,
                 transport: Optional[TransportConfig] = None,
                 batch_lane: Optional[BatchLaneConfig] = None,
                 enable_traffic_log: bool = True,
                 traffic_capacity: int = 4096,
                 traffic_spool_dir: Optional[str] = None):
        if not clients:
            raise ValueError("a fleet needs at least one replica")
        # per-replica roles (ISSUE 12 disaggregation): aligned with
        # `clients`; default everyone `mixed` (= pre-transport fleet)
        roles = (list(roles) if roles is not None
                 else [kv_transport.ROLE_MIXED] * len(clients))
        if len(roles) != len(clients):
            raise ValueError(
                f"roles ({len(roles)}) must align with clients "
                f"({len(clients)})")
        bad = [r for r in roles if r not in kv_transport.REPLICA_ROLES]
        if bad:
            raise ValueError(
                f"unknown replica roles {bad}; valid: "
                f"{kv_transport.REPLICA_ROLES}")
        if all(r == kv_transport.ROLE_PREFILL for r in roles):
            raise ValueError(
                "a fleet needs at least one decode-capable replica "
                "(role 'decode' or 'mixed')")
        auto = autoscale or AutoscaleConfig(
            min_replicas=len(clients), max_replicas=len(clients))
        if auto.max_replicas > len(clients):
            raise ValueError(
                f"max_replicas={auto.max_replicas} but only "
                f"{len(clients)} replicas are provisioned")
        if not 1 <= auto.min_replicas <= auto.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas={auto.min_replicas} "
                f"<= max_replicas={auto.max_replicas}")
        self.router = FleetRouter(router)
        # tenant-labeled front-door series (ISSUE 13 satellite) tag
        # with this fleet's model id
        self.admission = AdmissionController(
            admission, metrics_model_id=model_id)
        self.autoscaler = FleetAutoscaler(auto)
        self.refresh_period_s = refresh_period_s
        self.autoscale_period_s = autoscale_period_s
        # named operation timeouts (ISSUE 9 satellite — were scattered
        # 5.0/10.0 literals): probe = stats/metrics/bundle fan-outs,
        # dispatch = control-plane unary calls (postmortem dumps),
        # drain = scale-down engine drain
        self.probe_timeout_s = probe_timeout_s
        self.dispatch_timeout_s = dispatch_timeout_s
        self.drain_timeout_s = drain_timeout_s
        self.model_id = model_id
        # failure-handling plane (ISSUE 9)
        self.health = health or HealthConfig()
        self.metrics = failover.fleet_metrics()
        self.replicas: Dict[str, _ReplicaState] = {}
        for i, c in enumerate(clients):
            status = ACTIVE if i < auto.min_replicas else STANDBY
            self.replicas[c.replica_id] = _ReplicaState(
                c, status, self.health, role=roles[i])
            self.metrics["breaker"].set(
                0, {"model": self.model_id, "replica": c.replica_id})
        # -- fleet KV transport (ISSUE 12) -----------------------------
        self.transport = transport
        self.kvt_metrics = kv_transport.transport_metrics()
        self.prefix_store: Optional[FleetPrefixStore] = None
        if transport is not None and transport.enable_prefix_store:
            self.prefix_store = FleetPrefixStore(
                transport.prefix_store_bytes)
        # live relay-driven streams by minted request id -> which
        # replica currently serves them (the migration orchestrator's
        # inventory), and exported-but-not-yet-resumed session
        # payloads a drain shipped off a replica
        self._live_streams: Dict[str, Dict[str, Any]] = {}
        self._migrations: Dict[str, str] = {}
        # fingerprints already offered to the prefix store (success
        # or not): publishing is once-per-fingerprint, never a
        # per-request tax on the response path
        self._prefix_attempted: set = set()
        # preemptible batch-inference lane (ISSUE 14): POST /v1/batch
        # jobs dispatched at BATCH_PRIORITY outside the front-door
        # queue, soaking idle capacity; None = lane off (and then no
        # interactive priority stamping either — the pre-ISSUE-14
        # fleet byte-for-byte)
        self.batch: Optional[BatchLane] = (
            BatchLane(self, batch_lane)
            if batch_lane is not None else None)
        self._sync_ring()
        if not self._ring_ids():
            # the INITIAL ACTIVE set (the first min_replicas clients)
            # must contain a decode-capable replica — an all-prefill
            # head would start the fleet with an empty router ring
            # and reject every request until an autoscale activation
            # happened to fix it
            raise ValueError(
                "the first min_replicas replicas are all "
                "prefill-role: order at least one decode/mixed "
                "replica inside min_replicas")
        self._prev_slo: Dict[str, Dict[str, float]] = {}
        self._prev_shed = 0
        self._scale_events: Deque[Dict[str, Any]] = \
            collections.deque(maxlen=256)
        self._loop_task: Optional[asyncio.Task] = None
        # -- ISSUE 7 observability layer --------------------------------
        # fleet-level flight recorder: slo_alert/slo_clear, brownout
        # transitions, postmortem dump triggers (GET /fleet/debug/events
        # merges it with every replica's ring)
        self.recorder = FlightRecorder(capacity=512)
        self.watchdog = SLOBurnWatchdog(watchdog or WatchdogConfig(),
                                        recorder=self.recorder)
        # distributed tracing: every request gets a trace context at
        # ingress; the ingress's own spans land here and merge with
        # the replicas' lifecycle traces at GET /fleet/debug/trace
        self.enable_tracing = enable_tracing
        self.trace = IngressTraceBuffer()
        # -- ISSUE 20 traffic flight-data recorder ----------------------
        # always-on bounded request log at the ingress: one
        # privacy-scrubbed record per request (never prompt text),
        # armed captures snapshot into the replayable JSONL format
        # (GET /fleet/debug/traffic; sim.traffic.RecordedTrace and
        # tools/tracereplay consume the captures)
        self.enable_traffic_log = enable_traffic_log
        self.traffic = TrafficRecorder(
            capacity=traffic_capacity, model_id=model_id,
            spool_dir=traffic_spool_dir)
        # watchdog accumulation state: per-replica clamped deltas into
        # fleet-monotone totals (membership changes / engine restarts
        # must not produce negative or replayed windows)
        self._watch_prev: Dict[str, Dict[str, float]] = {}
        self._watch_accum: Dict[str, float] = \
            {k: 0.0 for k in _WATCH_KEYS}
        self._page_dump_task: Optional[asyncio.Task] = None
        self._dump_tasks: set = set()   # keep eviction dumps alive
        # per-dispatch perf accounting (ISSUE 11): fleet-level
        # utilization gauges — the decode-goodput-weighted mean of the
        # ACTIVE replicas' recent MFU/MBU (idle replicas with no
        # traffic don't drag the fleet number to zero), refreshed by
        # the same probe loop that stamps the snapshots
        from ...util import metrics as metrics_api
        self._fleet_mfu_gauge = metrics_api.Gauge(
            "ray_tpu_llm_fleet_mfu",
            "goodput-weighted mean replica MFU over active replicas",
            ("model",))
        self._fleet_mbu_gauge = metrics_api.Gauge(
            "ray_tpu_llm_fleet_mbu",
            "goodput-weighted mean replica MBU over active replicas",
            ("model",))

    # -- membership helpers --------------------------------------------
    def _ids(self, *statuses: str) -> List[str]:
        return [rid for rid, st in self.replicas.items()
                if st.status in statuses]

    def _ring_ids(self) -> List[str]:
        """ACTIVE decode-capable replicas — the router ring's
        membership. `prefill`-role replicas (ISSUE 12) never join:
        they only take explicit prefill handoffs."""
        return [rid for rid, st in self.replicas.items()
                if st.status == ACTIVE
                and st.role != kv_transport.ROLE_PREFILL]

    def _sync_ring(self) -> None:
        self.router.set_replicas(self._ring_ids())

    def _inflight_map(self) -> Dict[str, int]:
        return {rid: st.inflight for rid, st in self.replicas.items()}

    def _snapshots(self) -> Dict[str, ReplicaSnapshot]:
        return {rid: st.snapshot for rid, st in self.replicas.items()
                if st.snapshot is not None}

    # -- slice topology (ISSUE 17) --------------------------------------
    # Each replica is one slice: an engine built with
    # mesh_shape=(1, tp) spans tp chips and reports them in its
    # stats, which land in ReplicaSnapshot.chips. The fleet scales in
    # whole-slice units — activating a STANDBY replica provisions
    # chips_per_slice chips at once, never a fraction of a slice.
    def chips_per_slice(self) -> int:
        chips = [st.snapshot.chips for st in self.replicas.values()
                 if st.snapshot is not None]
        return max(chips) if chips else 1

    def active_chips(self) -> int:
        total = 0
        for rid in self._ids(ACTIVE):
            snap = self.replicas[rid].snapshot
            total += snap.chips if snap is not None else 1
        return total

    # -- request path ---------------------------------------------------
    def _route(self, body: Dict[str, Any],
               fp: Optional[str] = None
               ) -> "tuple[_ReplicaState, str]":
        if fp is None:
            fp = prefix_fingerprint(body,
                                    self.router.config.prefix_depth)
        rid, outcome = self.router.pick_ex(fp, self._snapshots(),
                                           self._inflight_map())
        if rid is None:
            raise AdmissionRejected("no_active_replicas",
                                    self.admission.retry_after())
        return self.replicas[rid], outcome

    @staticmethod
    def tenant_of(body: Dict[str, Any]) -> str:
        # OpenAI bodies carry the end-user id in "user"; fall back to
        # a header-injected hint if the ingress put one in the body
        return str(body.get("user") or body.get("tenant") or "default")

    # -- distributed tracing (ISSUE 7) ----------------------------------
    def _trace_begin(self, method: str, body: Dict[str, Any],
                     lane: Optional[str] = None):
        """Mint the request's trace context at fleet ingress: one
        request id and one trace id that follow it across admission,
        routing, and the replica's engine lifecycle (the context rides
        the body; LLMServerImpl pops it onto the engine Request).
        Returns (body', rec) — body' is a COPY carrying the plumbing
        keys, rec the in-progress ingress span record."""
        # ALWAYS copy + strip: the plumbing keys are internal even
        # when tracing is off — never forward client-supplied values
        # to the replica (and the failover/deadline paths mutate the
        # copy, never the caller's dict)
        body = {k: v for k, v in body.items()
                if k not in _INTERNAL_BODY_KEYS}
        # mint the tenant identity at admission (ISSUE 13): the
        # replica tags the engine Request (cost receipts, per-tenant
        # counters) with it; "" = default tenant so single-tenant
        # expositions stay label-free
        tenant = self.tenant_of(body)
        body["_tenant"] = "" if tenant == "default" else tenant
        if lane == "batch":
            # the batch lane's identity (ISSUE 14): priority is
            # FORCED to the bottom tier (a job body naming its own
            # priority must not outrank interactive traffic) and the
            # engine's SLO exclusion keys off the minted _lane
            body["_lane"] = "batch"
            body["priority"] = BATCH_PRIORITY
        elif self.batch is not None:
            # with the lane on, interactive traffic rides one tier up
            # so the engine's victim order (lowest priority first)
            # can never tie batch work against a user request — and a
            # client that explicitly sends the pre-lane default
            # priority 0 is CLAMPED up, not trusted: priorities <=
            # BATCH_PRIORITY belong to the lane (relative order among
            # clients above the floor is preserved)
            try:
                p = int(body.get("priority"))
            except (TypeError, ValueError):
                p = INTERACTIVE_PRIORITY
            body["priority"] = max(p, INTERACTIVE_PRIORITY)
        if not self.enable_tracing and not self.enable_traffic_log:
            return body, None
        # ALWAYS mint — `_request_id` doubles as the engine request id
        # downstream, so honoring a client-supplied value would let a
        # replayed id collide with (and abort/starve) another tenant's
        # in-flight request
        rid = uuid.uuid4().hex[:16]
        trace = None
        if self.enable_tracing:
            trace = {"trace_id": tracing.new_span_id(),
                     "span_id": tracing.new_span_id(),
                     "flow_id": tracing.new_span_id()}
            body["_request_id"] = rid
            body["_trace"] = trace
        # ISSUE 20 traffic-record fields: everything the capture
        # format needs, gathered HERE by allowlist (sampling_brief
        # never reads text fields) and enriched along the dispatch
        # path (fp, token counts, finish reason, failovers)
        deadline_s = body.get("deadline_s")
        try:
            deadline_s = (float(deadline_s)
                          if deadline_s is not None else None)
        except (TypeError, ValueError):
            deadline_s = None
        return body, {
            "rid": rid, "trace": trace, "method": method,
            "tenant": self.tenant_of(body), "t0": time.monotonic(),
            "t_admit": None, "t_route": None, "replica": None,
            "outcome": None, "status": "ok", "done": False,
            "lane": "batch" if lane == "batch" else "interactive",
            "stream": "stream" in method,
            "params": sampling_brief(body),
            "deadline_s": deadline_s, "fp": "",
            "prompt_tokens": 0, "out_tokens": 0, "finish": None,
            "failovers": 0, "t_first": None}

    def _trace_end(self, rec: Optional[Dict[str, Any]],
                   status: Optional[str] = None) -> None:
        """Close the ingress span set and publish it to the buffer
        (idempotent: the happy path and the error paths both reach
        here exactly once through the dispatch finally)."""
        if rec is None or rec["done"]:
            return
        rec["done"] = True
        if status is not None:
            rec["status"] = status
        if rec["trace"] is not None:
            self.trace.add(*request_events(
                self.trace.next_tid(), rec["rid"], rec["trace"],
                rec["t0"], rec["t_admit"], rec["t_route"],
                time.monotonic(), rec["replica"], rec["outcome"],
                rec["method"], rec["tenant"], rec["status"]))
        # ISSUE 20: every closed request feeds the traffic recorder
        # (rejects and errors included — a capture that omitted sheds
        # would replay a rosier workload than production saw)
        if self.enable_traffic_log:
            self.traffic.observe_request(rec)

    # -- deadline propagation (ISSUE 9) ---------------------------------
    def _mint_deadline(self, body: Dict[str, Any]
                       ) -> Optional[float]:
        """A client `deadline_s` (seconds from arrival) becomes an
        absolute `_deadline_epoch` on the body (wall clock, so it
        survives process hops to the replica, where the engine aborts
        past it at fold boundaries). Returns the MONOTONIC deadline
        admission compares against here at the ingress."""
        ds = body.get("deadline_s")
        if ds is None:
            return None
        ds = float(ds)
        body["_deadline_epoch"] = time.time() + ds
        return time.monotonic() + ds

    def _count_deadline_shed(self, stage: str) -> None:
        self.metrics["deadline_sheds"].inc(
            1, {"model": self.model_id, "stage": stage})

    async def dispatch(self, method: str, body: Dict[str, Any],
                       lane: Optional[str] = None) -> Any:
        """Unary request through admission + routing (trace-minted).
        A replica failure/timeout feeds the breaker and the request
        retries on another replica (bounded by health.max_failovers) —
        no tokens have reached the client, so a retry is invisible.

        lane="batch" (ISSUE 14) BYPASSES the admission controller:
        the front door's queue bound and SLO/brownout sheds protect
        user-visible waits, and a bulk job's whole point is to wait
        out the rush — its backpressure is the BatchLane pump's soak
        governor plus the engine's own priority-0 queueing, so its
        depth never feeds the shed/overload signals."""
        batch = lane == "batch"
        body, rec = self._trace_begin(method, body, lane=lane)
        deadline = self._mint_deadline(body)
        if not batch:
            try:
                await self.admission.acquire(self.tenant_of(body),
                                             deadline=deadline)
            except AdmissionRejected as e:
                if e.reason == "deadline":
                    self._count_deadline_shed("admission")
                self._trace_end(rec, f"rejected:{e.reason}")
                raise
        if rec is not None:
            rec["t_admit"] = time.monotonic()
        attempts = 0
        fp = prefix_fingerprint(body, self.router.config.prefix_depth)
        if rec is not None:
            rec["fp"] = fp
        try:
            while True:
                st, outcome = self._route(body, fp)
                if rec is not None and rec["replica"] is None:
                    rec["t_route"] = time.monotonic()
                    rec["replica"] = st.client.replica_id
                    rec["outcome"] = outcome
                rid = st.client.replica_id
                # fleet prefix store (ISSUE 12): seed the target with
                # the published prefix pages BEFORE dispatching, so
                # its local match_prefix hits like it prefilled the
                # prompt itself (best-effort, once per replica)
                await self._prefix_seed(fp, body, st)
                st.inflight += 1
                st.requests_total += 1
                try:
                    # per-attempt COPY: an in-process replica pops the
                    # plumbing keys (_deadline_epoch/_trace/...) off
                    # the dict it receives — a retry must re-send the
                    # fleet's canonical body, not the mutated one.
                    # With a deadline, the await is BOUNDED (remaining
                    # budget + grace): a healthy engine finishes with
                    # finish_reason="deadline" well inside the grace,
                    # so the timeout firing means the replica HUNG —
                    # the TimeoutError feeds the breaker below and the
                    # retry lands on a healthy replica (which sheds
                    # the expired request cleanly). Deadline-less
                    # requests keep unbounded unary semantics.
                    timeout = None
                    if deadline is not None:
                        timeout = (max(deadline - time.monotonic(),
                                       0.0)
                                   + self.health.unary_deadline_grace_s)
                    out = await asyncio.wait_for(
                        st.client.call(method, dict(body)), timeout)
                except (AdmissionRejected, asyncio.CancelledError):
                    raise
                except Exception as exc:
                    if not self._should_failover(rid, "dispatch",
                                                 exc, attempts):
                        raise
                    attempts += 1
                    if rec is not None:
                        rec["failovers"] = attempts
                    self.recorder.record(
                        "failover", mode="unary", replica=rid,
                        method=method, attempt=attempts,
                        error=repr(exc))
                    continue
                finally:
                    st.inflight -= 1
                if isinstance(out, dict):
                    fr = ((out.get("choices") or [{}])[0]
                          .get("finish_reason")
                          if out.get("choices") else None)
                    if fr == "deadline":
                        self._count_deadline_shed("engine")
                    if rec is not None:
                        usage = out.get("usage") or {}
                        rec["prompt_tokens"] = int(
                            usage.get("prompt_tokens") or 0)
                        rec["out_tokens"] = int(
                            usage.get("completion_tokens") or 0)
                        rec["finish"] = fr
                # publish the (now locally-cached) prefix into the
                # fleet store so the NEXT replica serving it imports
                # instead of cold-prefilling (once per fingerprint)
                await self._prefix_publish(fp, body, st)
                return out
        except AdmissionRejected as e:
            if rec is not None:
                rec["status"] = f"rejected:{e.reason}"
            raise
        except BaseException:
            if rec is not None:
                rec["status"] = "error"
            raise
        finally:
            if not batch:
                self.admission.release()
            self._trace_end(rec)

    async def dispatch_stream(self, method: str, body: Dict[str, Any]
                              ) -> AsyncIterator[Any]:
        """Streaming request: admission + routing hold for the WHOLE
        stream (a live stream occupies a decode slot, so it must keep
        weighing in both the router's in-flight counts and the
        admission concurrency bound until it completes).

        For the OpenAI stream methods the fleet consumes the
        replica's token-structured twin and renders the SSE framing
        HERE (ISSUE 9): a replica dying mid-stream feeds the breaker,
        the transcript's token-index dedup guarantees exactly-once
        delivery, and a continuation (original prompt + delivered
        tokens, same seed) re-dispatches to a healthy replica —
        token-exact, one stable completion id, no client-visible
        restart beyond latency."""
        body, rec = self._trace_begin(method, body)
        deadline = self._mint_deadline(body)
        token_method = failover.TOKEN_STREAM_METHODS.get(method)
        try:
            await self.admission.acquire(self.tenant_of(body),
                                         deadline=deadline)
        except AdmissionRejected as e:
            if e.reason == "deadline":
                self._count_deadline_shed("admission")
            self._trace_end(rec, f"rejected:{e.reason}")
            raise
        if rec is not None:
            rec["t_admit"] = time.monotonic()
        try:
            if token_method is None:
                # non-OpenAI stream: single-attempt passthrough
                st, outcome = self._route(body)
                if rec is not None:
                    rec["t_route"] = time.monotonic()
                    rec["replica"] = st.client.replica_id
                    rec["outcome"] = outcome
                st.inflight += 1
                st.requests_total += 1
                try:
                    async for chunk in st.client.stream(method, body):
                        yield chunk
                finally:
                    st.inflight -= 1
            else:
                async for chunk in self._stream_with_failover(
                        token_method, method == "chat_stream",
                        body, rec):
                    yield chunk
        except AdmissionRejected as e:
            if rec is not None:
                rec["status"] = f"rejected:{e.reason}"
            raise
        except GeneratorExit:
            if rec is not None:
                rec["status"] = "abandoned"
            raise
        except BaseException:
            if rec is not None:
                rec["status"] = "error"
            raise
        finally:
            self.admission.release()
            self._trace_end(rec)

    async def _stream_with_failover(self, token_method: str,
                                    is_chat: bool,
                                    body: Dict[str, Any],
                                    rec: Optional[Dict[str, Any]]
                                    ) -> AsyncIterator[str]:
        """The failover-aware SSE relay: drive the replica's token
        stream through the transcript (dedup by token index), render
        OpenAI SSE chunks with ONE stable completion id, and on a
        replica failure re-dispatch a token-exact continuation.

        ISSUE 12 layers the KV transport onto the same loop: a long
        prompt may first take the disaggregated handoff (prefill on a
        `prefill` replica, session shipped here), any attempt may be
        a RESUME of a shipped session instead of a fresh dispatch
        (`resume_stream_tokens` — the first chunk catches the
        transcript up, so index dedup keeps exactly-once), a serving
        replica may end its stream with a "migrated" marker (drain
        shipped the session off it — resume where the payload says),
        and a failing replica is first asked to EXPORT the session
        (failover-by-restore) before the PR 9 replay continuation
        kicks in. Every transport failure — severed ship, corrupted
        payload, import rejection — degrades to replay, which is
        token-exact by construction."""
        failover.pin_stream_identity(body)
        srid = str(body.get("_request_id") or uuid.uuid4().hex[:16])
        cid = ("chatcmpl-" if is_chat else "cmpl-") + srid
        created = int(time.time())
        transcript = failover.StreamTranscript()
        model = self.model_id
        attempts = 0
        cur = body
        session: Optional[str] = None     # shipped payload to resume
        fp = prefix_fingerprint(body, self.router.config.prefix_depth)
        if rec is not None:
            rec["fp"] = fp
        if self._disagg_applies(body):
            handoff = await self._prefill_handoff(body, is_chat)
            if handoff is not None:
                kind, val = handoff
                if kind == "final":
                    # finished during prefill (1-token generations):
                    # nothing left to disaggregate
                    folded = transcript.fold(val)
                    if folded is not None:
                        _, text, _, reason = folded
                        if rec is not None:
                            rec["t_first"] = time.monotonic()
                            rec["out_tokens"] = len(transcript.tokens)
                            rec["finish"] = reason
                            rec["prompt_tokens"] = int(
                                val.get("prompt_tokens") or 0)
                        yield failover.sse_chunk(
                            is_chat, cid,
                            val.get("model") or model, created,
                            text, True, reason, transcript.tokens)
                    yield "data: [DONE]\n\n"
                    return
                session = val
        self._live_streams[srid] = {"replica": None,
                                    "method": token_method}
        try:
            while True:
                st, outcome = self._route(cur, fp)
                if rec is not None and rec["replica"] is None:
                    rec["t_route"] = time.monotonic()
                    rec["replica"] = st.client.replica_id
                    rec["outcome"] = outcome
                rid = st.client.replica_id
                self._live_streams[srid]["replica"] = rid
                resumed = session is not None
                if not resumed:
                    await self._prefix_seed(fp, cur, st)
                st.inflight += 1
                st.requests_total += 1
                gen = None
                anext_task = None
                migrated = False
                try:
                    if resumed:
                        # resume a shipped session: import on the
                        # target and stream from the transcript head
                        # (the catch-up chunk regenerates nothing —
                        # the exporter's emitted-but-undelivered
                        # tokens ride the payload)
                        self.kvt_metrics["ship_bytes"].inc(
                            len(session) * 3 // 4,
                            {"model": self.model_id,
                             "direction": "import"})
                        gen = st.client.stream(
                            "resume_stream_tokens",
                            {"_session": session,
                             "_resume_offset": len(transcript.tokens),
                             "_request_id": body.get("_request_id"),
                             "_trace": body.get("_trace")})
                        session = None
                    else:
                        # per-attempt COPY (see dispatch): in-process
                        # replicas pop plumbing keys off the dict they
                        # receive; the continuation must inherit the
                        # CANONICAL body — deadline, trace, seed
                        gen = st.client.stream(token_method, dict(cur))
                    it = gen.__aiter__()
                    while True:
                        # stall watchdog (ISSUE 9): a HUNG replica
                        # (wedged loop, stuck device call) never
                        # raises — without this bound the stream,
                        # its admission slot, and the client would
                        # strand forever even after eviction.
                        # DELIBERATELY not wait_for (ISSUE 12): a
                        # timeout must NOT cancel into the replica's
                        # generator — that would abort the engine
                        # request (dropping any parked session)
                        # before the failover-by-restore handler
                        # below gets a chance to export it; the
                        # pending read is cancelled in the finally,
                        # after the restore decision.
                        anext_task = asyncio.ensure_future(
                            it.__anext__())
                        done, _ = await asyncio.wait(
                            {anext_task},
                            timeout=self.health.stream_stall_timeout_s)
                        if not done:
                            raise failover.StreamStalled(
                                f"no chunk from {rid} within "
                                f"{self.health.stream_stall_timeout_s}"
                                f"s")
                        t, anext_task = anext_task, None
                        try:
                            chunk = t.result()
                        except StopAsyncIteration:
                            # ended without a finish chunk: the
                            # transport died quietly — same failover
                            # path as a loud failure
                            raise failover.StreamBroken(
                                f"token stream from {rid} ended "
                                f"without finish")
                        if rec is not None and not rec["prompt_tokens"]:
                            rec["prompt_tokens"] = int(
                                chunk.get("prompt_tokens") or 0)
                        folded = transcript.fold(chunk)
                        if folded is None:
                            continue             # replayed: dedup'd
                        toks, text, fin, reason = folded
                        model = chunk.get("model") or model
                        if rec is not None and toks:
                            if rec["t_first"] is None:
                                rec["t_first"] = time.monotonic()
                            rec["out_tokens"] = len(transcript.tokens)
                        if fin and reason == "migrated":
                            # live migration marker (ISSUE 12): the
                            # session left this replica mid-stream —
                            # the logical stream is NOT finished.
                            # Tokens riding the marker (the export's
                            # drain can fold a not-yet-evented token
                            # into it) were folded into the
                            # transcript above, and the resume offset
                            # starts AT the transcript head — so they
                            # must reach the client NOW or they would
                            # be silently skipped
                            if toks or text:
                                yield failover.sse_chunk(
                                    is_chat, cid, model, created,
                                    text, False, None, toks)
                            transcript.finished = False
                            transcript.reason = None
                            migrated = True
                            break
                        yield failover.sse_chunk(
                            is_chat, cid, model, created, text, fin,
                            reason, toks)
                        if fin:
                            if reason == "deadline":
                                self._count_deadline_shed("engine")
                            if rec is not None:
                                rec["finish"] = reason
                            yield "data: [DONE]\n\n"
                            await self._prefix_publish(fp, body, st)
                            return
                except (GeneratorExit, asyncio.CancelledError):
                    raise            # client gone: nothing to fail over
                except AdmissionRejected:
                    raise
                except TransportError as exc:
                    # a corrupted/stale shipped payload landing on a
                    # HEALTHY replica: not the replica's fault (no
                    # breaker food, no failover budget) — degrade to
                    # the PR 9 replay continuation
                    self.recorder.record(
                        "kv_resume_failed", replica=rid,
                        request_id=srid, error=repr(exc))
                    cur = failover.continuation_body(body, transcript)
                except Exception as exc:
                    if resumed and failover.is_request_fault(exc):
                        # the import was REJECTED (id collision,
                        # incompatible geometry): same degradation as
                        # a corrupted payload
                        self.recorder.record(
                            "kv_resume_failed", replica=rid,
                            request_id=srid, error=repr(exc))
                        cur = failover.continuation_body(
                            body, transcript)
                    elif not self._should_failover(rid, "stream", exc,
                                                   attempts):
                        raise
                    else:
                        attempts += 1
                        if rec is not None:
                            rec["failovers"] = attempts
                        self.recorder.record(
                            "failover", mode="stream", replica=rid,
                            request_id=srid,
                            tokens_delivered=len(transcript.tokens),
                            attempt=attempts, error=repr(exc))
                        # failover-by-restore fast path (ISSUE 12):
                        # if the victim can still hand the session
                        # over (pages already spilled, or only the
                        # stream is wedged), resume beats replay —
                        # NOTE: runs before the finally closes the
                        # attempt generator, i.e. before the victim's
                        # server aborts the engine request
                        session = await self._restore_handoff(
                            rid, srid)
                        if session is None:
                            cur = failover.continuation_body(
                                body, transcript)
                finally:
                    st.inflight -= 1
                    if anext_task is not None:
                        # the stalled read abandoned above — cancel
                        # it NOW (after the restore decision): the
                        # replica-side generator unwinds and aborts
                        # its engine request like a real disconnect
                        # (a no-op if the session was just exported:
                        # the request is already finished "migrated")
                        anext_task.cancel()
                        try:
                            await anext_task
                        except (asyncio.CancelledError, Exception):
                            pass
                    if gen is not None:
                        # close the attempt's generator (a stalled one
                        # is abandoned mid-chunk): the replica side
                        # aborts its engine request like a real
                        # disconnect
                        await failover.close_quietly(gen)
                if migrated:
                    # the marker is enqueued inside the victim's
                    # export call, so this relay can observe it a few
                    # scheduler turns before the orchestrator's
                    # `_migrations[srid] = payload` bookkeeping runs —
                    # give that assignment a bounded grace before
                    # declaring the ship lost
                    session = self._migrations.pop(srid, None)
                    for _ in range(100):
                        if session is not None:
                            break
                        await asyncio.sleep(0.01)
                        session = self._migrations.pop(srid, None)
                    if session is None:
                        # the ship was lost mid-migration (severed
                        # export, crashed orchestrator): PR 9 replay
                        self.recorder.record(
                            "migration_lost", request_id=srid)
                        cur = failover.continuation_body(
                            body, transcript)
        finally:
            self._live_streams.pop(srid, None)
            self._migrations.pop(srid, None)

    # -- fleet KV transport (ISSUE 12) ----------------------------------
    def _ship_span(self, name: str, replica: str, t0: float,
                   request_id: Optional[str] = None,
                   **args: Any) -> None:
        """One KV-transport span into the ingress trace buffer —
        migrations/handoffs show up in GET /fleet/debug/trace beside
        the request lifecycles they interrupt."""
        if not self.enable_tracing:
            return
        self.trace.add(tracing.complete_event(
            name, "kv_transport", tracing.mono_to_epoch(t0),
            time.monotonic() - t0, tid=0,
            args={"replica": replica,
                  **({"request_id": request_id} if request_id
                     else {}),
                  **args}))

    def _pick_prefill(self) -> Optional[_ReplicaState]:
        """Least-loaded healthy ACTIVE prefill-role replica, or None
        (disaggregation silently degrades to mixed prefill)."""
        cands = [st for st in self.replicas.values()
                 if st.status == ACTIVE
                 and st.role == kv_transport.ROLE_PREFILL
                 and st.breaker.state == failover.CLOSED]
        if not cands:
            return None
        return min(cands, key=lambda st: (st.inflight,
                                          st.client.replica_id))

    def _disagg_applies(self, body: Dict[str, Any]) -> bool:
        t = self.transport
        return (t is not None and t.enable_disagg
                and kv_transport.prompt_char_len(body)
                >= t.disagg_prompt_chars
                and self._pick_prefill() is not None)

    async def _prefill_handoff(self, body: Dict[str, Any],
                               is_chat: bool
                               ) -> "Optional[tuple]":
        """Disaggregated prefill (ISSUE 12a): run the long prompt on
        a prefill replica and take the parked session for a decode
        replica to resume. -> ("session", payload) | ("final",
        chunk) when the request finished during prefill | None on
        any failure (the caller falls back to mixed prefill — the
        pre-transport path, always correct)."""
        st = self._pick_prefill()
        if st is None:
            return None
        rid = st.client.replica_id
        pbody = dict(body)
        pbody["_chat"] = is_chat
        st.inflight += 1
        st.requests_total += 1
        t0 = time.monotonic()
        try:
            # bound generously: a cold prefill replica may be
            # compiling — the same reasoning as the stall timeout
            out = await asyncio.wait_for(
                st.client.call("prefill_export", pbody),
                max(self.transport.ship_timeout_s,
                    self.health.stream_stall_timeout_s))
        except (AdmissionRejected, asyncio.CancelledError):
            raise
        except Exception as exc:
            if not failover.is_request_fault(exc):
                self._note_replica_failure(
                    rid, f"prefill:{type(exc).__name__}",
                    hard=not isinstance(exc, asyncio.TimeoutError))
            self.recorder.record("disagg_fallback", replica=rid,
                                 error=repr(exc))
            return None
        finally:
            st.inflight -= 1
        if out and out.get("final"):
            self._ship_span("disagg_prefill_final", rid, t0,
                            str(body.get("_request_id")))
            return ("final", out["final"])
        payload = (out or {}).get("session")
        if not payload:
            self.recorder.record("disagg_fallback", replica=rid,
                                 error="not exportable")
            return None
        tags = {"model": self.model_id}
        self.kvt_metrics["sessions_shipped"].inc(
            1, {**tags, "kind": "disagg"})
        self.kvt_metrics["ship_bytes"].inc(
            int(out.get("bytes") or 0),
            {**tags, "direction": "export"})
        self._ship_span("disagg_prefill", rid, t0,
                        str(body.get("_request_id")),
                        bytes=int(out.get("bytes") or 0),
                        pages=out.get("pages"))
        self.recorder.record(
            "disagg_handoff", replica=rid,
            request_id=str(body.get("_request_id")),
            bytes=out.get("bytes"), pages=out.get("pages"),
            generated=out.get("generated"))
        return ("session", payload)

    async def _restore_handoff(self, victim: str, srid: str
                               ) -> Optional[str]:
        """Failover-by-restore (ISSUE 12b): a pre-shipped payload
        (drain migration raced the failure) or a live export off the
        failing replica — which succeeds exactly when the victim can
        still serve control calls (pages already spilled to its host
        tier, or only the stream plane is wedged). None -> the
        caller replays (PR 9), token-exact either way."""
        t = self.transport
        if t is None or not t.enable_migration:
            return None
        pend = self._migrations.pop(srid, None)
        if pend is not None:
            return pend
        st = self.replicas.get(victim)
        if st is None:
            return None
        t0 = time.monotonic()
        try:
            out = await asyncio.wait_for(
                st.client.call("export_session",
                               {"request_id": srid,
                                "reason": "failover"}),
                t.ship_timeout_s)
        except Exception:
            return None
        payload = (out or {}).get("session")
        if not payload:
            return None
        tags = {"model": self.model_id}
        self.kvt_metrics["sessions_shipped"].inc(
            1, {**tags, "kind": "restore"})
        self.kvt_metrics["ship_bytes"].inc(
            int(out.get("bytes") or 0), {**tags,
                                         "direction": "export"})
        self._ship_span("failover_restore", victim, t0, srid,
                        bytes=int(out.get("bytes") or 0))
        self.recorder.record("failover_restore", replica=victim,
                             request_id=srid,
                             bytes=out.get("bytes"),
                             pages=out.get("pages"))
        return payload

    async def _migrate_sessions_off(self, rid: str) -> int:
        """Drain migration (ISSUE 12b): export every relay-driven
        stream this replica is serving; each stream's relay sees the
        "migrated" finish marker, claims its payload here, and
        resumes on a ring replica — tokens ship as pages, not
        replays. Best-effort per session: anything that fails to
        export just finishes on the draining replica like before."""
        t = self.transport
        st = self.replicas.get(rid)
        if t is None or not t.enable_migration or st is None:
            return 0
        moved = 0
        for srid, info in list(self._live_streams.items()):
            if info.get("replica") != rid \
                    or srid in self._migrations:
                continue
            t0 = time.monotonic()
            try:
                out = await asyncio.wait_for(
                    st.client.call("export_session",
                                   {"request_id": srid,
                                    "reason": "drain"}),
                    t.ship_timeout_s)
            except Exception as exc:
                self.recorder.record("migration_failed", replica=rid,
                                     request_id=srid,
                                     error=repr(exc))
                continue
            payload = (out or {}).get("session")
            if not payload:
                continue
            self._migrations[srid] = payload
            moved += 1
            tags = {"model": self.model_id}
            self.kvt_metrics["sessions_shipped"].inc(
                1, {**tags, "kind": "migration"})
            self.kvt_metrics["ship_bytes"].inc(
                int(out.get("bytes") or 0),
                {**tags, "direction": "export"})
            self._ship_span("session_migration", rid, t0, srid,
                            bytes=int(out.get("bytes") or 0),
                            pages=out.get("pages"))
            self.recorder.record(
                "session_migrated", replica=rid, request_id=srid,
                bytes=out.get("bytes"), pages=out.get("pages"),
                generated=out.get("generated"))
        return moved

    def _prefix_eligible(self, body: Dict[str, Any]) -> bool:
        t = self.transport
        if t is None or not t.enable_prefix_store \
                or self.prefix_store is None:
            return False
        if body.get("prompt") is None:
            # chat renderings are template-specific; the plain-prompt
            # prefix is the one chain both tokenizer paths share
            return False
        depth = self.router.config.prefix_depth
        return len(str(body["prompt"])[:depth]) >= t.prefix_min_chars

    async def _prefix_seed(self, fp: str, body: Dict[str, Any],
                           st: _ReplicaState) -> None:
        """Seed the routed replica with a published prefix it has not
        prefilled itself (ISSUE 12c) — best-effort and once per
        replica per fingerprint."""
        if not self._prefix_eligible(body):
            return
        ent = self.prefix_store.get(fp)
        rid = st.client.replica_id
        if ent is None or rid in ent.seeded:
            return
        t0 = time.monotonic()
        try:
            out = await asyncio.wait_for(
                st.client.call("import_prefix",
                               {"prefix": ent.payload}),
                self.transport.ship_timeout_s)
        except Exception as exc:
            self.recorder.record("prefix_seed_failed", replica=rid,
                                 error=repr(exc))
            return
        ent.seeded.add(rid)
        if (out or {}).get("pages"):
            self.prefix_store.hits += 1
            tags = {"model": self.model_id}
            self.kvt_metrics["prefix_store_hits"].inc(1, tags)
            self.kvt_metrics["ship_bytes"].inc(
                len(ent.payload) * 3 // 4,
                {**tags, "direction": "import"})
            self._ship_span("prefix_seed", rid, t0,
                            pages=out["pages"], fp=fp[:12])
            self.recorder.record("prefix_seeded", replica=rid,
                                 pages=out["pages"], fp=fp[:12])

    async def _prefix_publish(self, fp: str, body: Dict[str, Any],
                              st: _ReplicaState) -> None:
        """Publish a served prefix into the fleet store (ISSUE 12c)
        — ATTEMPTED once per fingerprint (success or not: a workload
        of distinct prompts must not pay an export round-trip on
        every response), exported from the replica that just
        (cheaply, cache-hot) served it."""
        if not self._prefix_eligible(body) \
                or fp in self.prefix_store \
                or fp in self._prefix_attempted:
            return
        self._prefix_attempted.add(fp)
        depth = self.router.config.prefix_depth
        text = str(body["prompt"])[:depth]
        rid = st.client.replica_id
        t0 = time.monotonic()
        try:
            out = await asyncio.wait_for(
                st.client.call("export_prefix", {"text": text}),
                self.transport.ship_timeout_s)
        except Exception as exc:
            self.recorder.record("prefix_publish_failed",
                                 replica=rid, error=repr(exc))
            return
        payload = (out or {}).get("prefix")
        if not payload:
            return
        self.prefix_store.put(fp, payload,
                              tokens=int(out.get("tokens") or 0),
                              publisher=rid)
        self.kvt_metrics["ship_bytes"].inc(
            int(out.get("bytes") or 0),
            {"model": self.model_id, "direction": "export"})
        self._ship_span("prefix_publish", rid, t0,
                        tokens=out.get("tokens"), fp=fp[:12])
        self.recorder.record("prefix_published", replica=rid,
                             tokens=out.get("tokens"), fp=fp[:12])

    # -- health state machine (ISSUE 9) ---------------------------------
    def _set_breaker_gauge(self, rid: str) -> None:
        self.metrics["breaker"].set(
            self.replicas[rid].breaker.gauge(),
            {"model": self.model_id, "replica": rid})

    def _should_failover(self, rid: str, mode: str,
                         exc: BaseException, attempts: int) -> bool:
        """The ONE failover policy for unary and stream attempts:
        classify the fault (request-caused faults surface unchanged —
        a retry would fail identically and the replica is fine), feed
        the breaker, check the retry budget, count the metric.
        Returns False when the caller must re-raise."""
        if failover.is_request_fault(exc):
            return False
        # a TIMEOUT (the ingress's own deadline-grace timer) is
        # ambiguous — hung replica vs cold compile vs a tight client
        # deadline — so it counts SOFTLY toward the threshold; a loud
        # failure (severed stream, raised call) is a death signal and
        # trips immediately
        self._note_replica_failure(
            rid, f"{mode}:{type(exc).__name__}",
            hard=not isinstance(exc, asyncio.TimeoutError))
        if attempts >= self.health.max_failovers:
            return False
        self.metrics["failovers"].inc(1, {"model": self.model_id})
        return True

    def _note_replica_failure(self, rid: str, reason: str,
                              hard: bool = True) -> None:
        """A dispatch/stream against this replica failed — a stronger
        death signal than a slow probe, so (by default, and unless
        the caller softens it) it trips the breaker immediately and
        evicts, instead of waiting out probe_failures refresh
        cycles."""
        st = self.replicas.get(rid)
        if st is None:
            return
        st.breaker.record_failure(
            hard=hard and self.health.fail_fast_on_dispatch)
        self._set_breaker_gauge(rid)
        # evict on the open TRANSITION — and also when the breaker
        # was already open but the eviction had been deferred (sole
        # active replica at the time; another may have activated
        # since, making the eviction possible now)
        if st.breaker.state == failover.OPEN:
            self._evict(rid, reason)

    def _evict(self, rid: str, reason: str) -> None:
        """The breaker opened: remove the replica from the router
        ring NOW (in-flight work fails over; new work never routes
        here) and mark it UNHEALTHY so only half-open probes can
        bring it back. Never evicts the LAST active replica — a
        false positive there would turn an incident into a total
        blackout; its open breaker still gates recovery."""
        st = self.replicas[rid]
        if st.status != ACTIVE:
            return                 # draining/standby: not in the ring
        if rid in self._ring_ids() \
                and not [r for r in self._ring_ids() if r != rid]:
            # the SOLE ring replica: activate a standby replacement
            # if one exists — spare healthy capacity must not idle
            # while everything routes to a dead replica. With no
            # standby either, defer: the breaker still gates
            # recovery, but an empty ring would be a total blackout.
            # (An evicted PREFILL replica never empties the ring —
            # disaggregation just falls back to mixed prefill.)
            # The replacement must itself be decode-capable: swapping
            # the last ring replica for a prefill-role standby would
            # leave the ring empty — the exact blackout this branch
            # exists to prevent
            standby = [r for r in self._ids(STANDBY)
                       if self.replicas[r].role
                       != kv_transport.ROLE_PREFILL]
            if not standby:
                self.recorder.record("eviction_deferred", replica=rid,
                                     reason=reason)
                return
            sub = standby[0]
            self.replicas[sub].status = ACTIVE
            self.recorder.record("failover_activate", replica=sub,
                                 replacing=rid)
            self._scale_events.append(
                {"ts": time.time(), "event": "activate",
                 "replica": sub, "reason": f"replacing:{rid}"})
        st.status = UNHEALTHY
        self._sync_ring()
        self.metrics["evictions"].inc(1, {"model": self.model_id})
        self.recorder.record("replica_evicted", replica=rid,
                             reason=reason,
                             trips=st.breaker.trips)
        self._scale_events.append(
            {"ts": time.time(), "event": "evict", "replica": rid,
             "reason": reason})
        # postmortem breadcrumb: best-effort black-box of the evicted
        # replica (it may be dead — the dump call is allowed to
        # fail). The task reference is RETAINED until done: the loop
        # holds tasks weakly, and a GC'd pending dump would silently
        # drop the one artifact the eviction exists to capture.
        try:
            task = asyncio.get_running_loop().create_task(
                self._dump_one(rid, f"evicted:{reason}"))
            self._dump_tasks.add(task)
            task.add_done_callback(self._dump_tasks.discard)
        except RuntimeError:
            pass                   # no running loop (sync test driver)

    async def _dump_one(self, rid: str, cause: str) -> None:
        try:
            await asyncio.wait_for(
                self.replicas[rid].client.call(
                    "debug_dump", {"cause": cause}),
                timeout=self.dispatch_timeout_s)
        except Exception:
            pass

    def _readmit(self, rid: str) -> None:
        """The breaker closed (half-open probes passed): back into
        the router ring. The autoscaler trims any surplus on its own
        cadence."""
        st = self.replicas[rid]
        if st.status != UNHEALTHY:
            return
        st.status = ACTIVE
        self._sync_ring()
        self.recorder.record("replica_readmitted", replica=rid,
                             trips=st.breaker.trips)
        self._scale_events.append(
            {"ts": time.time(), "event": "readmit", "replica": rid})

    # -- stats refresh --------------------------------------------------
    async def refresh(self) -> None:
        """Pull fleet_stats from every non-standby replica — the
        probe loop that drives the health state machine: consecutive
        failures/timeouts open the breaker (evict from the ring),
        and once its cooldown passes, half-open probes decide
        re-admission. A successful probe stamps a FRESH snapshot
        (mono_ts), so the router can deprioritize replicas whose
        numbers have gone stale instead of trusting them forever."""
        ids = self._ids(ACTIVE, DRAINING, UNHEALTHY)
        now = time.monotonic()

        async def one(rid: str):
            st = self.replicas[rid]
            if not st.breaker.should_probe(now):
                return          # open, inside its cooldown: leave it
            self._set_breaker_gauge(rid)     # open->half-open visible
            try:
                stats = await asyncio.wait_for(
                    st.client.call("fleet_stats"),
                    timeout=self.probe_timeout_s)
            except Exception as exc:
                st.breaker.record_failure()
                self._set_breaker_gauge(rid)
                if st.breaker.state == failover.OPEN:
                    # covers the transition AND a previously deferred
                    # eviction (last-active then; maybe not anymore)
                    self._evict(rid,
                                f"probe:{type(exc).__name__}")
                return                       # keep the stale snapshot
            closed = st.breaker.record_success()
            self._set_breaker_gauge(rid)
            snap = ReplicaSnapshot.from_stats(stats)
            snap.replica = rid
            st.snapshot = snap
            st.slo_totals = dict(stats.get("slo_totals") or {})
            if closed:
                self._readmit(rid)

        await asyncio.gather(*(one(rid) for rid in ids))
        self._update_perf_gauges()

    def _update_perf_gauges(self) -> None:
        """Aggregate per-replica MFU/MBU into the fleet gauges
        (ISSUE 11): goodput-weighted over ACTIVE replicas, falling
        back to a plain mean when no tokens flowed in the window."""
        snaps = [st.snapshot for rid, st in self.replicas.items()
                 if st.status == ACTIVE and st.snapshot is not None]
        if not snaps:
            return
        w = sum(s.decode_tps + s.prefill_tps for s in snaps)
        if w > 0:
            mfu = sum(s.mfu * (s.decode_tps + s.prefill_tps)
                      for s in snaps) / w
            mbu = sum(s.mbu * (s.decode_tps + s.prefill_tps)
                      for s in snaps) / w
        else:
            mfu = sum(s.mfu for s in snaps) / len(snaps)
            mbu = sum(s.mbu for s in snaps) / len(snaps)
        tags = {"model": self.model_id}
        self._fleet_mfu_gauge.set(round(mfu, 6), tags)
        self._fleet_mbu_gauge.set(round(mbu, 6), tags)

    # -- autoscaling ----------------------------------------------------
    def _window_metrics(self) -> FleetMetrics:
        """Fleet aggregates over the window since the last call:
        deltas of the cumulative TTFT/queue-wait sums each replica's
        telemetry summary exports (PR 5), plus live queue depths and
        the admission shed delta. Deltas are tracked PER REPLICA ID,
        not on a fleet sum over the changing ACTIVE/DRAINING set — a
        replica parking to STANDBY must not show up as a negative
        window, and a reactivated one must contribute only its growth
        since last seen, not its lifetime totals."""
        keys = ("ttft_s", "ttft_n", "queue_s", "queue_n")
        d = {k: 0.0 for k in keys}
        waiting = 0
        occ: List[float] = []
        pressure = 0.0
        for rid, st in self.replicas.items():
            if st.slo_totals:
                prev = self._prev_slo.get(rid, {})
                cur = {k: float(st.slo_totals.get(k, 0.0))
                       for k in keys}
                for k in keys:
                    # clamped: an engine restart resets its counters
                    d[k] += max(0.0, cur[k] - prev.get(k, 0.0))
                self._prev_slo[rid] = cur
            if st.snapshot is not None and st.status == ACTIVE:
                # batch lane (ISSUE 14): queued priority-0 bulk work
                # is harvested idle capacity — the autoscaler must
                # scale on INTERACTIVE depth only, or a deliberately
                # deep batch backlog would page the fleet to max
                waiting += st.snapshot.displaceable_waiting()
                occ.append(st.snapshot.interactive_occupancy())
                # max, not mean (ISSUE 10): one oversubscribed replica
                # is already spill/restore-taxing its streams even
                # when its siblings sit idle
                pressure = max(pressure, st.snapshot.page_pressure)
        shed = (self.admission.shed_total
                + self.admission.rejected["queue_full"]
                + self.admission.rejected["brownout"])
        shed_delta = shed - self._prev_shed
        self._prev_shed = shed
        return FleetMetrics(
            ttft_ms=(d["ttft_s"] / d["ttft_n"] * 1e3
                     if d["ttft_n"] > 0 else 0.0),
            queue_wait_ms=(d["queue_s"] / d["queue_n"] * 1e3
                           if d["queue_n"] > 0 else 0.0),
            waiting=waiting,
            occupancy=(sum(occ) / len(occ) if occ else 0.0),
            shed_delta=shed_delta,
            slo_page=self.watchdog.paging,
            slo_burn=self.watchdog.max_burn,
            page_pressure=pressure,
            chips_per_slice=self.chips_per_slice())

    # -- SLO burn-rate watchdog (ISSUE 7) -------------------------------
    def _watchdog_totals(self) -> Dict[str, float]:
        """Fleet-summed monotone SLO totals, accumulated per replica
        id with clamped deltas (same reasoning as _window_metrics:
        replica restarts and membership changes must not produce
        negative or replayed burn windows)."""
        for rid, st in self.replicas.items():
            if not st.slo_totals:
                continue
            prev = self._watch_prev.get(rid, {})
            cur = {k: float(st.slo_totals.get(k, 0.0))
                   for k in _WATCH_KEYS}
            for k in _WATCH_KEYS:
                self._watch_accum[k] += max(
                    0.0, cur[k] - prev.get(k, 0.0))
            self._watch_prev[rid] = cur
        return dict(self._watch_accum)

    def _interactive_idle(self) -> bool:
        """No interactive demand anywhere: front door empty AND every
        ACTIVE replica's snapshot shows zero interactive requests
        queued or decoding (batch-lane depth is the trough's own soak
        and does not count). Conservative toward False — a missing
        snapshot is unknown, not idle."""
        if self.admission.inflight > 0 \
                or self.admission._queue_len() > 0:
            return False
        for st in self.replicas.values():
            if st.status != ACTIVE:
                continue
            snap = st.snapshot
            if snap is None:
                return False
            if (snap.active - snap.active_batch) > 0 \
                    or snap.displaceable_waiting() > 0:
                return False
        return True

    def watchdog_tick(self, now: Optional[float] = None) -> None:
        """One watchdog evaluation over the freshly-refreshed replica
        totals, plus the reactions: brownout the front door while
        paging (shed before the SLO is blown) and black-box every
        replica on the page transition (the postmortem wants the
        fleet's state AT the breach, not after the restart)."""
        if not self.watchdog.config.enabled:
            return
        was_paging = self.watchdog.paging
        self.watchdog.observe(self._watchdog_totals(), now,
                              idle=self._interactive_idle())
        paging = self.watchdog.paging
        # KV page pressure (ISSUE 10): max over active replicas, with
        # fleet spillability deciding the reaction — pressure on a
        # fleet that can spill to its host tiers is a LATENCY tier
        # (requests queue with backpressure and complete), so only a
        # non-spillable pressured fleet sheds at the front door
        pressure = 0.0
        spillable = True
        anomaly_rate = 0.0
        for st in self.replicas.values():
            snap = st.snapshot
            if snap is None or st.status != ACTIVE:
                continue
            if snap.page_pressure > pressure:
                pressure = snap.page_pressure
                spillable = snap.spillable
            anomaly_rate = max(anomaly_rate, snap.anomaly_rate)
        self.watchdog.observe_pressure(pressure)
        # tick-anomaly page precursor (ISSUE 13): watch-only — the
        # alert precedes SLO burn, it never sheds on its own
        self.watchdog.observe_anomaly(anomaly_rate)
        pressure_shed = (self.watchdog.pressure_state == "high"
                         and not spillable)
        self.admission.set_page_pressure(pressure, spillable)
        if self.admission.set_brownout(paging or pressure_shed):
            self.recorder.record(
                "brownout_on" if (paging or pressure_shed)
                else "brownout_off",
                burn=round(self.watchdog.max_burn, 3),
                page_pressure=round(pressure, 4))
        if paging and not was_paging:
            try:
                self._page_dump_task = \
                    asyncio.get_running_loop().create_task(
                        self.debug_dump_all("slo_page"))
            except RuntimeError:
                pass     # no running loop (sync test driver)

    async def debug_dump_all(self, cause: str) -> Dict[str, Any]:
        """Ask every non-standby replica to snapshot a postmortem
        black-box bundle (watchdog page / POST /debug/dump).
        UNHEALTHY replicas included — an evicted-but-alive replica is
        the one most likely implicated in whatever paged; a dead one
        degrades to its error row under the timeout."""
        ids = self._ids(ACTIVE, DRAINING, UNHEALTHY)

        async def one(rid: str):
            try:
                return rid, await asyncio.wait_for(
                    self.replicas[rid].client.call(
                        "debug_dump", {"cause": cause}),
                    timeout=self.dispatch_timeout_s)
            except Exception as e:
                return rid, {"error": repr(e)}

        out = dict(await asyncio.gather(*(one(rid) for rid in ids)))
        self.recorder.record("postmortem_dump", cause=cause,
                             replicas=sorted(out))
        return out

    async def autoscale_tick(self, now: Optional[float] = None) -> int:
        """One control-loop iteration: refresh → watchdog → decide →
        apply. Returns the applied target (also at GET /fleet)."""
        await self.refresh()
        self.watchdog_tick(now)
        active = len(self._ids(ACTIVE))
        target = self.autoscaler.decide(self._window_metrics(),
                                        active, now)
        if target != active:
            self._apply_target(target)
        return target

    def _apply_target(self, target: int) -> None:
        active = self._ids(ACTIVE)
        if target > len(active):
            for rid in self._ids(STANDBY)[:target - len(active)]:
                self.replicas[rid].status = ACTIVE
                self._scale_events.append(
                    {"ts": time.time(), "event": "activate",
                     "replica": rid})
        elif target < len(active):
            # drain the emptiest replicas first: least in-flight work,
            # then least KV occupancy (cheapest caches to lose)
            def cost(rid: str):
                st = self.replicas[rid]
                occ = (st.snapshot.kv_occupancy
                       if st.snapshot is not None else 0.0)
                return (st.inflight, occ)

            chosen: List[str] = []
            for rid in sorted(active, key=cost):
                if len(chosen) >= len(active) - target:
                    break
                # never drain the LAST decode-capable replica: an
                # idle mixed replica must not be sacrificed while
                # prefill-role replicas (which can never serve ring
                # traffic) stay ACTIVE — that would empty the ring
                if rid in self._ring_ids() and not [
                        r for r in self._ring_ids()
                        if r != rid and r not in chosen]:
                    continue
                chosen.append(rid)
            for rid in chosen:
                self._begin_drain(rid)
        self._sync_ring()

    def _begin_drain(self, rid: str) -> None:
        st = self.replicas[rid]
        st.status = DRAINING
        self._scale_events.append(
            {"ts": time.time(), "event": "drain_begin", "replica": rid})
        st.drain_task = asyncio.get_running_loop().create_task(
            self._drain_to_standby(rid, self.drain_timeout_s))

    async def _drain_to_standby(self, rid: str,
                                timeout_s: float = 120.0) -> None:
        """Out of the ring already; wait for the router-side in-flight
        count to hit zero (every stream completed), then for the
        engine itself to run dry (the replica's drain() polls
        has_work(), which counts in-flight pipelined ticks and pending
        folds), then park."""
        st = self.replicas[rid]
        attempt = 0
        while True:
            deadline = time.monotonic() + timeout_s
            # KV transport (ISSUE 12): ship the replica's live
            # sessions to the survivors FIRST — their relays resume
            # from restored pages instead of replaying tokens, and
            # the in-flight count below drops as each relay moves off
            moved = await self._migrate_sessions_off(rid)
            if moved:
                self._scale_events.append(
                    {"ts": time.time(), "event": "drain_migrate",
                     "replica": rid, "sessions": moved})
            while st.inflight > 0 and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            drained = True
            try:
                rep = await st.client.call("drain", timeout_s)
                drained = bool((rep or {}).get("drained", True))
            except Exception:
                pass    # best-effort: the replica may not expose drain
            if st.inflight == 0 and drained:
                break
            # wedged: STAY DRAINING — out of the ring and ineligible
            # for reactivation (_apply_target only activates STANDBY)
            # — and retry; parking dirty would hand a replica known
            # unable to finish work back to the router on scale-up
            attempt += 1
            self._scale_events.append(
                {"ts": time.time(), "event": "drain_retry",
                 "replica": rid, "attempt": attempt})
            await asyncio.sleep(min(30.0, 2.0 * attempt))
        st.status = STANDBY
        self._scale_events.append(
            {"ts": time.time(), "event": "drain_done", "replica": rid,
             "clean": attempt == 0})

    # -- background control loop ---------------------------------------
    def start(self) -> None:
        """Start the refresh + autoscale loop on the current event
        loop (idempotent). Separate cadences: stats refresh keeps the
        router's view fresh; autoscale decisions run slower."""
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.get_running_loop().create_task(
                self._control_loop())

    async def stop(self) -> None:
        if self.batch is not None:
            await self.batch.stop()
        if self._loop_task is not None:
            self._loop_task.cancel()
            try:
                await self._loop_task
            except (asyncio.CancelledError, Exception):
                pass
            self._loop_task = None

    async def _control_loop(self) -> None:
        last_autoscale = 0.0
        while True:
            try:
                await self.refresh()
                self.watchdog_tick()
                now = time.monotonic()
                if now - last_autoscale >= self.autoscale_period_s:
                    last_autoscale = now
                    active = len(self._ids(ACTIVE))
                    target = self.autoscaler.decide(
                        self._window_metrics(), active)
                    if target != active:
                        self._apply_target(target)
            except asyncio.CancelledError:
                raise
            except Exception:
                import logging
                logging.getLogger(__name__).exception(
                    "fleet control loop iteration failed")
            await asyncio.sleep(self.refresh_period_s)

    # -- observability --------------------------------------------------
    async def metrics_text(self) -> str:
        """ONE valid Prometheus exposition for the whole fleet.

        Two registry topologies (the ISSUE 6 satellite):
        - shared registry (in-process replicas / local testing): every
          scrape renders the same process registry; each replica's
          engine tags its own series with its replica id, so the fleet
          scrapes every replica (each refreshes its own gauges) and
          keeps the LAST rendering — by then every replica's gauges
          are fresh in the shared registry.
        - separate registries (real replica actors): each exposition
          is scraped independently and relabeled with replica=<id> so
          identical series from different replicas cannot collide or
          silently sum in the merged document.
        """
        from ...util.metrics import (export_prometheus,
                                     merge_expositions,
                                     relabel_exposition)

        # UNHEALTHY included: an evicted replica's series must not
        # vanish from the merged exposition mid-incident (rate()
        # gaps, absent-series alerts); a dead one just times out
        ids = self._ids(ACTIVE, DRAINING, UNHEALTHY)

        async def one(rid: str):
            st = self.replicas[rid]
            try:
                return (rid, st.client, await asyncio.wait_for(
                    st.client.call("metrics_text"),
                    timeout=self.probe_timeout_s))
            except Exception:
                return None     # a wedged replica can't black out
                                # the whole fleet's scrape

        texts = [t for t in await asyncio.gather(
            *(one(rid) for rid in ids)) if t is not None]
        if not texts:
            return export_prometheus()
        if all(c.shares_registry for _, c, _ in texts):
            return texts[-1][2]
        # separate registries: the ingress's own series (watchdog
        # burn-rate gauges, alert counters) live in THIS process's
        # registry — merge them in unrelabeled (they are fleet-scoped,
        # not per-replica)
        return merge_expositions(
            [relabel_exposition(t, {"replica": rid})
             for rid, _, t in texts] + [export_prometheus()])

    async def status(self) -> Dict[str, Any]:
        """The GET /fleet document: routing inputs per replica,
        router/admission counters, last autoscale decision."""
        reps: Dict[str, Any] = {}
        for rid, st in self.replicas.items():
            snap = st.snapshot
            reps[rid] = {
                "status": st.status,
                "role": st.role,
                "inflight": st.inflight,
                "requests_total": st.requests_total,
                "breaker": st.breaker.stats(),
                **({} if snap is None else {
                    # slice topology (ISSUE 17): chips this replica's
                    # engine mesh occupies (a tp slice reports tp);
                    # mfu below is already per chip (the engine's
                    # accountant divides by mesh size)
                    "chips": snap.chips,
                    "active": snap.active,
                    "waiting": snap.waiting,
                    # batch lane (ISSUE 14): the preemptible share
                    "waiting_batch": snap.waiting_batch,
                    "active_batch": snap.active_batch,
                    "kv_occupancy": round(snap.kv_occupancy, 4),
                    "free_pages": snap.free_pages,
                    "prefix_cache_hit_rate": round(
                        snap.cache_hit_rate, 4),
                    "last_tick_age_s": snap.last_tick_age_s,
                    # KV memory hierarchy (ISSUE 10): host-tier
                    # occupancy + oversubscription per replica
                    "page_pressure": round(snap.page_pressure, 4),
                    "parked_sessions": snap.parked,
                    "kv_offload": snap.spillable,
                    # ISSUE 12 satellite: host-tier byte occupancy —
                    # migration/prefix-store pressure before page
                    # counts saturate
                    "kv_host_bytes_used": snap.kv_host_bytes,
                    # perf accounting (ISSUE 11): recent utilization
                    # against the replica's hardware envelope
                    "mfu": round(snap.mfu, 6),
                    "mbu": round(snap.mbu, 6),
                    "roof": snap.roof,
                    "decode_tokens_per_s": round(snap.decode_tps, 3),
                    "prefill_tokens_per_s": round(
                        snap.prefill_tps, 3),
                    # tick-anomaly analyzer (ISSUE 13): recent
                    # anomaly rate + lifetime count per replica
                    "anomaly_rate": round(snap.anomaly_rate, 4),
                    "anomalies_total": snap.anomalies_total,
                    **({"anomaly_last_kind": snap.anomaly_last_kind}
                       if snap.anomaly_last_kind else {}),
                    # snapshot age (ISSUE 9): how old the routing
                    # inputs above are — stale = probes failing
                    "snapshot_age_s": round(snap.age_s(), 3),
                }),
            }
        return {
            "replicas": reps,
            "router": self.router.stats(),
            "admission": self.admission.stats(),
            "watchdog": {
                "enabled": self.watchdog.config.enabled,
                "paging": self.watchdog.paging,
                "state": dict(self.watchdog.state),
                "burn": self.watchdog.last,
                "alerts_total": self.watchdog.alerts_total,
                "objective": self.watchdog.config.objective,
                # fleet page-pressure monitor (ISSUE 10)
                "page_pressure": round(self.watchdog.last_pressure, 4),
                "pressure_state": self.watchdog.pressure_state,
                # tick-anomaly page precursor (ISSUE 13)
                "anomaly_rate": round(
                    self.watchdog.last_anomaly_rate, 4),
                "anomaly_state": self.watchdog.anomaly_state,
            },
            "tracing": {
                "enabled": self.enable_tracing,
                "ingress_buffer": self.trace.stats(),
            },
            # fleet KV transport (ISSUE 12)
            "transport": {
                "enabled": self.transport is not None,
                **({} if self.transport is None else {
                    "roles": {rid: st.role
                              for rid, st in self.replicas.items()},
                    "disagg": self.transport.enable_disagg,
                    "migration": self.transport.enable_migration,
                    "live_streams": len(self._live_streams),
                    "pending_migrations": len(self._migrations),
                    "prefix_store": (
                        self.prefix_store.stats()
                        if self.prefix_store is not None else None),
                }),
            },
            # preemptible batch lane (ISSUE 14)
            "batch": (self.batch.stats()
                      if self.batch is not None
                      else {"enabled": False}),
            "recorder": self.recorder.stats(),
            # ISSUE 20 traffic recorder (GET /fleet/debug/traffic)
            "traffic": (self.traffic.stats()
                        if self.enable_traffic_log
                        else {"enabled": False}),
            "health": {
                "probe_failures": self.health.probe_failures,
                "open_cooldown_s": self.health.open_cooldown_s,
                "half_open_probes": self.health.half_open_probes,
                "max_failovers": self.health.max_failovers,
                "unhealthy": self._ids(UNHEALTHY),
            },
            "autoscale": {
                "min_replicas": self.autoscaler.config.min_replicas,
                "max_replicas": self.autoscaler.config.max_replicas,
                "active": len(self._ids(ACTIVE)),
                "draining": len(self._ids(DRAINING)),
                "standby": len(self._ids(STANDBY)),
                "unhealthy": len(self._ids(UNHEALTHY)),
                # slice topology (ISSUE 17): the fleet scales in
                # whole-slice units — a scale-up provisions
                # chips_per_slice chips, and active_chips is the
                # chip-denominated capacity behind the replica count
                "chips_per_slice": self.chips_per_slice(),
                "active_chips": self.active_chips(),
                "last_decision": self.autoscaler.last_decision,
                "events": list(self._scale_events)[-32:],
            },
        }


__all__ = ["FleetManager", "LocalReplicaClient", "HandleReplicaClient",
           "ACTIVE", "DRAINING", "STANDBY", "UNHEALTHY"]
