"""FleetManager: N engine replicas behind one router + front door.

The composition layer of ISSUE 6. A fleet is a set of `LLMServerImpl`
replicas reached through a small client interface (so the SAME manager
runs over in-process servers in tier-1 tests and benches, over
local-testing-mode deployment handles, and over real replica actors),
plus the three policy objects:

- `FleetRouter` (router.py): prefix-affine, occupancy-aware pick;
- `AdmissionController` (admission.py): bounded queue + 429 shed;
- `FleetAutoscaler` (autoscaler.py): TTFT/queue-wait-driven target.

Replica lifecycle: ACTIVE (in the ring) -> DRAINING (out of the ring,
finishing in-flight work) -> STANDBY (idle, instantly re-activatable).
The fleet provisions `max_replicas` up front and moves them between
these states — scale-down never drops a stream: the victim leaves the
ring first, the router's in-flight count reaches zero only when every
stream it was serving has completed, and only then does the engine's
own idle check (`has_work`) retire it to standby.

Single-event-loop discipline: every mutation of fleet state happens on
the loop the ingress serves from (the manager is created there); the
blocking engine work stays inside each replica's own executor pump.
"""

from __future__ import annotations

import asyncio
import collections
import time
from typing import Any, AsyncIterator, Deque, Dict, List, Optional, \
    Sequence

from .admission import (AdmissionConfig, AdmissionController,
                        AdmissionRejected)
from .autoscaler import AutoscaleConfig, FleetAutoscaler, FleetMetrics
from .router import (FleetRouter, ReplicaSnapshot, RouterConfig,
                     prefix_fingerprint)

ACTIVE = "ACTIVE"
DRAINING = "DRAINING"
STANDBY = "STANDBY"


class LocalReplicaClient:
    """Direct in-process LLMServerImpl (tier-1 tests, bench --fleet)."""

    shares_registry = True

    def __init__(self, replica_id: str, server: Any):
        self.replica_id = replica_id
        self.server = server

    async def call(self, method: str, *args) -> Any:
        return await getattr(self.server, method)(*args)

    def stream(self, method: str, body: Dict[str, Any]):
        return getattr(self.server, method)(body)


class HandleReplicaClient:
    """A serve DeploymentHandle to an LLMServer deployment. In
    local_testing_mode every handle resolves to an in-process replica
    sharing this process's metric registry; across real replica
    actors each process has its own registry (shares_registry drives
    the /metrics merge strategy — see metrics_text())."""

    def __init__(self, replica_id: str, handle: Any,
                 shares_registry: bool = False):
        self.replica_id = replica_id
        self.handle = handle
        self.shares_registry = shares_registry

    async def call(self, method: str, *args) -> Any:
        return await getattr(self.handle, method).remote(*args)

    def stream(self, method: str, body: Dict[str, Any]):
        return getattr(self.handle, method).options(
            stream=True).remote(body)


class _ReplicaState:
    def __init__(self, client: Any, status: str):
        self.client = client
        self.status = status
        self.inflight = 0            # router-side, zero-lag
        self.requests_total = 0
        self.snapshot: Optional[ReplicaSnapshot] = None
        self.slo_totals: Dict[str, float] = {}
        self.drain_task: Optional[asyncio.Task] = None


class FleetManager:
    def __init__(self, clients: Sequence[Any],
                 router: Optional[RouterConfig] = None,
                 admission: Optional[AdmissionConfig] = None,
                 autoscale: Optional[AutoscaleConfig] = None,
                 refresh_period_s: float = 0.5,
                 autoscale_period_s: float = 2.0):
        if not clients:
            raise ValueError("a fleet needs at least one replica")
        auto = autoscale or AutoscaleConfig(
            min_replicas=len(clients), max_replicas=len(clients))
        if auto.max_replicas > len(clients):
            raise ValueError(
                f"max_replicas={auto.max_replicas} but only "
                f"{len(clients)} replicas are provisioned")
        if not 1 <= auto.min_replicas <= auto.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas={auto.min_replicas} "
                f"<= max_replicas={auto.max_replicas}")
        self.router = FleetRouter(router)
        self.admission = AdmissionController(admission)
        self.autoscaler = FleetAutoscaler(auto)
        self.refresh_period_s = refresh_period_s
        self.autoscale_period_s = autoscale_period_s
        self.replicas: Dict[str, _ReplicaState] = {}
        for i, c in enumerate(clients):
            status = ACTIVE if i < auto.min_replicas else STANDBY
            self.replicas[c.replica_id] = _ReplicaState(c, status)
        self.router.set_replicas(self._ids(ACTIVE))
        self._prev_slo: Dict[str, Dict[str, float]] = {}
        self._prev_shed = 0
        self._scale_events: Deque[Dict[str, Any]] = \
            collections.deque(maxlen=256)
        self._loop_task: Optional[asyncio.Task] = None

    # -- membership helpers --------------------------------------------
    def _ids(self, *statuses: str) -> List[str]:
        return [rid for rid, st in self.replicas.items()
                if st.status in statuses]

    def _inflight_map(self) -> Dict[str, int]:
        return {rid: st.inflight for rid, st in self.replicas.items()}

    def _snapshots(self) -> Dict[str, ReplicaSnapshot]:
        return {rid: st.snapshot for rid, st in self.replicas.items()
                if st.snapshot is not None}

    # -- request path ---------------------------------------------------
    def _route(self, body: Dict[str, Any]) -> _ReplicaState:
        fp = prefix_fingerprint(body, self.router.config.prefix_depth)
        rid = self.router.pick(fp, self._snapshots(),
                               self._inflight_map())
        if rid is None:
            raise AdmissionRejected("no_active_replicas",
                                    self.admission.retry_after())
        return self.replicas[rid]

    @staticmethod
    def tenant_of(body: Dict[str, Any]) -> str:
        # OpenAI bodies carry the end-user id in "user"; fall back to
        # a header-injected hint if the ingress put one in the body
        return str(body.get("user") or body.get("tenant") or "default")

    async def dispatch(self, method: str, body: Dict[str, Any]) -> Any:
        """Unary request through admission + routing."""
        await self.admission.acquire(self.tenant_of(body))
        try:
            st = self._route(body)
            st.inflight += 1
            st.requests_total += 1
            try:
                return await st.client.call(method, body)
            finally:
                st.inflight -= 1
        finally:
            self.admission.release()

    async def dispatch_stream(self, method: str, body: Dict[str, Any]
                              ) -> AsyncIterator[Any]:
        """Streaming request: admission + routing hold for the WHOLE
        stream (a live stream occupies a decode slot, so it must keep
        weighing in both the router's in-flight counts and the
        admission concurrency bound until it completes)."""
        await self.admission.acquire(self.tenant_of(body))
        try:
            st = self._route(body)
            st.inflight += 1
            st.requests_total += 1
            try:
                async for chunk in st.client.stream(method, body):
                    yield chunk
            finally:
                st.inflight -= 1
        finally:
            self.admission.release()

    # -- stats refresh --------------------------------------------------
    async def refresh(self) -> None:
        """Pull fleet_stats from every non-standby replica."""
        ids = self._ids(ACTIVE, DRAINING)

        async def one(rid: str):
            st = self.replicas[rid]
            try:
                stats = await asyncio.wait_for(
                    st.client.call("fleet_stats"), timeout=5.0)
            except Exception:
                return                       # keep the stale snapshot
            snap = ReplicaSnapshot.from_stats(stats)
            snap.replica = rid
            st.snapshot = snap
            st.slo_totals = dict(stats.get("slo_totals") or {})

        await asyncio.gather(*(one(rid) for rid in ids))

    # -- autoscaling ----------------------------------------------------
    def _window_metrics(self) -> FleetMetrics:
        """Fleet aggregates over the window since the last call:
        deltas of the cumulative TTFT/queue-wait sums each replica's
        telemetry summary exports (PR 5), plus live queue depths and
        the admission shed delta. Deltas are tracked PER REPLICA ID,
        not on a fleet sum over the changing ACTIVE/DRAINING set — a
        replica parking to STANDBY must not show up as a negative
        window, and a reactivated one must contribute only its growth
        since last seen, not its lifetime totals."""
        keys = ("ttft_s", "ttft_n", "queue_s", "queue_n")
        d = {k: 0.0 for k in keys}
        waiting = 0
        occ: List[float] = []
        for rid, st in self.replicas.items():
            if st.slo_totals:
                prev = self._prev_slo.get(rid, {})
                cur = {k: float(st.slo_totals.get(k, 0.0))
                       for k in keys}
                for k in keys:
                    # clamped: an engine restart resets its counters
                    d[k] += max(0.0, cur[k] - prev.get(k, 0.0))
                self._prev_slo[rid] = cur
            if st.snapshot is not None and st.status == ACTIVE:
                waiting += st.snapshot.waiting
                occ.append(st.snapshot.kv_occupancy)
        shed = (self.admission.shed_total
                + self.admission.rejected["queue_full"])
        shed_delta = shed - self._prev_shed
        self._prev_shed = shed
        return FleetMetrics(
            ttft_ms=(d["ttft_s"] / d["ttft_n"] * 1e3
                     if d["ttft_n"] > 0 else 0.0),
            queue_wait_ms=(d["queue_s"] / d["queue_n"] * 1e3
                           if d["queue_n"] > 0 else 0.0),
            waiting=waiting,
            occupancy=(sum(occ) / len(occ) if occ else 0.0),
            shed_delta=shed_delta)

    async def autoscale_tick(self, now: Optional[float] = None) -> int:
        """One control-loop iteration: refresh → decide → apply.
        Returns the applied target (also reachable at GET /fleet)."""
        await self.refresh()
        active = len(self._ids(ACTIVE))
        target = self.autoscaler.decide(self._window_metrics(),
                                        active, now)
        if target != active:
            self._apply_target(target)
        return target

    def _apply_target(self, target: int) -> None:
        active = self._ids(ACTIVE)
        if target > len(active):
            for rid in self._ids(STANDBY)[:target - len(active)]:
                self.replicas[rid].status = ACTIVE
                self._scale_events.append(
                    {"ts": time.time(), "event": "activate",
                     "replica": rid})
        elif target < len(active):
            # drain the emptiest replicas first: least in-flight work,
            # then least KV occupancy (cheapest caches to lose)
            def cost(rid: str):
                st = self.replicas[rid]
                occ = (st.snapshot.kv_occupancy
                       if st.snapshot is not None else 0.0)
                return (st.inflight, occ)

            for rid in sorted(active, key=cost)[:len(active) - target]:
                self._begin_drain(rid)
        self.router.set_replicas(self._ids(ACTIVE))

    def _begin_drain(self, rid: str) -> None:
        st = self.replicas[rid]
        st.status = DRAINING
        self._scale_events.append(
            {"ts": time.time(), "event": "drain_begin", "replica": rid})
        st.drain_task = asyncio.get_running_loop().create_task(
            self._drain_to_standby(rid))

    async def _drain_to_standby(self, rid: str,
                                timeout_s: float = 120.0) -> None:
        """Out of the ring already; wait for the router-side in-flight
        count to hit zero (every stream completed), then for the
        engine itself to run dry (the replica's drain() polls
        has_work(), which counts in-flight pipelined ticks and pending
        folds), then park."""
        st = self.replicas[rid]
        attempt = 0
        while True:
            deadline = time.monotonic() + timeout_s
            while st.inflight > 0 and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            drained = True
            try:
                rep = await st.client.call("drain", timeout_s)
                drained = bool((rep or {}).get("drained", True))
            except Exception:
                pass    # best-effort: the replica may not expose drain
            if st.inflight == 0 and drained:
                break
            # wedged: STAY DRAINING — out of the ring and ineligible
            # for reactivation (_apply_target only activates STANDBY)
            # — and retry; parking dirty would hand a replica known
            # unable to finish work back to the router on scale-up
            attempt += 1
            self._scale_events.append(
                {"ts": time.time(), "event": "drain_retry",
                 "replica": rid, "attempt": attempt})
            await asyncio.sleep(min(30.0, 2.0 * attempt))
        st.status = STANDBY
        self._scale_events.append(
            {"ts": time.time(), "event": "drain_done", "replica": rid,
             "clean": attempt == 0})

    # -- background control loop ---------------------------------------
    def start(self) -> None:
        """Start the refresh + autoscale loop on the current event
        loop (idempotent). Separate cadences: stats refresh keeps the
        router's view fresh; autoscale decisions run slower."""
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.get_running_loop().create_task(
                self._control_loop())

    async def stop(self) -> None:
        if self._loop_task is not None:
            self._loop_task.cancel()
            try:
                await self._loop_task
            except (asyncio.CancelledError, Exception):
                pass
            self._loop_task = None

    async def _control_loop(self) -> None:
        last_autoscale = 0.0
        while True:
            try:
                await self.refresh()
                now = time.monotonic()
                if now - last_autoscale >= self.autoscale_period_s:
                    last_autoscale = now
                    active = len(self._ids(ACTIVE))
                    target = self.autoscaler.decide(
                        self._window_metrics(), active)
                    if target != active:
                        self._apply_target(target)
            except asyncio.CancelledError:
                raise
            except Exception:
                import logging
                logging.getLogger(__name__).exception(
                    "fleet control loop iteration failed")
            await asyncio.sleep(self.refresh_period_s)

    # -- observability --------------------------------------------------
    async def metrics_text(self) -> str:
        """ONE valid Prometheus exposition for the whole fleet.

        Two registry topologies (the ISSUE 6 satellite):
        - shared registry (in-process replicas / local testing): every
          scrape renders the same process registry; each replica's
          engine tags its own series with its replica id, so the fleet
          scrapes every replica (each refreshes its own gauges) and
          keeps the LAST rendering — by then every replica's gauges
          are fresh in the shared registry.
        - separate registries (real replica actors): each exposition
          is scraped independently and relabeled with replica=<id> so
          identical series from different replicas cannot collide or
          silently sum in the merged document.
        """
        from ...util.metrics import merge_expositions, relabel_exposition

        ids = self._ids(ACTIVE, DRAINING)

        async def one(rid: str):
            st = self.replicas[rid]
            try:
                return (rid, st.client, await asyncio.wait_for(
                    st.client.call("metrics_text"), timeout=5.0))
            except Exception:
                return None     # a wedged replica can't black out
                                # the whole fleet's scrape

        texts = [t for t in await asyncio.gather(
            *(one(rid) for rid in ids)) if t is not None]
        if not texts:
            return "\n"
        if all(c.shares_registry for _, c, _ in texts):
            return texts[-1][2]
        return merge_expositions(
            [relabel_exposition(t, {"replica": rid})
             for rid, _, t in texts])

    async def status(self) -> Dict[str, Any]:
        """The GET /fleet document: routing inputs per replica,
        router/admission counters, last autoscale decision."""
        reps: Dict[str, Any] = {}
        for rid, st in self.replicas.items():
            snap = st.snapshot
            reps[rid] = {
                "status": st.status,
                "inflight": st.inflight,
                "requests_total": st.requests_total,
                **({} if snap is None else {
                    "active": snap.active,
                    "waiting": snap.waiting,
                    "kv_occupancy": round(snap.kv_occupancy, 4),
                    "free_pages": snap.free_pages,
                    "prefix_cache_hit_rate": round(
                        snap.cache_hit_rate, 4),
                    "last_tick_age_s": snap.last_tick_age_s,
                }),
            }
        return {
            "replicas": reps,
            "router": self.router.stats(),
            "admission": self.admission.stats(),
            "autoscale": {
                "min_replicas": self.autoscaler.config.min_replicas,
                "max_replicas": self.autoscaler.config.max_replicas,
                "active": len(self._ids(ACTIVE)),
                "draining": len(self._ids(DRAINING)),
                "standby": len(self._ids(STANDBY)),
                "last_decision": self.autoscaler.last_decision,
                "events": list(self._scale_events)[-32:],
            },
        }


__all__ = ["FleetManager", "LocalReplicaClient", "HandleReplicaClient",
           "ACTIVE", "DRAINING", "STANDBY"]
