"""Telemetry-driven fleet autoscaling with drain-before-downscale.

ISSUE 6: PR 5's request-lifecycle telemetry exists precisely so a
control loop can consume it. This policy scales the ACTIVE replica set
between min and max off the fleet's recent TTFT and queue-wait
aggregates (windowed deltas of the cumulative sums the telemetry
summary exports — not lifetime averages, which would never recover
after one bad minute) plus the admission controller's shed counter
(a shed request is the strongest "we are out of capacity" signal the
front door produces).

The policy only DECIDES a target size; FleetManager applies it. Scale
down never kills a replica with work in flight: the victim is removed
from the router ring first (no new requests), drains through the
engine's own has_work()/abort semantics, and is only retired once
idle — in-flight streams complete token-exact (the e2e test pins
this against a single-replica oracle).

Hysteresis mirrors serve's deployment autoscaler
(_private/controller.py autoscale_tick): a breach must persist for
upscale_delay_s before adding a replica, idleness for
downscale_delay_s before removing one, so one bursty tick cannot flap
the fleet.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional


@dataclasses.dataclass
class AutoscaleConfig:
    min_replicas: int = 1
    max_replicas: int = 1
    # scale-up triggers (recent-window aggregates)
    ttft_high_ms: float = 2000.0
    queue_wait_high_ms: float = 500.0
    # scale-down gate: ALL of these must hold
    queue_wait_low_ms: float = 50.0
    occupancy_low: float = 0.30
    # hysteresis
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 30.0
    # KV memory hierarchy (ISSUE 10): page pressure is (device pages
    # used + parked host pages) / usable pages — sustained demand past
    # this on the WORST replica means the fleet is oversubscribing its
    # KV (spill/restore churn taxes every affected stream), which the
    # TTFT mean can hide while streams still trickle; scale up
    page_pressure_high: float = 1.25


@dataclasses.dataclass
class FleetMetrics:
    """Windowed fleet aggregates (FleetManager computes the deltas)."""
    ttft_ms: float = 0.0            # recent-window mean TTFT
    queue_wait_ms: float = 0.0      # recent-window mean engine queue wait
    waiting: int = 0                # engine queues, fleet-wide, now
    occupancy: float = 0.0          # mean KV occupancy over active
    shed_delta: int = 0             # admission sheds/rejects this window
    # SLO burn-rate watchdog signal (ISSUE 7): paging means the fleet
    # is burning its error budget multi-window-confirmed — treated as
    # an instant breach so capacity is added BEFORE the SLO is blown
    slo_page: bool = False
    slo_burn: float = 0.0           # max confirmed burn across SLOs
    # KV page pressure (ISSUE 10): max over active replicas of
    # (device pages used + parked host pages) / usable pages
    page_pressure: float = 0.0
    # slice topology (ISSUE 17): chips behind each replica (a
    # tp-sharded engine on mesh_shape=(1, tp) spans tp chips).
    # Scaling is in whole-slice units: the decision below is still
    # denominated in replicas, but each +1/-1 provisions or releases
    # chips_per_slice chips at once.
    chips_per_slice: int = 1


class FleetAutoscaler:
    def __init__(self, config: Optional[AutoscaleConfig] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.config = config or AutoscaleConfig()
        # injectable clock (ISSUE 14): the hysteresis deltas only need
        # a monotone time source, so the discrete-event simulator can
        # drive decide() in virtual time; real fleets default to
        # time.monotonic (NTP-step immune, like the rest of the plane)
        self._clock = clock if clock is not None else time.monotonic
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        self.last_decision: Dict[str, Any] = {}

    def _breached(self, m: FleetMetrics, active: int) -> bool:
        c = self.config
        return (m.shed_delta > 0
                or m.slo_page                   # watchdog: pre-emptive
                or m.page_pressure > c.page_pressure_high   # ISSUE 10
                or m.ttft_ms > c.ttft_high_ms
                or m.queue_wait_ms > c.queue_wait_high_ms
                or m.waiting > active)      # >1 queued per replica

    def _idle(self, m: FleetMetrics) -> bool:
        c = self.config
        return (m.shed_delta == 0 and not m.slo_page
                and m.waiting == 0
                and m.page_pressure <= 1.0       # not oversubscribed
                and m.queue_wait_ms < c.queue_wait_low_ms
                and m.occupancy < c.occupancy_low)

    def decide(self, m: FleetMetrics, active: int,
               now: Optional[float] = None) -> int:
        """Target active-replica count, clamped to [min, max]."""
        c = self.config
        now = self._clock() if now is None else now
        target = active
        if self._breached(m, active):
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            if now - self._above_since >= c.upscale_delay_s:
                target = active + 1
                self._above_since = None
        elif self._idle(m):
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            if now - self._below_since >= c.downscale_delay_s:
                target = active - 1
                self._below_since = None
        else:
            self._above_since = self._below_since = None
        target = max(c.min_replicas, min(c.max_replicas, target))
        chips = max(int(m.chips_per_slice), 1)
        self.last_decision = {
            "ts": now, "active": active, "target": target,
            # chip-denominated view of the same decision (ISSUE 17):
            # one slice = chips_per_slice chips, scaled atomically
            "chips_per_slice": chips,
            "active_chips": active * chips,
            "target_chips": target * chips,
            "ttft_ms": round(m.ttft_ms, 3),
            "queue_wait_ms": round(m.queue_wait_ms, 3),
            "waiting": m.waiting,
            "occupancy": round(m.occupancy, 4),
            "shed_delta": m.shed_delta,
            "slo_page": m.slo_page,
            "slo_burn": round(m.slo_burn, 3),
            "page_pressure": round(m.page_pressure, 4),
        }
        return target


__all__ = ["AutoscaleConfig", "FleetAutoscaler", "FleetMetrics"]
