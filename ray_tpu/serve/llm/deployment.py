"""LLM fleet deployment: builder + OpenAI-compatible fleet ingress.

`build_llm_fleet_app(FleetConfig)` provisions `max_replicas`
LLMServer deployments (one engine each, distinct replica ids tagged
into their Prometheus series) behind ONE `LLMFleetIngressImpl` — the
deployment that owns the FleetManager (prefix-affine router, bounded
admission, autoscale control loop). Registering the app through
`serve.run` gives the fleet the controller's replica supervision and
the proxy's HTTP/SSE plane for free; `local_testing_mode=True` runs
the identical graph in-process (the tier-1 e2e tests do).

Ingress HTTP surface (rides the existing proxy):
    POST /v1/chat/completions      unary or SSE (stream=true)
    POST /v1/completions           unary or SSE
    POST /v1/batch                 submit a batch-lane job (ISSUE 14):
                                   priority-0, admission-exempt,
                                   preemptible bulk inference
    GET  /v1/batch                 list batch jobs (+ lane stats);
                                   /v1/batch/{id} = one job's results
    GET  /v1/models                the fleet's model (+ adapters)
    GET  /fleet                    fleet status: per-replica routing
                                   inputs, router/admission counters,
                                   autoscale decisions
    GET  /stats                    per-replica engine stats + fleet
    GET  /metrics                  ONE Prometheus exposition for the
                                   fleet (replica-tagged series)
    GET  /debug/events             per-replica flight recorders
                                   (?since=<seq> polls incrementally)
    GET  /debug/trace              merged Chrome-trace lifecycles
    GET  /fleet/debug/events       ingress+replica recorders merged
                                   (?since= returns only newer events
                                   + per-source high-water marks)
    GET  /fleet/debug/attribution  fleet-merged per-request cost
                                   receipts + tenant rollups
                                   (?k=&tenant= — ISSUE 13)
    GET  /fleet/debug/traffic      traffic recorder (ISSUE 20): ring
                                   tail + capture stats; ?capture=1
                                   downloads the last sealed capture
                                   (the replayable JSONL artifact)
    POST /fleet/debug/traffic      capture controls: {"action":
                                   "start"|"stop"|"mark", ...}
Overload returns 429 with a Retry-After header (admission.py).
"""

from __future__ import annotations

import asyncio
import dataclasses
import math
import time
from typing import Any, Dict, List, Optional, Tuple

from ...llm._internal.server import parse_since
from .admission import AdmissionConfig, AdmissionRejected
from .autoscaler import AutoscaleConfig
from .batch import BatchLaneConfig
from .failover import HealthConfig
from .fleet import (ACTIVE, DRAINING, STANDBY, FleetManager,
                    HandleReplicaClient)
from .kv_transport import REPLICA_ROLES, ROLE_PREFILL, TransportConfig
from .router import RouterConfig
from .tracemerge import merge_fleet_traces, merge_flight_recorders
from .trafficlog import CaptureError
from .watchdog import WatchdogConfig


@dataclasses.dataclass
class FleetConfig:
    """One model's replica fleet (wraps the single-replica LLMConfig)."""
    llm_config: Any                      # ray_tpu.llm.LLMConfig
    min_replicas: int = 1
    max_replicas: int = 1
    router: RouterConfig = dataclasses.field(default_factory=RouterConfig)
    admission: AdmissionConfig = dataclasses.field(
        default_factory=AdmissionConfig)
    autoscale: Optional[AutoscaleConfig] = None   # min/max come from above
    # SLO burn-rate watchdog (ISSUE 7): multi-window error-budget burn
    # over the replicas' slo_totals; pages pre-emptively into the
    # autoscaler and admission brownout
    watchdog: WatchdogConfig = dataclasses.field(
        default_factory=WatchdogConfig)
    # distributed request tracing (ISSUE 7): mint a trace context per
    # request at ingress; one trace id follows it across router and
    # replica (GET /fleet/debug/trace merges the spans)
    enable_tracing: bool = True
    # failure handling (ISSUE 9): probe-failure eviction thresholds,
    # circuit-breaker cooldowns, and the mid-stream failover budget
    health: HealthConfig = dataclasses.field(
        default_factory=HealthConfig)
    # named operation timeouts (ISSUE 9 satellite — replace the old
    # scattered 5.0/10.0 literals so chaos tests and operators can
    # tune them): probe = stats/metrics/debug fan-outs, dispatch =
    # control-plane unary calls, drain = scale-down engine drain
    probe_timeout_s: float = 5.0
    dispatch_timeout_s: float = 10.0
    drain_timeout_s: float = 120.0
    refresh_period_s: float = 0.5
    autoscale_period_s: float = 2.0
    # fleet KV transport (ISSUE 12): None = off (pre-transport fleet).
    # `replica_roles` aligns with r0..rN-1 ("prefill" | "decode" |
    # "mixed"; None = all mixed) — prefill replicas take long-prompt
    # handoffs only, never ring traffic. With a transport configured,
    # every replica's engine gets enable_kv_offload=True by default
    # (sessions park/restore through the host tier on both ends).
    transport: Optional[TransportConfig] = None
    replica_roles: Optional[List[str]] = None
    # preemptible batch-inference lane (ISSUE 14): None = off. With a
    # lane configured, POST /v1/batch submits priority-0 bulk jobs
    # that soak idle capacity and yield token-exact to interactive
    # traffic. Engines should run enable_kv_offload: the engine's
    # priority preemption is gated on it entirely, so WITHOUT it
    # batch work is never preempted at all — interactive requests
    # queue behind running batch jobs until they finish naturally,
    # and only the lane's soak governor (which stops LAUNCHING under
    # load) still protects interactive latency.
    batch_lane: Optional[BatchLaneConfig] = None
    # slice topology (ISSUE 17 / ROADMAP 4): the fleet's scaling UNIT
    # is a pod slice, not a chip. slice_shape=(1, tp) makes every
    # provisioned replica a tp-sharded engine on its own named mesh
    # (threaded into EngineConfig.mesh_shape), so a scale-up decision
    # provisions a whole tp-chip slice, /fleet rows report chips per
    # replica, and the fleet's capacity math is chip-denominated.
    # None = single-chip replicas (every pre-slice fleet unchanged).
    slice_shape: Optional[Tuple[int, int]] = None
    # traffic flight-data recorder (ISSUE 20): always-on bounded
    # request log at the ingress (privacy-scrubbed — never prompt
    # text); armed captures become replayable JSONL artifacts
    # (GET/POST /fleet/debug/traffic). The spool dir, when set,
    # retains sealed captures on disk (BlackboxSpool bounds).
    enable_traffic_log: bool = True
    traffic_capacity: int = 4096
    traffic_spool_dir: Optional[str] = None

    def resolved_autoscale(self) -> AutoscaleConfig:
        auto = self.autoscale or AutoscaleConfig()
        return dataclasses.replace(auto,
                                   min_replicas=self.min_replicas,
                                   max_replicas=self.max_replicas)

    def to_wire(self) -> Dict[str, Any]:
        return {
            "model_id": self.llm_config.model_id,
            "router": dataclasses.asdict(self.router),
            "admission": dataclasses.asdict(self.admission),
            "autoscale": dataclasses.asdict(self.resolved_autoscale()),
            "watchdog": dataclasses.asdict(self.watchdog),
            "enable_tracing": self.enable_tracing,
            "health": dataclasses.asdict(self.health),
            "probe_timeout_s": self.probe_timeout_s,
            "dispatch_timeout_s": self.dispatch_timeout_s,
            "drain_timeout_s": self.drain_timeout_s,
            "refresh_period_s": self.refresh_period_s,
            "autoscale_period_s": self.autoscale_period_s,
            "transport": (None if self.transport is None
                          else dataclasses.asdict(self.transport)),
            "replica_roles": (None if self.replica_roles is None
                              else list(self.replica_roles)),
            "batch_lane": (None if self.batch_lane is None
                           else dataclasses.asdict(self.batch_lane)),
            "slice_shape": (None if self.slice_shape is None
                            else list(self.slice_shape)),
            "enable_traffic_log": self.enable_traffic_log,
            "traffic_capacity": self.traffic_capacity,
            "traffic_spool_dir": self.traffic_spool_dir,
        }


class LLMFleetIngressImpl:
    """The fleet's front door (a serve deployment class body)."""

    def __init__(self, fleet_wire: Dict[str, Any], *server_handles):
        self.model_id = fleet_wire.get("model_id", "default")
        clients = []
        # handles arrive in bind order (r0..rN-1); in local testing
        # mode they resolve to in-process replicas that share THIS
        # process's metric registry, which flips the /metrics merge
        # strategy (fleet.py metrics_text)
        from .._private import local_testing
        shared = local_testing.active()
        for i, h in enumerate(server_handles):
            clients.append(HandleReplicaClient(
                f"r{i}", h, shares_registry=shared))
        wd_wire = dict(fleet_wire.get("watchdog") or {})
        if "slos" in wd_wire:           # JSON round-trip: list -> tuple
            wd_wire["slos"] = tuple(wd_wire["slos"])
        self.fleet = FleetManager(
            clients,
            router=RouterConfig(**fleet_wire.get("router") or {}),
            admission=AdmissionConfig(
                **fleet_wire.get("admission") or {}),
            autoscale=AutoscaleConfig(
                **fleet_wire.get("autoscale") or {}),
            watchdog=WatchdogConfig(**wd_wire),
            enable_tracing=bool(fleet_wire.get("enable_tracing", True)),
            health=HealthConfig(**fleet_wire.get("health") or {}),
            model_id=self.model_id,
            # fallbacks come from the dataclass, never re-stated
            # literals (the satellite that removed the scattered
            # 5.0/10.0 must not reintroduce them here)
            probe_timeout_s=fleet_wire.get(
                "probe_timeout_s", FleetConfig.probe_timeout_s),
            dispatch_timeout_s=fleet_wire.get(
                "dispatch_timeout_s", FleetConfig.dispatch_timeout_s),
            drain_timeout_s=fleet_wire.get(
                "drain_timeout_s", FleetConfig.drain_timeout_s),
            refresh_period_s=fleet_wire.get("refresh_period_s", 0.5),
            autoscale_period_s=fleet_wire.get("autoscale_period_s",
                                              2.0),
            roles=fleet_wire.get("replica_roles"),
            transport=(TransportConfig(**fleet_wire["transport"])
                       if fleet_wire.get("transport") else None),
            batch_lane=(BatchLaneConfig(**fleet_wire["batch_lane"])
                        if fleet_wire.get("batch_lane") else None),
            enable_traffic_log=bool(
                fleet_wire.get("enable_traffic_log", True)),
            traffic_capacity=int(
                fleet_wire.get("traffic_capacity", 4096)),
            traffic_spool_dir=fleet_wire.get("traffic_spool_dir"))
        self._adapters: Optional[List[str]] = None
        self._adapters_ts = 0.0

    # -- helpers --------------------------------------------------------
    def _429(self, exc: AdmissionRejected):
        """Admission rejections: 429 + Retry-After for overload; a
        request shed because its own deadline expired (ISSUE 9) is
        504 Gateway Timeout — retrying won't help a client whose
        budget is spent."""
        from ...serve import Response
        if exc.reason == "deadline":
            return Response(
                {"error": {"type": "deadline_exceeded",
                           "reason": exc.reason}},
                status=504, content_type="application/json")
        return Response(
            {"error": {"type": "overloaded",
                       "reason": exc.reason,
                       "retry_after_s": exc.retry_after_s}},
            status=429, content_type="application/json",
            headers={"Retry-After":
                     str(int(math.ceil(exc.retry_after_s)))})

    async def _known_model(self, name: str) -> bool:
        if not name or name == self.model_id:
            return True
        if name in (self._adapters or ()):
            return True
        # unknown name: (re)resolve — adapters can be registered live —
        # but at most once per cooldown, so an unknown-model storm
        # can't turn every request into a fleet-wide stats fan-out
        # (model_info snapshots engine stats under the step lock)
        now = time.monotonic()
        if self._adapters is None or now - self._adapters_ts >= 2.0:
            self._adapters_ts = now
            await self._resolve_adapters()
        return name in (self._adapters or ())

    async def _resolve_adapters(self) -> None:
        infos = await self._replica_infos()
        self._adapters = sorted(
            {a for info in infos.values()
             for a in info.get("adapters") or []})

    async def _replica_infos(self) -> Dict[str, Any]:
        return await self._fanout("model_info")

    async def _fanout(self, method: str, *args) -> Dict[str, Any]:
        """Call `method` on every non-standby replica concurrently,
        bounded: one wedged replica (step lock held mid-tick) degrades
        its row to an error instead of hanging the whole GET."""
        ids = [rid for rid, st in self.fleet.replicas.items()
               if st.status != STANDBY]

        async def one(rid: str):
            try:
                return rid, await asyncio.wait_for(
                    self.fleet.replicas[rid].client.call(
                        method, *args),
                    timeout=self.fleet.probe_timeout_s)
            except Exception as e:
                return rid, {"error": repr(e)}

        return dict(await asyncio.gather(*(one(rid) for rid in ids)))

    # -- GET surface ----------------------------------------------------
    async def _handle_get(self, norm: str,
                          query: Optional[Dict[str, str]] = None
                          ) -> Any:
        from ...serve import Response

        query = query or {}
        # preemptible batch lane (ISSUE 14): job listing + status
        if norm == "/v1/batch" or norm.startswith("/v1/batch/"):
            if self.fleet.batch is None:
                return Response(
                    {"error": "batch lane not configured"},
                    status=404, content_type="application/json")
            if norm == "/v1/batch":
                return {"object": "list",
                        "data": self.fleet.batch.list(),
                        "lane": self.fleet.batch.stats()}
            doc = self.fleet.batch.get(norm.rsplit("/", 1)[1])
            if doc is None:
                return Response({"error": "unknown batch job"},
                                status=404,
                                content_type="application/json")
            return doc
        if norm == "/v1/models":
            if self._adapters is None:
                await self._resolve_adapters()
            return {"object": "list",
                    "data": [{"id": self.model_id, "object": "model",
                              "owned_by": "ray_tpu"}]
                    + [{"id": a, "object": "model",
                        "owned_by": "ray_tpu",
                        "parent": self.model_id}
                       for a in self._adapters or []]}
        if norm == "/fleet":
            return await self.fleet.status()
        if norm == "/metrics":
            return Response(await self.fleet.metrics_text(),
                            status=200, content_type="text/plain")
        if norm == "/stats":
            infos = await self._replica_infos()
            return {"object": "stats", "model": self.model_id,
                    "replicas": {rid: info.get("engine", info)
                                 for rid, info in infos.items()},
                    "fleet": await self.fleet.status()}
        if norm == "/debug/events":
            # ?since=<seq> (ISSUE 20 satellite): each replica returns
            # only events newer than the cursor + its high-water mark
            since = parse_since(query.get("since"))
            rows = (await self._fanout("debug_events")
                    if since is None
                    else await self._fanout("debug_events", since))
            return {"object": "events", "replicas": rows}
        if norm == "/debug/trace":
            events: List[Any] = []
            for doc in (await self._fanout("debug_trace")).values():
                events.extend(doc.get("traceEvents") or [])
            return {"traceEvents": events, "displayTimeUnit": "ms"}
        # -- fleet-merged debug surface (ISSUE 7) ------------------------
        if norm == "/fleet/debug/trace":
            # time-aligned merge of every replica's Chrome trace with
            # the ingress's own span buffer; ?request_id= / ?trace_id=
            # narrow to one request's cross-process lifecycle
            return merge_fleet_traces(
                await self._fanout("debug_trace"), self.fleet.trace,
                request_id=query.get("request_id"),
                trace_id=query.get("trace_id"))
        if norm == "/fleet/debug/events":
            # ?since=<seq> polls incrementally. Sequence numbers are
            # PER SOURCE (each recorder counts its own), so the
            # scalar cursor applies to every source and the response
            # carries per-source high-water marks for the next poll.
            since = parse_since(query.get("since"))
            rows = (await self._fanout("debug_events")
                    if since is None
                    else await self._fanout("debug_events", since))
            high: Dict[str, Any] = {}
            by_rid: Dict[str, Any] = {}
            for rid, row in rows.items():
                if isinstance(row, dict) and "events" in row:
                    by_rid[rid] = row["events"]
                    high[rid] = row.get("high_water")
                else:
                    by_rid[rid] = row    # legacy list / error row
            merged = merge_flight_recorders(
                by_rid, self.fleet.recorder.events(since),
                request_id=query.get("request_id"))
            doc: Dict[str, Any] = {
                "object": "events", "events": merged,
                "ingress": self.fleet.recorder.stats()}
            if since is not None:
                high["ingress"] = doc["ingress"]["total"]
                doc["since"] = since
                doc["high_water"] = high
            return doc
        if norm == "/fleet/debug/traffic":
            # ISSUE 20 traffic recorder: stats + ring tail;
            # ?capture=1 downloads the last sealed capture bytes
            tl = self.fleet.traffic
            if query.get("capture"):
                try:
                    text = tl.export()
                except CaptureError as e:
                    return Response({"error": str(e)}, status=404,
                                    content_type="application/json")
                return Response(text, status=200,
                                content_type="text/plain")
            try:
                n = max(int(query.get("n") or 64), 1)
            except ValueError:
                n = 64
            return {"object": "traffic", "model": self.model_id,
                    "enabled": self.fleet.enable_traffic_log,
                    "stats": tl.stats(),
                    "records": tl.tail(
                        n, since=parse_since(query.get("since")))}
        if norm == "/fleet/debug/attribution":
            # ISSUE 13: fleet-merged cost attribution — every
            # replica's top receipts re-ranked into ONE top-K and the
            # tenant rollups summed fleet-wide (?k= bounds the list;
            # ?tenant= filters the receipt rows)
            per = await self._fanout("debug_attribution")
            try:
                k = max(int(query.get("k") or 8), 1)
            except ValueError:
                k = 8
            want_tenant = query.get("tenant")
            tenants: Dict[str, Dict[str, float]] = {}
            top: List[Dict[str, Any]] = []
            for rid, doc in sorted(per.items()):
                if not isinstance(doc, dict) or "error" in doc:
                    continue
                for row in doc.get("top") or []:
                    if want_tenant and row.get("tenant") != want_tenant:
                        continue
                    top.append({**row, "replica": rid})
                for t, v in (doc.get("tenants") or {}).items():
                    agg = tenants.setdefault(t, {})
                    for key, val in v.items():
                        agg[key] = agg.get(key, 0) + val
            top.sort(key=lambda r: (-r.get("flops", 0),
                                    r.get("request_id", "")))
            return {"object": "attribution", "model": self.model_id,
                    "top": top[:k], "tenants": tenants,
                    "replicas": per}
        if norm == "/fleet/debug/bundles":
            # list every replica's black-box spool; ?replica=&id=
            # fetches one bundle
            rid, bid = query.get("replica"), query.get("id")
            if rid and bid:
                st = self.fleet.replicas.get(rid)
                if st is None:
                    return Response(
                        {"error": f"unknown replica {rid!r}"},
                        status=404, content_type="application/json")
                try:
                    # bounded like every other replica fan-out: a
                    # wedged replica (step lock held — often exactly
                    # why its bundle is wanted) degrades, not hangs
                    bundle = await asyncio.wait_for(
                        st.client.call("debug_bundle", bid),
                        timeout=self.fleet.probe_timeout_s)
                except Exception as e:
                    return Response(
                        {"error": f"bundle fetch from {rid} failed: "
                                  f"{e!r}"},
                        status=504, content_type="application/json")
                if bundle is None:
                    return Response(
                        {"error": f"no bundle {bid!r} on {rid}"},
                        status=404, content_type="application/json")
                return bundle
            return {"object": "bundles",
                    "replicas": await self._fanout("debug_bundles")}
        return Response({"error": f"no route {norm}"}, status=404,
                        content_type="application/json")

    # -- request path ---------------------------------------------------
    async def __call__(self, request) -> Any:
        from ...serve import Response, StreamingHint

        self.fleet.start()       # control loop rides the serving loop
        path = getattr(request, "path", "/")
        method = getattr(request, "method", "POST")
        norm = path.rstrip("/") or "/"
        if method == "GET":
            return await self._handle_get(
                norm, dict(getattr(request, "query_params", None)
                           or {}))
        try:
            body = request.json()
        except Exception:
            return Response({"error": "invalid JSON body"}, status=400,
                            content_type="application/json")
        if not isinstance(body, dict):
            body = {}
        if norm == "/debug/dump":
            # POST /debug/dump: black-box every replica now
            cause = str(body.get("cause") or "manual")
            return {"object": "dump",
                    "replicas": await self.fleet.debug_dump_all(cause)}
        if norm == "/fleet/debug/traffic":
            # ISSUE 20 capture controls. Control misuse (double
            # start, stop with nothing armed) is a 409 with the typed
            # error's message — never a 500.
            action = str(body.get("action") or "")
            tl = self.fleet.traffic
            try:
                if action == "start":
                    out = tl.start_capture(
                        str(body.get("note") or ""))
                elif action == "stop":
                    out = tl.stop_capture()
                elif action == "mark":
                    out = tl.mark(str(body.get("label") or ""))
                else:
                    return Response(
                        {"error": f"unknown traffic action "
                                  f"{action!r} (start|stop|mark)"},
                        status=400,
                        content_type="application/json")
            except CaptureError as e:
                return Response({"error": str(e)}, status=409,
                                content_type="application/json")
            return {"object": "traffic_control", "action": action,
                    **out}
        if norm == "/v1/batch" or (norm.startswith("/v1/batch/")
                                   and norm.endswith("/cancel")):
            # preemptible batch lane (ISSUE 14): submit a bulk job —
            # returns the job brief immediately; the lane pump soaks
            # it through idle capacity at priority 0. POST
            # /v1/batch/{id}/cancel stops its unlaunched requests.
            if self.fleet.batch is None:
                return Response(
                    {"error": "batch lane not configured"},
                    status=404, content_type="application/json")
            if norm != "/v1/batch":
                doc = self.fleet.batch.cancel(norm.split("/")[-2])
                if doc is None:
                    return Response({"error": "unknown batch job"},
                                    status=404,
                                    content_type="application/json")
                return doc
            try:
                return self.fleet.batch.submit(body)
            except ValueError as e:
                return Response({"error": str(e)}, status=400,
                                content_type="application/json")
        if not await self._known_model(body.get("model") or ""):
            return Response(
                {"error": f"model {body.get('model')!r} not found"},
                status=404, content_type="application/json")
        is_chat = norm.endswith("/chat/completions")
        is_cmpl = not is_chat and norm.endswith("/completions")
        if not (is_chat or is_cmpl):
            return Response({"error": f"no route {path}"}, status=404,
                            content_type="application/json")
        if body.get("stream"):
            # preflight the front door so a flat-out overloaded fleet
            # answers 429 instead of opening a 200 SSE stream only to
            # shed inside it (a shed after headers can only be an SSE
            # error event — see the stream_* methods)
            if self.fleet.admission.would_reject():
                return self._429(AdmissionRejected(
                    "queue_full", self.fleet.admission.retry_after()))
            return StreamingHint(
                "stream_chat" if is_chat else "stream_completions",
                body)
        try:
            return await self.fleet.dispatch(
                "chat" if is_chat else "completions", body)
        except AdmissionRejected as e:
            return self._429(e)

    async def _relay(self, method: str, body: Dict[str, Any]):
        import json
        self.fleet.start()
        try:
            async for chunk in self.fleet.dispatch_stream(method, body):
                yield chunk
        except (GeneratorExit, asyncio.CancelledError):
            raise                      # client gone: nothing to frame
        except AdmissionRejected as e:
            # headers are already on the wire: the rejection becomes
            # an SSE error event (the OpenAI streaming convention).
            # Same distinction as _429: a deadline shed is the
            # client's budget spent (no Retry-After — retrying won't
            # help), anything else is overload.
            if e.reason == "deadline":
                err = {"type": "deadline_exceeded", "reason": e.reason}
            else:
                err = {"type": "overloaded", "reason": e.reason,
                       "retry_after_s": e.retry_after_s}
            yield "data: " + json.dumps({"error": err}) + "\n\n"
            yield "data: [DONE]\n\n"
        except Exception as e:
            # failover budget exhausted / every replica down (ISSUE
            # 9): the stream must still END per the SSE convention —
            # an error event + [DONE] — never a silent truncation a
            # client can't tell from a transport blip. The terminal
            # cause goes to the log + fleet flight recorder (the SSE
            # event only names the type; the operator needs the rest)
            import logging
            logging.getLogger(__name__).exception(
                "fleet stream %s failed terminally", method)
            self.fleet.recorder.record(
                "stream_failed", method=method, error=repr(e))
            yield "data: " + json.dumps(
                {"error": {"type": "upstream_failure",
                           "reason": type(e).__name__}}) + "\n\n"
            yield "data: [DONE]\n\n"

    async def stream_chat(self, body: Dict[str, Any]):
        async for chunk in self._relay("chat_stream", body):
            yield chunk

    async def stream_completions(self, body: Dict[str, Any]):
        async for chunk in self._relay("completions_stream", body):
            yield chunk

    async def check_health(self) -> None:
        return None

    async def health_detail(self) -> Dict[str, Any]:
        """serve.status() row for the ingress itself: fleet shape +
        front-door pressure (the per-engine rows come from each
        LLMServer replica's own health_detail)."""
        f = self.fleet
        adm = f.admission
        return {
            "model": self.model_id,
            "active": len(f._ids(ACTIVE)),
            "draining": len(f._ids(DRAINING)),
            "standby": len(f._ids(STANDBY)),
            "inflight": adm.inflight,
            "queued": adm._queue_len(),
            "queue_wait_p99_s": round(adm.queue_wait_p99_s(), 4),
        }


def build_llm_fleet_app(config: FleetConfig):
    """FleetConfig → bound serve Application (deploy via serve.run)."""
    import dataclasses as _dc

    from ... import serve
    from ...llm import build_llm_deployment

    lc = config.llm_config
    if config.min_replicas < 1 \
            or config.max_replicas < config.min_replicas:
        raise ValueError("need 1 <= min_replicas <= max_replicas")
    roles = config.replica_roles
    if roles is not None:
        if len(roles) != config.max_replicas:
            raise ValueError(
                f"replica_roles ({len(roles)}) must align with "
                f"max_replicas ({config.max_replicas})")
        bad = [r for r in roles if r not in REPLICA_ROLES]
        if bad:
            raise ValueError(f"unknown replica roles {bad}")
        if roles.count(ROLE_PREFILL) == len(roles):
            raise ValueError("a fleet needs at least one "
                             "decode-capable replica")
    servers = []
    for i in range(config.max_replicas):
        rid = f"r{i}"
        ek = dict(lc.engine_kwargs or {})
        # the replica id tags this engine's Prometheus series (and is
        # how LLMServerImpl learns its own identity)
        ek["metrics_replica_id"] = rid
        if config.slice_shape is not None:
            # every replica IS one slice: a tp-sharded engine on its
            # own named mesh (explicit unless the operator pinned a
            # per-replica mesh themselves)
            ek.setdefault("mesh_shape", tuple(config.slice_shape))
        if config.transport is not None:
            # both ends of a session ship stage through the host
            # tier (export parks via the spill path, import restores
            # via _restore_parked) — default it ON fleet-wide unless
            # the operator pinned it explicitly
            ek.setdefault("enable_kv_offload", True)
        dep_cfg = dict(lc.deployment_config or {})
        dep_cfg["name"] = f"LLMServer:{lc.model_id}:{rid}"
        servers.append(build_llm_deployment(
            _dc.replace(lc, engine_kwargs=ek,
                        deployment_config=dep_cfg)))
    ingress = serve.deployment(
        name=f"LLMFleet:{lc.model_id}",
        max_ongoing_requests=max(
            256, config.admission.max_concurrent
            + config.admission.max_queue))(LLMFleetIngressImpl)
    return ingress.bind(config.to_wire(), *servers)


__all__ = ["FleetConfig", "LLMFleetIngressImpl", "build_llm_fleet_app"]
