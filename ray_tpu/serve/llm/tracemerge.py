"""Fleet trace assembly: ingress span buffer + cross-replica merge.

ISSUE 7, the "one trace id per request" half. The fleet ingress mints
a trace context per request (util.tracing ids) and records its own
side of the story — admission wait, routing decision, end-to-end span
— into a bounded IngressTraceBuffer as Chrome-trace events. Each
replica's engine telemetry renders the same request's lifecycle spans
tagged with the SAME trace id (the context rides the request body) and
emits the Perfetto flow-finish bound to the ingress's flow-start, so
the merged document draws an arrow from the routing decision into the
replica's prefill/decode spans.

`merge_fleet_traces` is the `GET /fleet/debug/trace` backend: it
time-aligns (every source renders monotonic stamps through its own
process wall anchor into epoch microseconds), dedups the shared
process tracing ring (in-process replicas each merge the same ring
into their doc), applies `?request_id=` / `?trace_id=` filters, and
carries per-source metadata — including each ring's dropped-event
count — so a truncated trace is legible as truncated.
"""

from __future__ import annotations

import itertools
import json
import os
import time
from typing import Any, Dict, List, Optional

from ...util import tracing

_INGRESS_RING = 4096        # ingress trace events retained


class IngressTraceBuffer:
    """Bounded ring of Chrome-trace events recorded at the fleet
    ingress (one tid per request; thread_name metadata rows included).
    Storage is the shared tracing.BoundedRing — same displacement
    accounting as the process tracing ring."""

    def __init__(self, capacity: int = _INGRESS_RING):
        self._ring = tracing.BoundedRing(capacity)
        self._tid = itertools.count(1)

    def next_tid(self) -> int:
        return next(self._tid)

    def add(self, *events: Dict[str, Any]) -> None:
        self._ring.append(*events)

    def events(self) -> List[Dict[str, Any]]:
        return self._ring.items()

    def stats(self) -> Dict[str, int]:
        return self._ring.stats()


def request_events(tid: int, rid: str, trace: Dict[str, str],
                   t_queued: float, t_admitted: Optional[float],
                   t_routed: Optional[float], t_done: float,
                   replica: Optional[str], outcome: Optional[str],
                   method: str, tenant: str, status: str
                   ) -> List[Dict[str, Any]]:
    """Build the ingress-side Chrome events for ONE completed request
    (monotonic inputs; rendered epoch-aligned via the process anchor).
    The routing-decision span carries the Perfetto flow-start whose
    matching finish the replica's telemetry emits."""
    pid = os.getpid()
    wall = tracing.mono_to_epoch
    args = {"request_id": rid, "trace_id": trace["trace_id"]}
    evs: List[Dict[str, Any]] = [
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
         "args": {"name": f"ingress {rid}"}},
        tracing.complete_event(
            "fleet_request", "fleet", wall(t_queued),
            t_done - t_queued, pid=pid, tid=tid,
            args={**args, "method": method, "tenant": tenant,
                  "status": status,
                  **({"replica": replica} if replica else {})}),
    ]
    if t_admitted is not None:
        evs.append(tracing.complete_event(
            "admission_wait", "fleet", wall(t_queued),
            t_admitted - t_queued, pid=pid, tid=tid, args=dict(args)))
    if t_routed is not None and replica is not None:
        t0 = t_admitted if t_admitted is not None else t_queued
        evs.append(tracing.complete_event(
            "routing_decision", "fleet", wall(t0),
            max(t_routed - t0, 1e-6), pid=pid, tid=tid,
            args={**args, "replica": replica,
                  **({"outcome": outcome} if outcome else {})}))
        # flow-start INSIDE the routing span (same pid/tid/ts): the
        # replica's flow-finish ("f", bp="e") binds the arrow to its
        # request row
        evs.append({"name": "route", "cat": "flow", "ph": "s",
                    "id": trace["flow_id"], "ts": wall(t0) * 1e6,
                    "pid": pid, "tid": tid, "args": dict(args)})
    return evs


def filter_trace(events: List[Dict[str, Any]],
                 request_id: Optional[str] = None,
                 trace_id: Optional[str] = None
                 ) -> List[Dict[str, Any]]:
    """Keep events belonging to one request/trace. Matching is on the
    args payload (every fleet-traced event — spans, instants, flow
    endpoints — carries request_id and trace_id there); thread_name
    metadata rows are kept for exactly the (pid, tid) rows that still
    own a kept event, so the filtered doc renders with its labels."""
    if request_id is None and trace_id is None:
        return list(events)

    def match(ev: Dict[str, Any]) -> bool:
        args = ev.get("args") or {}
        if request_id is not None \
                and args.get("request_id") != request_id:
            return False
        if trace_id is not None and args.get("trace_id") != trace_id:
            return False
        return True

    kept = [ev for ev in events if ev.get("ph") != "M" and match(ev)]
    rows = {(ev.get("pid"), ev.get("tid")) for ev in kept}
    meta = [ev for ev in events if ev.get("ph") == "M"
            and (ev.get("pid"), ev.get("tid")) in rows]
    return meta + kept


def _dedup(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Drop exact-duplicate events: in-process replicas each merge the
    SAME process tracing ring into their chrome_trace doc, so a naive
    fleet concatenation repeats every ring span once per replica."""
    seen = set()
    out: List[Dict[str, Any]] = []
    for ev in events:
        key = json.dumps(ev, sort_keys=True, default=repr)
        if key not in seen:
            seen.add(key)
            out.append(ev)
    return out


def merge_fleet_traces(replica_docs: Dict[str, Any],
                       ingress: Optional[IngressTraceBuffer] = None,
                       request_id: Optional[str] = None,
                       trace_id: Optional[str] = None
                       ) -> Dict[str, Any]:
    """Assemble the fleet-wide Chrome trace (GET /fleet/debug/trace):
    every replica's lifecycle doc + the ingress span buffer, deduped,
    optionally filtered to one request or trace id. Events are
    already time-aligned — each source stamps epoch microseconds
    through its own process wall anchor — so the merge is a
    concatenation plus bookkeeping, and per-source metadata (anchors,
    ring drop counts) rides along for skew forensics."""
    events: List[Dict[str, Any]] = []
    meta: Dict[str, Any] = {}
    if ingress is not None:
        events.extend(ingress.events())
        meta["ingress"] = {
            "pid": os.getpid(),
            "wall_anchor_s": tracing.wall_anchor(),
            "buffer": ingress.stats(),
        }
    per_replica: Dict[str, Any] = {}
    source_pids: List[Any] = []
    for rid in sorted(replica_docs):
        doc = replica_docs[rid]
        if not isinstance(doc, dict):
            per_replica[rid] = {"error": repr(doc)}
            continue
        if "error" in doc and "traceEvents" not in doc:
            per_replica[rid] = {"error": doc["error"]}
            continue
        events.extend(doc.get("traceEvents") or [])
        per_replica[rid] = doc.get("metadata") or {}
        source_pids.append((doc.get("metadata") or {}).get("pid"))
    meta["replicas"] = per_replica
    # duplicates exist only when replica docs came from ONE process
    # (each merged the same tracing ring); cross-process fleets — the
    # production topology — skip the O(events) canonical-JSON pass
    if (len(source_pids) != len(set(source_pids))
            or any(p is None for p in source_pids)):
        events = _dedup(events)
    if request_id is not None or trace_id is not None:
        events = filter_trace(events, request_id=request_id,
                              trace_id=trace_id)
        meta["filter"] = {
            **({"request_id": request_id} if request_id else {}),
            **({"trace_id": trace_id} if trace_id else {}),
        }
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": meta}


def merge_flight_recorders(replica_events: Dict[str, Any],
                           ingress_events: List[Dict[str, Any]],
                           request_id: Optional[str] = None
                           ) -> List[Dict[str, Any]]:
    """One time-ordered fleet event stream (GET /fleet/debug/events):
    every replica's flight-recorder ring plus the ingress's own,
    each event tagged with its source, sorted by timestamp (epoch
    via per-process anchors), optionally filtered by request id."""
    merged: List[Dict[str, Any]] = []
    for rid in sorted(replica_events):
        evs = replica_events[rid]
        if not isinstance(evs, list):
            merged.append({"ts": time.time(), "replica": rid,
                           "event": "collect_error",
                           "error": repr(evs)})
            continue
        for ev in evs:
            merged.append({**ev, "replica": rid})
    for ev in ingress_events:
        merged.append({**ev, "replica": "ingress"})
    if request_id is not None:
        merged = [ev for ev in merged
                  if ev.get("request_id") == request_id]
    merged.sort(key=lambda ev: ev.get("ts", 0.0))
    return merged


__all__ = ["IngressTraceBuffer", "request_events", "filter_trace",
           "merge_fleet_traces", "merge_flight_recorders"]
