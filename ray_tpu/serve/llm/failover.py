"""Failure-handling plane for the serving fleet (ISSUE 9).

The fleet built in ISSUEs 6–8 routes, sheds, autoscales, and observes —
but treated every replica as immortal: a dead or hung replica kept its
ring slot (stale snapshot forever) and killed every stream routed to
it. This module holds the pieces that make replica loss a routine
latency blip instead (the reference Ray's core promise — actor death
is detected and recovered, not propagated to clients; RLAX makes the
same argument for preemptible TPU pods):

- `CircuitBreaker` + `HealthConfig` — the per-replica health state
  machine the FleetManager's refresh loop drives: consecutive probe
  failures/timeouts open the breaker (the replica is EVICTED from the
  router ring immediately), a cooldown later one half-open probe at a
  time decides re-admission, and repeated trips back the cooldown off
  exponentially. Breaker state is exported as a gauge
  (`ray_tpu_llm_breaker_state`: 0 closed / 1 open / 2 half-open).

- `StreamTranscript` + `continuation_body` — token-exact mid-stream
  failover. The fleet consumes each replica's token-structured stream
  (`*_stream_tokens`: token ids + text per chunk, globally indexed),
  folds chunks through the transcript (dedup by token index →
  exactly-once delivery), and on a replica failure re-dispatches the
  ORIGINAL prompt with the already-delivered tokens appended
  (`_continue_tokens`), `max_tokens` decremented and the token index
  offset. The per-request sampling seed (pinned on the body at
  ingress) keys every token's sample by its ABSOLUTE index
  (engine `_row_sample_keys`), so greedy AND sampled continuations are
  token-exact; the prefix cache makes the re-prefill cheap.

- fleet failure metrics — `failovers_total`,
  `replica_evictions_total`, `breaker_state`, `deadline_sheds_total`
  (registered idempotently in the ingress process registry, riding
  the fleet /metrics scrape like the watchdog gauges).

Everything here is host-side control-plane Python: no jax, no device
work — the dispatch-guard suite runs with the whole plane active.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ...llm._internal.engine import derive_seed
from ...llm._internal.server import DEFAULT_MAX_TOKENS  # noqa: F401
from ...util import metrics as metrics_api

# fleet stream method -> the replica's token-structured twin
TOKEN_STREAM_METHODS = {
    "chat_stream": "chat_stream_tokens",
    "completions_stream": "completions_stream_tokens",
}

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"
_BREAKER_GAUGE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

# exception types a replica raises for a BAD REQUEST (malformed
# sampling params, unknown LoRA adapter, prompt over max_seq): the
# request caused them deterministically, so they must neither feed
# the breaker (one poisoned body would evict healthy replicas ring
# by ring) nor be retried (the re-dispatch would fail identically).
# Deliberately narrow: a replica-internal KeyError/AttributeError is
# a replica bug and MUST keep feeding the breaker.
REQUEST_FAULT_TYPES = (ValueError, TypeError)


def is_request_fault(exc: BaseException) -> bool:
    return isinstance(exc, REQUEST_FAULT_TYPES)


async def close_quietly(gen: Any, timeout_s: float = 1.0) -> None:
    """Best-effort aclose of a replica-side async generator (the ONE
    close-a-stream idiom — fleet relay and chaos wrappers share it):
    closing tells the replica its client is gone, so it aborts the
    engine request instead of decoding to nobody until the 300 s
    queue timeout. Bounded: a wedged replica must not hang the
    closer."""
    close = getattr(gen, "aclose", None)
    if close is None:
        return
    try:
        await asyncio.wait_for(close(), timeout=timeout_s)
    except Exception:
        pass


@dataclasses.dataclass
class HealthConfig:
    """Failure detection + failover policy (FleetConfig.health)."""
    # consecutive probe failures/timeouts that open the breaker and
    # evict the replica from the router ring
    probe_failures: int = 3
    # open -> half-open cooldown before the first re-admission probe;
    # repeated trips multiply it (bounded), so a flapping replica
    # spends progressively longer out of the ring
    open_cooldown_s: float = 2.0
    cooldown_backoff: float = 2.0
    max_cooldown_s: float = 30.0
    # consecutive half-open probe successes that close the breaker
    # and re-admit the replica
    half_open_probes: int = 2
    # a hard dispatch/stream failure trips the breaker immediately
    # (a severed stream is a stronger death signal than a slow probe)
    fail_fast_on_dispatch: bool = True
    # bounded mid-stream re-dispatches per client stream (and unary
    # retries per request)
    max_failovers: int = 2
    # a live stream that produces NO chunk for this long is a HUNG
    # replica (the ISSUE 9 motivating case: hangs, not just crashes —
    # a healthy engine emits a token every tick, ms-scale): the relay
    # treats the stall as a failure and fails over. Generous default:
    # it must clear first-token latency under load (queueing +
    # prefill + cold compiles).
    stream_stall_timeout_s: float = 60.0
    # grace past a unary request's deadline before the ingress stops
    # waiting on the replica (a healthy engine sheds at a fold
    # boundary well inside it; the timeout firing means the replica
    # is hung or badly behind). Generous for the same cold-compile
    # reason as the stall timeout — and the resulting TimeoutError
    # feeds the breaker SOFTLY (threshold-counted, never an instant
    # trip): tight client deadlines must not evict healthy replicas.
    unary_deadline_grace_s: float = 10.0


class CircuitBreaker:
    """Per-replica closed → open → half-open state machine.

    The refresh loop is the driver: `should_probe()` gates whether
    this cycle probes the replica at all (an OPEN breaker inside its
    cooldown is left alone; past it, the breaker half-opens and admits
    exactly the probes that decide recovery), then `record_success` /
    `record_failure` move the state. Failure paths outside the probe
    loop (dispatch errors, severed streams) feed `record_failure`
    with hard=True."""

    def __init__(self, config: Optional[HealthConfig] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.config = config or HealthConfig()
        # injectable clock (ISSUE 14): cooldown timing runs on
        # whatever monotone source the driver provides — the fleet
        # simulator's chaos overlays exercise open/half-open/close in
        # virtual time through it
        self._clock = clock if clock is not None else time.monotonic
        self.state = CLOSED
        self.failures = 0            # consecutive
        self.trips = 0               # lifetime opens
        self.opened_at = 0.0
        self._half_ok = 0

    def cooldown_s(self) -> float:
        c = self.config
        return min(c.max_cooldown_s,
                   c.open_cooldown_s
                   * (c.cooldown_backoff ** max(self.trips - 1, 0)))

    def should_probe(self, now: Optional[float] = None) -> bool:
        if self.state != OPEN:
            return True
        now = self._clock() if now is None else now
        if now - self.opened_at >= self.cooldown_s():
            self.state = HALF_OPEN
            self._half_ok = 0
            return True
        return False

    def record_success(self, now: Optional[float] = None) -> bool:
        """One healthy probe. Returns True when it CLOSED the breaker
        (the caller re-admits the replica)."""
        self.failures = 0
        if self.state == CLOSED:
            return False
        # a success can only arrive through a half-open probe; treat a
        # stray OPEN success the same way
        if self.state == OPEN:
            self.state = HALF_OPEN
            self._half_ok = 0
        self._half_ok += 1
        if self._half_ok >= self.config.half_open_probes:
            self.state = CLOSED
            self._half_ok = 0
            return True
        return False

    def record_failure(self, now: Optional[float] = None,
                       hard: bool = False) -> bool:
        """One failed probe/dispatch. Returns True when it OPENED the
        breaker (the caller evicts the replica)."""
        now = self._clock() if now is None else now
        self.failures += 1
        if self.state == HALF_OPEN or (
                self.state == CLOSED
                and (hard or self.failures
                     >= self.config.probe_failures)):
            self.state = OPEN
            self.trips += 1
            self.opened_at = now
            self._half_ok = 0
            return True
        return False

    def gauge(self) -> int:
        return _BREAKER_GAUGE[self.state]

    def stats(self) -> Dict[str, Any]:
        return {"state": self.state, "failures": self.failures,
                "trips": self.trips,
                "cooldown_s": round(self.cooldown_s(), 3)}


class StreamBroken(RuntimeError):
    """A replica's token stream ended without a finish chunk — the
    transport died quietly; the fleet treats it like any failure."""


class StreamStalled(RuntimeError):
    """A replica's token stream produced no chunk within the stall
    timeout — the replica hung (wedged event loop / stuck device
    call); the fleet fails the attempt over."""


class StreamTranscript:
    """The client-visible token transcript of ONE logical stream,
    across however many replica attempts served it. `fold()` dedups
    replica chunks by global token index, so the client sees
    exactly-once delivery: tokens the dead replica generated but never
    shipped are regenerated by the continuation (token-exact, same
    seed), and anything replayed is dropped here."""

    def __init__(self):
        self.tokens: List[int] = []
        self.finished = False
        self.reason: Optional[str] = None

    def fold(self, chunk: Dict[str, Any]):
        """-> (new_tokens, text_delta, finished, reason) | None."""
        n = len(self.tokens)
        i = int(chunk.get("i", n))
        toks = list(chunk.get("toks") or [])
        fin = bool(chunk.get("finished"))
        if i + len(toks) <= n and not fin:
            return None                  # wholly replayed chunk
        if i < n:
            # partial overlap — defensive only: continuations start
            # exactly at the transcript head by construction. The
            # text delta is indivisible, so it is dropped with the
            # replayed tokens.
            toks = toks[n - i:]
            text = ""
        else:
            text = chunk.get("text") or ""
        self.tokens.extend(toks)
        reason = chunk.get("reason")
        if fin:
            self.finished, self.reason = True, reason
        return toks, text, fin, reason


def pin_stream_identity(body: Dict[str, Any]) -> None:
    """Pin everything a continuation must replay exactly, BEFORE the
    first dispatch: an explicit max_tokens (so it can be decremented)
    and the per-request sampling seed (derived from the minted request
    id — the engine would derive the same one, but the continuation
    may land under a different engine request id, so the fleet pins it
    on the body where it survives the hop)."""
    body["max_tokens"] = int(body.get("max_tokens")
                             or DEFAULT_MAX_TOKENS)
    if body.get("seed") is None:
        body["seed"] = derive_seed(
            str(body.get("_request_id") or uuid.uuid4().hex))


def continuation_body(body: Dict[str, Any],
                      transcript: StreamTranscript) -> Dict[str, Any]:
    """The re-dispatch body for a severed stream: original prompt
    (the replica re-encodes it) + delivered tokens appended, token
    indices offset, max_tokens decremented. Seed and deadline ride
    the copied body unchanged."""
    out = dict(body)
    done = len(transcript.tokens)
    out["_continue_tokens"] = list(transcript.tokens)
    out["_token_offset"] = done
    out["max_tokens"] = max(
        int(body.get("max_tokens") or DEFAULT_MAX_TOKENS) - done, 1)
    return out


def sse_chunk(chat: bool, cid: str, model: str, created: int,
              text: str, finished: bool, reason: Optional[str],
              token_ids: List[int]) -> str:
    """One OpenAI-format SSE chunk rendered at the INGRESS (the fleet
    owns the SSE framing so a mid-stream failover keeps one stable
    completion id — no restart is client-visible except latency).
    `token_ids` is a vLLM-style extension: the emitted ids, so
    failover-aware clients (and the chaos gates) can assert
    token-exactness without re-tokenizing text."""
    if chat:
        doc = {
            "id": cid, "object": "chat.completion.chunk",
            "created": created, "model": model,
            "choices": [{
                "index": 0,
                "delta": ({"content": text} if text else {}),
                "finish_reason": reason if finished else None,
                "token_ids": list(token_ids),
            }],
        }
    else:
        doc = {
            "id": cid, "object": "text_completion",
            "created": created, "model": model,
            "choices": [{
                "index": 0, "text": text,
                "finish_reason": reason if finished else None,
                "token_ids": list(token_ids),
            }],
        }
    return f"data: {json.dumps(doc)}\n\n"


def fleet_metrics() -> Dict[str, Any]:
    """The fleet failure-plane metric families, registered
    idempotently in THIS process's registry (the ingress scrape —
    same pattern as the watchdog gauges)."""
    C, G = metrics_api.Counter, metrics_api.Gauge
    return {
        "failovers": C(
            "ray_tpu_llm_failovers_total",
            "requests re-dispatched to another replica after a "
            "failure (mid-stream token-exact continuations + unary "
            "retries)", ("model",)),
        "evictions": C(
            "ray_tpu_llm_replica_evictions_total",
            "replicas evicted from the router ring by the health "
            "state machine", ("model",)),
        "breaker": G(
            "ray_tpu_llm_breaker_state",
            "per-replica circuit breaker state "
            "(0 closed / 1 open / 2 half-open)",
            ("model", "replica")),
        "deadline_sheds": C(
            "ray_tpu_llm_deadline_sheds_total",
            "requests shed (admission) or aborted (engine) past "
            "their client deadline", ("model", "stage")),
    }


__all__ = [
    "HealthConfig", "CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN",
    "StreamTranscript", "StreamBroken", "continuation_body",
    "pin_stream_identity", "sse_chunk", "fleet_metrics",
    "TOKEN_STREAM_METHODS", "DEFAULT_MAX_TOKENS",
]
