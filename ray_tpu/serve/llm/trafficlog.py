"""Traffic flight-data recorder + capture codec (ISSUE 20).

The fleet can trace, profile, and cost-attribute single requests
(PRs 5/7/11/13) but could not *record* the workload that produced
those numbers: the simulator replayed only synthetic generators.
This module is the missing source — an always-on, bounded
`TrafficRecorder` at the fleet ingress appends one privacy-scrubbed
record per request (arrival clock, tenant, lane, token counts,
prefix fingerprint, sampling params incl. per-request seed,
deadline, stream-vs-unary, and the outcome brief), and an armed
capture snapshots that stream into a versioned, checksummed JSONL
format any later session can replay deterministically
(`sim.traffic.RecordedTrace`, `tools/tracereplay`).

Privacy by construction: records NEVER contain prompt or completion
text. The only content-derived field is the router's prefix-chain
fingerprint (a hash-cons key); sampling params pass through a
numeric allowlist (`sampling_brief`). The tier-1 suite and the
bench_llm smoke gate both assert no prompt substring survives into
capture bytes.

Wire discipline mirrors `kv_transport.py`, transposed to text: every
capture line is one segment `RTTC<version> <crc32:08x> <canonical
JSON>`; the first segment is the capture header (capture id + one
wall anchor for the whole capture, monotonic anchor for arrival
math), the last is an `end` segment carrying the record count.
Corruption or truncation anywhere raises a typed `CaptureError` /
`CaptureChecksumError` — never a crash, never a silently short
replay. Stopped captures optionally spool to disk through
`BlackboxSpool` (bounded count+bytes, atomic writes, traversal-safe
reads — the PR 7 mechanics, reused).
"""

from __future__ import annotations

import collections
import json
import threading
import time
import uuid
import zlib
from typing import Any, Dict, Iterable, List, Optional, Union

from ...llm._internal.blackbox import BlackboxSpool
from ...util import tracing
from ...util.metrics import Counter

CAPTURE_MAGIC = "RTTC"
CAPTURE_VERSION = 1

_RING_CAPACITY = 4096                    # always-on in-memory ring
_CAPTURE_MAX_RECORDS = 200_000           # per-capture record bound
_CAPTURE_MAX_BYTES = 64 * 1024 * 1024    # per-capture byte bound
_SPOOL_CAPACITY = 8                      # captures kept on disk
_SPOOL_MAX_BYTES = 256 * 1024 * 1024

# the sampling-param allowlist: scalar knobs only, never text.
# per-request seed rides here so a replay can re-run the exact
# sampling path (the PR 9 failover contract, extended to captures).
_PARAM_KEYS = ("max_tokens", "temperature", "top_p", "top_k", "seed")


class CaptureError(RuntimeError):
    """A capture blob failed structural validation (bad magic,
    version skew, malformed segment, truncation)."""


class CaptureChecksumError(CaptureError):
    """A capture segment's payload does not match its crc32."""


# -- the wire format ---------------------------------------------------

def _crc(payload: bytes) -> str:
    return f"{zlib.crc32(payload) & 0xFFFFFFFF:08x}"


def encode_segment(doc: Dict[str, Any]) -> str:
    """One capture segment: magic+version token, crc32 of the
    canonical-JSON payload, then the payload itself."""
    payload = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return (f"{CAPTURE_MAGIC}{CAPTURE_VERSION} "
            f"{_crc(payload.encode('utf-8'))} {payload}")


def decode_segment(line: str, lineno: int = 0) -> Dict[str, Any]:
    """Validate and decode one segment; every malformed shape maps to
    a typed error naming the line."""
    where = f"segment {lineno}" if lineno else "segment"
    parts = line.split(" ", 2)
    if len(parts) != 3:
        raise CaptureError(f"malformed {where}: expected "
                           f"'<magic> <crc> <json>'")
    tag, crc, payload = parts
    if not tag.startswith(CAPTURE_MAGIC):
        raise CaptureError(f"bad magic in {where}: {tag[:8]!r}")
    ver = tag[len(CAPTURE_MAGIC):]
    if ver != str(CAPTURE_VERSION):
        raise CaptureError(f"unsupported capture version {ver!r} "
                           f"in {where} (have {CAPTURE_VERSION})")
    if _crc(payload.encode("utf-8")) != crc:
        raise CaptureChecksumError(f"checksum mismatch in {where}")
    try:
        doc = json.loads(payload)
    except ValueError as e:
        raise CaptureError(f"bad JSON in {where}: {e}") from None
    if not isinstance(doc, dict) or "kind" not in doc:
        raise CaptureError(f"{where} is not a tagged segment")
    return doc


def decode_capture(blob: Union[str, bytes]) -> Dict[str, Any]:
    """Parse a full capture. Returns {"header", "records", "marks",
    "end"}; raises CaptureError/CaptureChecksumError on any
    corruption or truncation (a capture with no end segment was cut
    mid-write and must not replay as if complete)."""
    if isinstance(blob, bytes):
        try:
            blob = blob.decode("utf-8")
        except UnicodeDecodeError as e:
            raise CaptureError(f"capture is not utf-8: {e}") from None
    lines = [ln for ln in blob.splitlines() if ln.strip()]
    if not lines:
        raise CaptureError("empty capture")
    docs = [decode_segment(ln, i + 1) for i, ln in enumerate(lines)]
    header = docs[0]
    if header.get("kind") != "header":
        raise CaptureError("first segment is not a capture header")
    records = [d for d in docs if d.get("kind") == "record"]
    marks = [d for d in docs if d.get("kind") == "mark"]
    end = docs[-1]
    if end.get("kind") != "end":
        raise CaptureError("truncated capture: no end segment")
    if end.get("records") != len(records):
        raise CaptureError(
            f"truncated capture: end segment says "
            f"{end.get('records')} records, found {len(records)}")
    return {"header": header, "records": records, "marks": marks,
            "end": end}


def load_capture(path: str) -> Dict[str, Any]:
    """decode_capture over a file; I/O failures become CaptureError
    so callers handle exactly one exception family."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise CaptureError(f"cannot read capture {path!r}: {e}") \
            from None
    return decode_capture(blob)


# -- record construction ----------------------------------------------

def sampling_brief(body: Dict[str, Any]) -> Dict[str, Any]:
    """The ONLY reader of the request body on the capture path:
    numeric sampling knobs by allowlist. Text fields (prompt,
    messages, stop strings, ...) are structurally unreachable."""
    out: Dict[str, Any] = {}
    for k in _PARAM_KEYS:
        v = body.get(k)
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[k] = v
    return out


def traffic_metrics() -> Dict[str, Any]:
    """The recorder's metric families (fleet_metrics() pattern;
    idempotent via the registry)."""
    return {
        "captured": Counter(
            "ray_tpu_llm_traffic_captured_total",
            "Requests recorded by the ingress traffic recorder.",
            ("model",)),
        "capture_bytes": Counter(
            "ray_tpu_llm_traffic_capture_bytes_total",
            "Encoded capture bytes appended while a capture is "
            "armed.",
            ("model",)),
    }


class TrafficRecorder:
    """Always-on bounded request log + armed-capture snapshotter.

    `record()` is on the dispatch hot path: one dict build and a
    deque append under a lock; segment encoding happens only while a
    capture is armed. The ring is the `GET /fleet/debug/traffic`
    surface; captures are the replay artifact."""

    def __init__(self, capacity: int = _RING_CAPACITY,
                 model_id: str = "default",
                 spool_dir: Optional[str] = None,
                 spool_capacity: int = _SPOOL_CAPACITY,
                 spool_max_bytes: int = _SPOOL_MAX_BYTES,
                 max_capture_records: int = _CAPTURE_MAX_RECORDS,
                 max_capture_bytes: int = _CAPTURE_MAX_BYTES,
                 clock=time.monotonic):
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self._seq = 0
        self.dropped = 0
        self.model_id = model_id
        self._clock = clock
        self._max_records = int(max_capture_records)
        self._max_bytes = int(max_capture_bytes)
        self._capture: Optional[Dict[str, Any]] = None
        self._last: Optional[Dict[str, Any]] = None
        self.spool = (BlackboxSpool(spool_dir,
                                    capacity=spool_capacity,
                                    max_bytes=spool_max_bytes)
                      if spool_dir else None)
        m = traffic_metrics()
        self._captured_total = m["captured"]
        self._capture_bytes_total = m["capture_bytes"]

    # -- hot path ------------------------------------------------------
    def record(self, **fields: Any) -> int:
        """Append one record; returns its seq."""
        line = None
        with self._lock:
            self._seq += 1
            seq = self._seq
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            rec = {"kind": "record", "seq": seq, **fields}
            self._ring.append(rec)
            cap = self._capture
            if cap is not None:
                if (cap["records"] >= self._max_records
                        or cap["bytes"] >= self._max_bytes):
                    cap["dropped"] += 1
                else:
                    line = encode_segment(rec)
                    cap["lines"].append(line)
                    cap["records"] += 1
                    cap["bytes"] += len(line) + 1
        # metric publication outside the lock (FlightRecorder rule)
        tags = {"model": self.model_id}
        self._captured_total.inc(1, tags)
        if line is not None:
            self._capture_bytes_total.inc(len(line) + 1, tags)
        return seq

    def observe_request(self, rec: Optional[Dict[str, Any]]) -> None:
        """Fold a FleetManager request record (the `_trace_begin`
        dict, enriched along the dispatch path) into one traffic
        record. Explicit field allowlist — nothing body-derived
        enters except `sampling_brief` scalars and the prefix
        fingerprint."""
        if rec is None:
            return
        t0 = float(rec.get("t0") or 0.0)
        now = self._clock()
        t_first = rec.get("t_first")
        out_tokens = int(rec.get("out_tokens") or 0)
        ttft_ms = None
        itl_ms = None
        if t_first is not None:
            ttft_ms = round(max(t_first - t0, 0.0) * 1e3, 3)
            if out_tokens > 1:
                itl_ms = round(max(now - t_first, 0.0) * 1e3
                               / (out_tokens - 1), 3)
        self.record(
            t_mono=round(t0, 6),
            rid=rec.get("rid") or "",
            method=rec.get("method") or "",
            stream=bool(rec.get("stream")),
            tenant=rec.get("tenant") or "",
            lane=rec.get("lane") or "interactive",
            fp=rec.get("fp") or "",
            prompt_tokens=int(rec.get("prompt_tokens") or 0),
            out_tokens=out_tokens,
            params=dict(rec.get("params") or {}),
            deadline_s=rec.get("deadline_s"),
            outcome={
                "status": rec.get("status") or "ok",
                "finish": rec.get("finish"),
                "route": rec.get("outcome"),
                "replica": rec.get("replica"),
                "failovers": int(rec.get("failovers") or 0),
                "preemptions": int(rec.get("preemptions") or 0),
                "ttft_ms": ttft_ms,
                "itl_ms": itl_ms,
                "e2e_ms": round(max(now - t0, 0.0) * 1e3, 3),
            })

    # -- capture controls ----------------------------------------------
    def start_capture(self, note: str = "") -> Dict[str, Any]:
        with self._lock:
            if self._capture is not None:
                raise CaptureError("capture already active: "
                                   + self._capture["id"])
            cid = uuid.uuid4().hex[:16]
            mono = self._clock()
            header = {
                "kind": "header",
                "object": "traffic_capture",
                "version": CAPTURE_VERSION,
                "capture_id": cid,
                "model": self.model_id,
                # one wall anchor per capture (PR 7's clock
                # discipline): arrivals are monotonic offsets from
                # mono_anchor; wall_anchor pins them to epoch time
                "mono_anchor": round(mono, 6),
                "wall_anchor": round(tracing.mono_to_epoch(mono), 6),
                "note": str(note)[:256],
            }
            line = encode_segment(header)
            self._capture = {"id": cid, "header": header,
                             "mono_anchor": mono,
                             "lines": [line], "records": 0,
                             "bytes": len(line) + 1, "dropped": 0,
                             "marks": 0}
            return {"capture_id": cid, "active": True}

    def mark(self, label: str = "") -> Dict[str, Any]:
        """Drop a labeled mark segment into the armed capture (the
        'something happened here' flag for later diffing)."""
        with self._lock:
            cap = self._capture
            if cap is None:
                raise CaptureError("no active capture to mark")
            doc = {"kind": "mark", "label": str(label)[:256],
                   "t_mono": round(self._clock(), 6)}
            line = encode_segment(doc)
            cap["lines"].append(line)
            cap["bytes"] += len(line) + 1
            cap["marks"] += 1
            return {"capture_id": cap["id"], "marks": cap["marks"]}

    def stop_capture(self) -> Dict[str, Any]:
        """Seal the armed capture (end segment with the record count
        — the truncation sentinel), retain it as the last capture,
        spool it if a spool is configured."""
        with self._lock:
            cap = self._capture
            if cap is None:
                raise CaptureError("no active capture to stop")
            end = {"kind": "end", "capture_id": cap["id"],
                   "records": cap["records"], "marks": cap["marks"],
                   "dropped": cap["dropped"]}
            cap["lines"].append(encode_segment(end))
            text = "\n".join(cap["lines"]) + "\n"
            self._capture = None
            self._last = {"capture_id": cap["id"], "text": text,
                          "records": cap["records"],
                          "bytes": len(text),
                          "dropped": cap["dropped"],
                          "marks": cap["marks"]}
        spool_id = None
        if self.spool is not None:
            spool_id = self.spool.dump(
                "traffic-" + cap["id"],
                {"capture_id": cap["id"], "capture": text})
        return {"capture_id": cap["id"], "records": cap["records"],
                "bytes": len(text), "dropped": cap["dropped"],
                "marks": cap["marks"], "spool_id": spool_id}

    def export(self) -> str:
        """The last sealed capture's bytes (the replay artifact)."""
        with self._lock:
            if self._last is None:
                raise CaptureError("no sealed capture to export")
            return self._last["text"]

    # -- read surface --------------------------------------------------
    def tail(self, n: int = 64,
             since: Optional[int] = None) -> List[Dict[str, Any]]:
        """Most recent `n` ring records, optionally only those with
        seq > `since` (the satellite-1 cursor discipline)."""
        with self._lock:
            evs: Iterable[Dict[str, Any]] = list(self._ring)
        if since is not None:
            evs = [e for e in evs if e["seq"] > since]
        evs = list(evs)
        return evs[-max(int(n), 0):]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            cap = self._capture
            active = (None if cap is None else
                      {"capture_id": cap["id"],
                       "records": cap["records"],
                       "bytes": cap["bytes"],
                       "dropped": cap["dropped"],
                       "marks": cap["marks"]})
            last = (None if self._last is None else
                    {k: self._last[k]
                     for k in ("capture_id", "records", "bytes",
                               "dropped", "marks")})
            return {"records": len(self._ring), "total": self._seq,
                    "dropped": self.dropped, "capture": active,
                    "last_capture": last}


__all__ = ["TrafficRecorder", "CaptureError", "CaptureChecksumError",
           "CAPTURE_MAGIC", "CAPTURE_VERSION", "encode_segment",
           "decode_segment", "decode_capture", "load_capture",
           "sampling_brief", "traffic_metrics"]
