"""Fleet admission control: bounded queue, backpressure, tenant weights.

ISSUE 6: the engine already does per-replica admission control (KV
pages at add_request / slot admission), but a fleet needs a SINGLE
front door: without it, overload turns into unbounded queueing inside
whichever replica the router picked — every queued request eventually
completes, but p99 queue wait grows without limit and clients time out
anyway, having wasted the fleet's work. This controller makes overload
an explicit, bounded signal instead:

- at most `max_concurrent` requests are dispatched fleet-wide; excess
  waits in ONE bounded queue (`max_queue`);
- a request that would exceed the queue bound is rejected immediately
  (HTTP 429 + Retry-After at the ingress), and a queued request that
  waits past `queue_wait_slo_s` is shed the same way — so the queue
  wait of EVERY request, admitted or shed, is bounded by the SLO;
- dequeue order is weighted fair across tenants (stride scheduling:
  each tenant advances a virtual-time pass by 1/weight per request),
  so a tenant flooding the queue cannot starve the others — it just
  burns its own share.

Pure asyncio, single event loop, no locks: every mutation happens on
the loop the ingress runs on.

ISSUE 14 layered a SYNCHRONOUS twin under the async surface: all the
policy state (heap, stride passes, brownout bound, shed accounting)
lives in loop-free methods — `submit()` enqueues, `shed_expired()`
applies the SLO/deadline timers, `granted_sync()` drains grants — and
`acquire()` is now a thin asyncio waiter over them. The discrete-event
fleet simulator (serve/llm/sim) drives THIS object, not a fork, in
virtual time through the injected `clock`.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import heapq
import itertools
import time
from typing import Any, Callable, Dict, List, Optional


@dataclasses.dataclass
class AdmissionConfig:
    max_concurrent: int = 64       # fleet-wide dispatched requests
    max_queue: int = 128           # bounded front-door queue
    queue_wait_slo_s: float = 2.0  # queued past this -> shed (429)
    retry_after_s: float = 1.0     # floor for the Retry-After hint
    # tenant name -> weight (absent tenants get 1.0); higher weight =
    # larger share of dequeues under contention
    tenant_weights: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    # brownout (ISSUE 7): while the SLO burn-rate watchdog pages, the
    # queue bound shrinks to this fraction — shed the marginal request
    # at the front door BEFORE it burns more of the error budget
    # inside an already-slow fleet (0 < factor <= 1; 1 disables)
    brownout_queue_factor: float = 0.25


class AdmissionRejected(Exception):
    """Maps to HTTP 429 at the ingress."""

    def __init__(self, reason: str, retry_after_s: float):
        super().__init__(reason)
        self.reason = reason
        self.retry_after_s = retry_after_s


def admission_metrics() -> Dict[str, Any]:
    """Tenant-labeled front-door series (ISSUE 13 satellite): noisy-
    tenant diagnosis — whose queue waits grew, who is being shed —
    without log archaeology. Registered idempotently in the ingress
    process registry. The default tenant exports tenant="" and the
    exposition omits empty labels, so single-tenant scrapes stay
    byte-identical (the PR 6 `replica` convention)."""
    from ...llm._internal.telemetry import LATENCY_BOUNDARIES
    from ...util import metrics as metrics_api
    return {
        "queue_wait": metrics_api.Histogram(
            "ray_tpu_llm_fleet_queue_wait_seconds",
            "front-door admission queue wait of ADMITTED requests, "
            "per tenant", boundaries=LATENCY_BOUNDARIES,
            tag_keys=("model", "tenant")),
        "rejected": metrics_api.Counter(
            "ray_tpu_llm_fleet_admission_rejected_total",
            "front-door rejections per tenant and reason "
            "(queue_full | brownout -> 429; queue_wait_slo = SLO "
            "shed -> 429; deadline -> 504)",
            ("model", "tenant", "reason")),
    }


class _Ticket:
    """One queued admission claim. The asyncio `future` exists only
    for async waiters (acquire); synchronous drivers (the fleet
    simulator) read `granted`/`dead` directly — grants they missed
    accumulate in the controller's `granted_sync()` drain."""

    __slots__ = ("tenant", "vtime", "seq", "future", "queued_at",
                 "deadline", "granted", "dead", "sync")

    def __init__(self, tenant: str, vtime: float, seq: int,
                 queued_at: float, deadline: Optional[float] = None,
                 sync: bool = True):
        self.tenant = tenant
        self.vtime = vtime
        self.seq = seq
        self.queued_at = queued_at
        self.deadline = deadline       # absolute clock instant | None
        self.future: Optional[asyncio.Future] = None
        self.granted = False
        self.dead = False
        # sync tickets (no asyncio waiter) report their grants through
        # granted_sync() and their sheds through shed_expired(); async
        # tickets (acquire) run their own future + timer instead
        self.sync = sync

    @property
    def done(self) -> bool:
        return self.granted or self.dead

    def __lt__(self, other: "_Ticket") -> bool:
        return (self.vtime, self.seq) < (other.vtime, other.seq)


class AdmissionController:
    """`await acquire(tenant)` then `release()` around each dispatch —
    or, for clock-driven hosts (the fleet simulator), `submit()` /
    `shed_expired()` / `granted_sync()` / `release()`."""

    def __init__(self, config: Optional[AdmissionConfig] = None,
                 metrics_model_id: Optional[str] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.config = config or AdmissionConfig()
        # injectable clock (ISSUE 14): every time source in this
        # controller goes through it, so the simulator can drive the
        # REAL policy in virtual time without monkeypatching
        self._clock = clock if clock is not None else time.monotonic
        # tenant-labeled Prometheus series (ISSUE 13 satellite): off
        # unless the owner names a model id — bare unit-test
        # controllers stay registry-silent
        self._metrics = (admission_metrics()
                         if metrics_model_id is not None else None)
        self._mtags = {"model": metrics_model_id or ""}
        self.inflight = 0
        self._heap: List[_Ticket] = []
        self._dead = 0     # shed/cancelled tickets still in the heap
        self._seq = itertools.count()
        # stride-scheduling state: a tenant's next pass; the global
        # vtime floor stops an idle tenant banking credit forever
        self._pass: Dict[str, float] = {}
        self._vtime = 0.0
        # grants made to SYNC tickets (no future to resolve): the
        # clock-driven host collects them here after submit()/release()
        self._granted_sync: List[_Ticket] = []
        # observability (GET /fleet)
        self.admitted = 0
        self.rejected: Dict[str, int] = {"queue_full": 0,
                                         "queue_wait_slo": 0,
                                         "brownout": 0,
                                         "deadline": 0}
        self.shed_total = 0
        # watchdog-driven degraded mode (see set_brownout)
        self.brownout = False
        # KV page pressure (ISSUE 10), stamped by the fleet's control
        # loop: "pages short but SPILLABLE" keeps the full queue bound
        # — requests wait behind the latency tier instead of being
        # shed — while a pressured fleet that CANNOT spill sheds via
        # brownout (FleetManager gates set_brownout on spillability)
        self.page_pressure = 0.0
        self.spillable = False
        self._recent_waits: collections.deque = collections.deque(
            maxlen=512)

    # -- internals ------------------------------------------------------
    def _weight(self, tenant: str) -> float:
        w = self.config.tenant_weights.get(tenant, 1.0)
        return w if w > 0 else 1.0

    def _effective_max_queue(self) -> int:
        cfg = self.config
        if not self.brownout:
            return cfg.max_queue
        return max(0, int(cfg.max_queue * cfg.brownout_queue_factor))

    def set_page_pressure(self, pressure: float,
                          spillable: bool) -> None:
        """Stamp the fleet's page-pressure observation (ISSUE 10) —
        observability plus the documented queue-vs-shed contract: the
        caller only engages brownout for pressure when `spillable` is
        False (see FleetManager.watchdog_tick)."""
        self.page_pressure = float(pressure)
        self.spillable = bool(spillable)

    def set_brownout(self, on: bool) -> bool:
        """Engage/release brownout (the SLO watchdog's shed signal):
        while on, the queue bound shrinks so overload turns into fast
        429s instead of deep queueing — already-queued requests are
        untouched (they drain or shed under their own SLO timer).
        Returns True when the state actually changed."""
        on = bool(on)
        if on == self.brownout:
            return False
        self.brownout = on
        return True

    @staticmethod
    def _tenant_label(tenant: str) -> str:
        # the default tenant's label is "" (omitted from expositions)
        return "" if tenant in ("", "default") else tenant

    def _count_reject(self, tenant: str, reason: str) -> None:
        self.rejected[reason] += 1
        if self._metrics is not None:
            self._metrics["rejected"].inc(
                1, {**self._mtags, "reason": reason,
                    "tenant": self._tenant_label(tenant)})

    def _queue_len(self) -> int:
        # done tickets still heaped are exactly the shed/cancelled
        # ones (_dead): grants pop their ticket before resolving it
        return len(self._heap) - self._dead

    def _discard(self, ticket: _Ticket) -> None:
        """A queued ticket was shed or cancelled. It stays in the heap
        (removal from the middle is O(n)) but MUST NOT wait for
        _grant_next's capacity-gated pop to reap it — long-lived
        streams can peg inflight at the cap for minutes, during which
        sustained overload would accumulate every ticket ever shed and
        degrade admission to O(dead) per call. Mark, then compact once
        the dead tickets win."""
        if ticket.done:
            return
        ticket.dead = True
        if ticket.future is not None:
            ticket.future.cancel()
        self._dead += 1
        if self._dead > 32 and self._dead * 2 > len(self._heap):
            self._heap = [t for t in self._heap if not t.done]
            heapq.heapify(self._heap)
            self._dead = 0

    def _grant_next(self) -> None:
        while self._heap and self.inflight < self.config.max_concurrent:
            t = heapq.heappop(self._heap)
            if t.done:
                self._dead -= 1
                continue             # shed while queued
            self.inflight += 1
            self._vtime = max(self._vtime, t.vtime)
            t.granted = True
            self._record_admit(self._clock() - t.queued_at, t.tenant)
            if t.future is not None:
                if not t.future.done():
                    t.future.set_result(None)
            elif t.sync:
                self._granted_sync.append(t)

    def _record_admit(self, wait_s: float,
                      tenant: str = "default") -> None:
        self.admitted += 1
        self._recent_waits.append(max(wait_s, 0.0))
        if self._metrics is not None:
            self._metrics["queue_wait"].observe(
                max(wait_s, 0.0),
                {**self._mtags,
                 "tenant": self._tenant_label(tenant)})

    def _prune_pass(self) -> None:
        # entries at or below the global floor are semantically dead —
        # submit()'s max(pass, vtime) picks the floor anyway — and the
        # tenant string is CLIENT-controlled (the OpenAI "user" field),
        # so without eviction one dict entry per distinct end-user id
        # accumulates forever; size-triggered so the rebuild stays off
        # the per-request path
        if len(self._pass) > 1024:
            self._pass = {t: p for t, p in self._pass.items()
                          if p > self._vtime}

    # -- synchronous policy core (async acquire + sim both drive it) ----
    def submit(self, tenant: str = "default",
               deadline: Optional[float] = None,
               now: Optional[float] = None,
               sync: bool = True) -> _Ticket:
        """Enqueue one admission claim RIGHT NOW: raises
        AdmissionRejected (deadline already expired, queue full,
        brownout) or returns a ticket — possibly already granted
        (sync tickets' grants ALSO land in granted_sync(), so a
        clock-driven host handles immediate and queued grants through
        one drain). `deadline` is an absolute instant on this
        controller's clock."""
        cfg = self.config
        now = self._clock() if now is None else now
        if deadline is not None and now >= deadline:
            # NOT counted into shed_total: a deadline shed is the
            # client's budget spent, not fleet overload — it must not
            # feed the autoscaler's shed_delta breach signal
            self._count_reject(tenant, "deadline")
            raise AdmissionRejected("deadline", self.retry_after())
        # flush cancelled heap heads / spare capacity first, so the
        # queue-full check below sees the true backlog
        self._grant_next()
        limit = self._effective_max_queue()
        if self.inflight >= cfg.max_concurrent \
                and self._queue_len() >= limit:
            # attribute the shed: under brownout a rejection the full
            # bound would have admitted is a pre-emptive brownout shed
            reason = ("brownout"
                      if limit < cfg.max_queue
                      and self._queue_len() < cfg.max_queue
                      else "queue_full")
            self._count_reject(tenant, reason)
            raise AdmissionRejected(reason, self.retry_after())
        vtime = max(self._pass.get(tenant, 0.0), self._vtime) \
            + 1.0 / self._weight(tenant)
        self._pass[tenant] = vtime
        self._prune_pass()
        ticket = _Ticket(tenant, vtime, next(self._seq),
                         queued_at=now, deadline=deadline, sync=sync)
        heapq.heappush(self._heap, ticket)
        self._grant_next()
        return ticket

    def shed_expired(self, now: Optional[float] = None
                     ) -> List[_Ticket]:
        """Apply the SLO/deadline timers to queued SYNC tickets (the
        async path runs its own asyncio timers): a ticket queued past
        queue_wait_slo_s — or past its own deadline, whichever is
        sooner — is shed, counted exactly like acquire()'s timeout
        path. Returns the tickets shed this call so a clock-driven
        host can fail their sessions. O(queue) per call; drivers call
        it at control-loop cadence, not per request."""
        now = self._clock() if now is None else now
        slo = self.config.queue_wait_slo_s
        shed: List[_Ticket] = []
        for t in self._heap:
            if t.done or not t.sync:
                continue
            by_deadline = t.deadline is not None and now >= t.deadline
            if not by_deadline and now - t.queued_at < slo:
                continue
            # attribute by whichever timer fired FIRST (the async
            # path's semantics): with a coarse driver cadence both
            # may have elapsed by now, but a deadline sooner than the
            # SLO instant is the client's budget, not fleet overload
            reason = ("deadline"
                      if by_deadline
                      and t.deadline <= t.queued_at + slo
                      else "queue_wait_slo")
            self._discard(t)
            self._count_reject(t.tenant, reason)
            if reason != "deadline":
                self.shed_total += 1
            shed.append(t)
        return shed

    def granted_sync(self) -> List[_Ticket]:
        """Drain the grants made to sync tickets since the last call
        (in grant order) — the clock-driven host routes each one's
        session now."""
        out, self._granted_sync = self._granted_sync, []
        return out

    # -- public API -----------------------------------------------------
    async def acquire(self, tenant: str = "default",
                      deadline: Optional[float] = None) -> None:
        """Admit or raise AdmissionRejected. Bounded wait: returns
        within queue_wait_slo_s — or within the request's remaining
        deadline, whichever is sooner (ISSUE 9: an already-expired
        request sheds BEFORE queueing, and a queued one sheds the
        moment waiting any longer could not possibly help; either way
        the fleet does zero work for a request its client has already
        abandoned). `deadline` is absolute on this controller's clock
        (time.monotonic unless injected)."""
        cfg = self.config
        now = self._clock()
        ticket = self.submit(tenant, deadline=deadline, now=now,
                             sync=False)
        if ticket.granted:
            return                      # admitted without waiting
        ticket.future = asyncio.get_running_loop().create_future()
        timeout = cfg.queue_wait_slo_s
        if deadline is not None:
            timeout = min(timeout, max(deadline - now, 0.0))
        try:
            await asyncio.wait_for(
                asyncio.shield(ticket.future), timeout=timeout)
        except asyncio.TimeoutError:
            if ticket.granted:
                # granted in the same loop turn the timer fired:
                # the grant stands
                return
            self._discard(ticket)
            # attribute the shed: the deadline timer firing first
            # means the CLIENT's budget ran out, not the fleet's SLO
            # (and only SLO sheds count into shed_total — the
            # autoscaler's overload signal)
            reason = ("deadline"
                      if deadline is not None
                      and timeout < cfg.queue_wait_slo_s
                      else "queue_wait_slo")
            self._count_reject(ticket.tenant, reason)
            if reason != "deadline":
                self.shed_total += 1
            raise AdmissionRejected(reason,
                                    self.retry_after()) from None
        except asyncio.CancelledError:
            # caller cancelled (client gone) — give the slot back if
            # the grant raced the cancellation
            if ticket.granted:
                self.release()
            else:
                self._discard(ticket)
            raise

    def would_reject(self) -> bool:
        """Preflight: would acquire() reject RIGHT NOW? (The ingress
        checks before committing a 200 SSE stream to the wire.)"""
        self._grant_next()
        return (self.inflight >= self.config.max_concurrent
                and self._queue_len() >= self._effective_max_queue())

    def release(self) -> None:
        """One dispatched request finished; grant the next waiter."""
        self.inflight = max(self.inflight - 1, 0)
        self._grant_next()

    def retry_after(self) -> float:
        """Retry-After hint: the SLO-bounded drain estimate — a full
        queue drains within one SLO window by construction (every
        waiter is granted or shed by then)."""
        cfg = self.config
        if self._queue_len() == 0:
            return cfg.retry_after_s
        return max(cfg.retry_after_s, cfg.queue_wait_slo_s)

    # -- observability --------------------------------------------------
    def queue_wait_p99_s(self) -> float:
        waits = sorted(self._recent_waits)
        if not waits:
            return 0.0
        return waits[min(len(waits) - 1, int(len(waits) * 0.99))]

    def stats(self) -> Dict[str, Any]:
        return {
            "inflight": self.inflight,
            "queued": self._queue_len(),
            "admitted": self.admitted,
            "rejected": dict(self.rejected),
            "shed_total": self.shed_total,
            "queue_wait_p99_s": round(self.queue_wait_p99_s(), 4),
            "max_concurrent": self.config.max_concurrent,
            "max_queue": self.config.max_queue,
            "queue_wait_slo_s": self.config.queue_wait_slo_s,
            "brownout": self.brownout,
            "effective_max_queue": self._effective_max_queue(),
            "page_pressure": round(self.page_pressure, 4),
            "spillable": self.spillable,
        }


__all__ = ["AdmissionConfig", "AdmissionController",
           "AdmissionRejected", "admission_metrics"]
