"""SimCalibration: measured engine timing -> synthetic-replica model.

The simulator's fidelity rests entirely on this file: a synthetic
replica is nothing but a tick-index clock whose tick DURATION comes
from here. The numbers are extracted from a REAL engine's telemetry —
`stats()["tick_times"]` (PR 4's wall/host/device window) and the
per-tick `PerfSample` window PR 11's accountant keeps (batch
composition per tick — the piece the aggregate percentiles lack) —
by `tools/simcal`, which commits the result as a JSON file beside
this module (`calibration_cpu.json` for the CPU tier-1 environment;
real-TPU files land next to the BENCH_rNN artifacts when the tunnel
returns).

Model shape:
- decode ticks: wall-ms percentiles (p50/p95/p99) per
  batch-size bucket (1, 2, 4, ... slots decoding) — the simulator
  draws from a 3-point mixture over them (seeded), so simulated
  TTFT/ITL distributions grow tails instead of being delta spikes;
- prefill: extra wall-ms per prompt token ridden on a tick, plus the
  engine's chunk budget (a prompt occupies ceil(len/chunk) ticks);
- spill/restore: the latency a preemption/restore event charges
  (PR 10's page-gather + scatter, measured from offload-flagged
  ticks).

The sim-vs-real A/B gate (tests/test_fleet_sim.py +
bench_llm --smoke) replays a small real workload through both and
pins the predicted TTFT/e2e within a tolerance band — the file
cannot silently rot.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional

# tolerance band of the sim-vs-real calibration A/B (ratio of sim
# predicted to real measured mean e2e) — wide because the CPU tier's
# tick times wobble with host load; the gate catches rot (10x drift
# from a stale file), not noise
CALIBRATION_BAND = (0.25, 4.0)

_PCTS = ("p50", "p95", "p99")


def _bucket(n: int) -> int:
    b = 1
    while b < max(n, 1):
        b *= 2
    return b


def _pctl(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    return s[min(int(q * (len(s) - 1) + 0.5), len(s) - 1)]


@dataclasses.dataclass
class SimCalibration:
    """The synthetic replica's timing model (JSON-serializable)."""
    name: str = "uncalibrated"
    page_size: int = 16
    # batch-size bucket (as str key for JSON) -> {"p50","p95","p99"}
    # decode-tick wall ms
    decode_tick_ms: Dict[str, Dict[str, float]] = dataclasses.field(
        default_factory=dict)
    # extra wall-ms a tick pays per prefill token it carries
    prefill_ms_per_token: float = 0.05
    # the engine's per-tick prefill budget (max_prefill_tokens)
    prefill_chunk_tokens: int = 512
    # preemption spill / restore latency (ms charged to the event)
    spill_ms: float = 2.0
    restore_ms: float = 2.0
    # provenance (never consumed by the model)
    source: Dict[str, Any] = dataclasses.field(default_factory=dict)

    # -- the model -----------------------------------------------------
    def tick_point(self, batch: int, pct: str) -> float:
        """Decode-tick wall ms for `batch` decoding slots at one of
        the modeled percentile points, falling back to the nearest
        measured bucket (scaled linearly past the largest)."""
        if not self.decode_tick_ms:
            return 1.0
        b = _bucket(batch)
        key = str(b)
        if key in self.decode_tick_ms:
            return self.decode_tick_ms[key].get(pct, 1.0)
        known = sorted(int(k) for k in self.decode_tick_ms)
        if b < known[0]:
            return self.decode_tick_ms[str(known[0])].get(pct, 1.0)
        top = known[-1]
        base = self.decode_tick_ms[str(top)].get(pct, 1.0)
        return base * (b / top)

    def draw_tick_ms(self, batch: int, prefill_tokens: int,
                     u: float) -> float:
        """One tick's wall ms: a 3-point mixture over the bucket's
        percentiles (u ~ Uniform[0,1) from the replica's seeded RNG —
        90% body, 8% p95 shoulder, 2% p99 tail) plus the prefill
        surcharge. Deterministic given (batch, prefill_tokens, u)."""
        pct = "p50" if u < 0.90 else ("p95" if u < 0.98 else "p99")
        return (self.tick_point(batch, pct)
                + prefill_tokens * self.prefill_ms_per_token)

    def prefill_ticks(self, prompt_tokens: int) -> int:
        """Ticks a prompt occupies before its first token (Sarathi
        chunking: ceil(prompt / chunk budget))."""
        chunk = max(self.prefill_chunk_tokens, 1)
        return max((prompt_tokens + chunk - 1) // chunk, 1)

    # -- (de)serialization --------------------------------------------
    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True,
                          indent=2) + "\n"

    def checksum(self) -> str:
        """sha256 of the canonical JSON rendering — the artifact
        provenance key (ISSUE 20 satellite): a committed sweep /
        summary / capture-diff names exactly which calibration
        produced it. Computed over to_json(), so a file round-trip
        (load → checksum) matches the original."""
        import hashlib
        return hashlib.sha256(self.to_json().encode()).hexdigest()

    @classmethod
    def from_json(cls, text: str) -> "SimCalibration":
        doc = json.loads(text)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in doc.items() if k in known})

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "SimCalibration":
        with open(path) as f:
            return cls.from_json(f.read())

    # -- extraction from a live engine --------------------------------
    @classmethod
    def from_engine(cls, engine: Any,
                    name: str = "extracted") -> "SimCalibration":
        """Extract the model from a driven engine's telemetry:
        `stats()["tick_times"]` for the aggregate provenance and the
        perf accountant's PerfSample window (ISSUE 11) for per-tick
        batch composition. The engine must have run a mixed workload
        first (tools/simcal drives one); buckets never observed fall
        back to nearest-bucket scaling at draw time."""
        stats = engine.stats()
        perf = getattr(engine, "perf", None)
        window = list(perf.window()) if perf is not None else []
        decode: Dict[int, List[float]] = {}
        prefill_rates: List[float] = []
        spill: List[float] = []
        restore: List[float] = []
        for t in window:
            if t.wall_ms <= 0:
                continue
            if t.bytes_d2h > 0:
                spill.append(t.wall_ms)
            if t.bytes_h2d > 0:
                restore.append(t.wall_ms)
            if t.prefill_tokens > 0 and t.decode_tokens >= 0:
                base = _pctl(decode.get(_bucket(
                    max(t.decode_tokens, 1)), []), 0.5)
                extra = max(t.wall_ms - base, 0.0)
                prefill_rates.append(extra / t.prefill_tokens)
            elif t.decode_tokens > 0:
                decode.setdefault(_bucket(t.decode_tokens),
                                  []).append(t.wall_ms)
        # structural-outlier trim (the anomaly detector's philosophy,
        # ISSUE 13): a cold compile or GC pause in the measurement
        # window is 10-100x the bucket median and would become the
        # model's p99 — the simulator must model steady-state tails,
        # not the measurement harness's warmup
        decode = {b: [v for v in vals
                      if v <= 10.0 * max(_pctl(vals, 0.5), 1e-6)]
                  for b, vals in decode.items()}
        decode_tick_ms = {
            str(b): {p: round(_pctl(vals, {"p50": 0.5, "p95": 0.95,
                                           "p99": 0.99}[p]), 4)
                     for p in _PCTS}
            for b, vals in sorted(decode.items()) if vals}
        # decode-only median as the baseline for event surcharges
        all_decode = [v for vals in decode.values() for v in vals]
        base_ms = _pctl(all_decode, 0.5)
        tick = stats.get("tick_times") or {}
        return cls(
            name=name,
            page_size=int(getattr(engine.allocator, "page_size", 16)),
            decode_tick_ms=decode_tick_ms,
            prefill_ms_per_token=round(
                _pctl(prefill_rates, 0.5), 6) or 0.05,
            prefill_chunk_tokens=int(
                getattr(engine.config, "max_prefill_tokens", 512)),
            spill_ms=round(max(_pctl(spill, 0.5) - base_ms, 0.1), 4),
            restore_ms=round(
                max(_pctl(restore, 0.5) - base_ms, 0.1), 4),
            source={
                "ticks_observed": len(window),
                "tick_wall_ms_p50": tick.get("wall_ms_p50"),
                "tick_wall_ms_p95": tick.get("wall_ms_p95"),
                "tick_wall_ms_p99": tick.get("wall_ms_p99"),
                "dispatches_per_step": stats.get(
                    "dispatches_per_step"),
            })


def default_cpu_calibration() -> SimCalibration:
    """The committed CPU-tier calibration (tools/simcal output against
    the debug model in this repo's tier-1 environment)."""
    path = os.path.join(os.path.dirname(__file__),
                        "calibration_cpu.json")
    return SimCalibration.load(path)


__all__ = ["SimCalibration", "default_cpu_calibration",
           "CALIBRATION_BAND"]
