"""Seeded traffic-trace generators for the fleet simulator.

Each generator yields `SimSession`s in non-decreasing arrival order —
the simulator streams them (a million-session trace never
materializes as a list unless the caller asks). Everything is driven
by ONE `random.Random(seed)`: the same (config, seed) pair produces
the identical trace byte-for-byte, which is half of the simulator's
determinism gate (the other half is the virtual clock).

Shapes (ROADMAP item 5):
- **diurnal**: sinusoidal rate over a day — the capacity-planning
  baseline, and the trace whose troughs the batch lane soaks;
- **flash_crowd**: a steady floor plus K sudden bursts (launch/retry
  storms) — exercises admission shed + autoscaler reaction;
- **tenant_skew**: Zipf-weighted tenants — one tenant floods, the
  stride scheduler's fairness is what keeps the rest alive;
- **chaos overlays**: replica stall/death/recovery events layered on
  any trace — exercises the breaker/failover plane in virtual time.

Arrival times come from inverse-CDF sampling of the rate profile
(cumulative rate over a fixed grid, then one bisect per session), so
a trace with N sessions costs O(N log G) and hits N exactly.
"""

from __future__ import annotations

import bisect
import dataclasses
import math
import random
from typing import Any, Dict, Iterator, List, Optional

INTERACTIVE = "interactive"
BATCH = "batch"

_GRID = 1440         # rate-profile resolution (1 min at 24 h)


class SimSession:
    """One logical request: arrival instant, identity, and size."""

    __slots__ = ("at", "tenant", "group", "prompt_tokens",
                 "out_tokens", "lane", "sid")

    def __init__(self, at: float, tenant: str, group: int,
                 prompt_tokens: int, out_tokens: int,
                 lane: str = INTERACTIVE, sid: int = 0):
        self.at = at
        self.tenant = tenant
        self.group = group          # prefix-fingerprint group
        self.prompt_tokens = prompt_tokens
        self.out_tokens = out_tokens
        self.lane = lane
        self.sid = sid


@dataclasses.dataclass
class TraceConfig:
    """One synthetic workload. `kind` picks the rate profile."""
    kind: str = "diurnal"           # diurnal|flash_crowd|tenant_skew|steady
    sessions: int = 10_000
    duration_s: float = 86_400.0
    seed: int = 0
    # request shape (geometric-ish around the means)
    prompt_tokens_mean: int = 64
    out_tokens_mean: int = 24
    prompt_tokens_max: int = 512
    out_tokens_max: int = 128
    # identity
    tenants: int = 4
    prefix_groups: int = 256
    # diurnal: peak/trough rate ratio
    diurnal_amplitude: float = 0.8
    # flash_crowd: bursts as a fraction of all sessions, burst width
    crowds: int = 3
    crowd_fraction: float = 0.5
    crowd_width_s: float = 300.0
    # tenant_skew: Zipf exponent over tenant popularity
    skew: float = 1.5


@dataclasses.dataclass
class ChaosEvent:
    """A replica-plane fault in simulated time (the sim applies it):
    kind "stall" multiplies the victim's tick duration by `factor`
    for `duration_s`; kind "die" makes it drop its streams and fail
    probes until `duration_s` later (the breaker plane handles the
    rest)."""
    at: float
    replica: int                     # index into the sim's fleet
    kind: str = "stall"              # stall | die
    duration_s: float = 60.0
    factor: float = 10.0


def _rate_profile(cfg: TraceConfig) -> List[float]:
    """Relative arrival rate over _GRID equal bins of the trace."""
    if cfg.kind == "diurnal":
        a = min(max(cfg.diurnal_amplitude, 0.0), 1.0)
        return [1.0 + a * math.sin(2 * math.pi * (i / _GRID) * 1.0
                                   - math.pi / 2)
                for i in range(_GRID)]
    if cfg.kind == "flash_crowd":
        base = [1.0] * _GRID
        width = max(int(cfg.crowd_width_s / cfg.duration_s * _GRID),
                    1)
        # crowd centers are structural (evenly spread, deterministic
        # in config alone) so the burst mass is independent of the
        # per-session RNG stream
        per = (cfg.crowd_fraction / max(1.0 - cfg.crowd_fraction,
                                        1e-6)) * _GRID / max(
            cfg.crowds * width, 1)
        for k in range(cfg.crowds):
            center = int((k + 0.5) / max(cfg.crowds, 1) * _GRID)
            for i in range(center - width // 2,
                           center + (width + 1) // 2):
                if 0 <= i < _GRID:
                    base[i] += per
        return base
    # steady / tenant_skew: flat arrivals (skew lives in identity)
    return [1.0] * _GRID


def _tenant_weights(cfg: TraceConfig) -> List[float]:
    if cfg.kind == "tenant_skew":
        w = [1.0 / (i + 1) ** cfg.skew for i in range(cfg.tenants)]
    else:
        w = [1.0] * cfg.tenants
    total = sum(w)
    return [x / total for x in w]


def _cum(weights: List[float]) -> List[float]:
    out: List[float] = []
    acc = 0.0
    for w in weights:
        acc += w
        out.append(acc)
    return out


def generate(cfg: TraceConfig) -> Iterator[SimSession]:
    """The trace: cfg.sessions SimSessions in arrival order."""
    rng = random.Random(cfg.seed)
    profile = _rate_profile(cfg)
    cum = _cum(profile)
    total = cum[-1]
    tcum = _cum(_tenant_weights(cfg))
    bin_w = cfg.duration_s / _GRID
    n = cfg.sessions
    for i in range(n):
        # stratified inverse-CDF: session i lands in the quantile
        # band [i/n, (i+1)/n) of the rate profile — arrival order is
        # construction-sorted, no sort of a million records needed
        u = (i + rng.random()) / n * total
        b = min(bisect.bisect_left(cum, u), _GRID - 1)
        frac = (u - (cum[b - 1] if b else 0.0)) \
            / max(profile[b], 1e-12)
        at = (b + min(frac, 1.0)) * bin_w
        tv = rng.random()
        tenant = f"t{bisect.bisect_left(tcum, tv * tcum[-1])}"
        # sizes: geometric-ish tails clipped to the max
        prompt = min(1 + int(rng.expovariate(
            1.0 / max(cfg.prompt_tokens_mean, 1))),
            cfg.prompt_tokens_max)
        out = min(1 + int(rng.expovariate(
            1.0 / max(cfg.out_tokens_mean, 1))),
            cfg.out_tokens_max)
        group = rng.randrange(cfg.prefix_groups)
        yield SimSession(at, tenant, group, prompt, out,
                         INTERACTIVE, sid=i)


def batch_backlog(count: int, out_tokens: int = 32,
                  prompt_tokens: int = 32, at: float = 0.0,
                  group_base: int = 1_000_000) -> List[SimSession]:
    """A bulk-inference backlog submitted up front (the sim's batch
    lane input): `count` priority-0 sessions all arriving at `at` —
    the soak governor and preemption plane decide when they actually
    run."""
    return [SimSession(at, "batch", group_base + i, prompt_tokens,
                       out_tokens, BATCH, sid=-(i + 1))
            for i in range(count)]


class RecordedTrace:
    """A production capture as a simulator trace (ISSUE 20 — the
    ROADMAP item 5 'trace replay from recorded production traffic'
    REMAINS, closed).

    Wraps a decoded `trafficlog` capture (the dict from
    `decode_capture`/`load_capture`, or raw capture text/bytes) and
    yields `SimSession`s in non-decreasing arrival order:

    - `at` = (record monotonic arrival − capture mono anchor) /
      `speed` — `speed` is the time-warp knob (2.0 replays the
      capture at twice the recorded density);
    - `group` = the recorded prefix fingerprint folded to a stable
      int, so the sim router's consistent-hash affinity sees the SAME
      prefix-chain structure production saw;
    - token counts / tenant / lane pass straight through (records
      with no measured token counts fall back to 1 — a shed request
      still arrived and must still load the front door).

    Deterministic by construction: no RNG, no wall clock — the same
    capture bytes always yield the identical session stream, which
    extends the simulator's byte-identical-summary gate to recorded
    workloads."""

    def __init__(self, capture: Any, speed: float = 1.0,
                 include_rejected: bool = True):
        if isinstance(capture, (str, bytes)):
            from ..trafficlog import decode_capture
            capture = decode_capture(capture)
        self.header: Dict[str, Any] = capture["header"]
        self.records: List[Dict[str, Any]] = list(capture["records"])
        self.capture_id: str = str(self.header.get("capture_id", ""))
        self.speed = max(float(speed), 1e-9)
        self.include_rejected = include_rejected

    @staticmethod
    def group_of(fp: str) -> int:
        """Prefix fingerprint → stable sim routing group (the first
        8 hex chars; non-hex/empty fingerprints collapse to 0)."""
        try:
            return int(str(fp)[:8], 16)
        except ValueError:
            return 0

    def __len__(self) -> int:
        return len(self.records)

    def __iter__(self) -> Iterator[SimSession]:
        anchor = float(self.header.get("mono_anchor") or 0.0)
        sessions: List[SimSession] = []
        for i, r in enumerate(self.records):
            status = str((r.get("outcome") or {}).get("status", "ok"))
            if (not self.include_rejected
                    and status.startswith("rejected")):
                continue
            at = max(float(r.get("t_mono") or anchor) - anchor, 0.0) \
                / self.speed
            lane = BATCH if r.get("lane") == BATCH else INTERACTIVE
            sessions.append(SimSession(
                at,
                str(r.get("tenant") or "") or "default",
                self.group_of(r.get("fp") or ""),
                max(int(r.get("prompt_tokens") or 0), 1),
                max(int(r.get("out_tokens") or 0), 1),
                lane, sid=i))
        # arrivals were recorded under concurrency: dispatch order at
        # the ingress need not be monotone in t0, so sort (stable —
        # ties keep record order) to satisfy the generator contract
        sessions.sort(key=lambda s: s.at)
        return iter(sessions)


def chaos_overlay(cfg: TraceConfig, replicas: int, events: int = 2,
                  kind: str = "stall",
                  duration_s: float = 120.0,
                  factor: float = 10.0,
                  seed: Optional[int] = None) -> List[ChaosEvent]:
    """Seeded fault schedule over the trace span (deterministic, and
    independent of the arrival RNG stream so layering chaos does not
    reshuffle the traffic)."""
    rng = random.Random(cfg.seed + 0x5EED if seed is None else seed)
    out = [ChaosEvent(
        at=rng.uniform(0.1, 0.8) * cfg.duration_s,
        replica=rng.randrange(max(replicas, 1)),
        kind=kind, duration_s=duration_s, factor=factor)
        for _ in range(events)]
    out.sort(key=lambda e: e.at)
    return out


__all__ = ["SimSession", "TraceConfig", "ChaosEvent", "generate",
           "batch_backlog", "chaos_overlay", "RecordedTrace",
           "INTERACTIVE", "BATCH"]
