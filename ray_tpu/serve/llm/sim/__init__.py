"""serve.llm.sim — the million-session fleet simulator (ISSUE 14).

A seeded discrete-event simulator (virtual clock + event heap) that
drives the REAL fleet policy objects — `FleetRouter`,
`AdmissionController`, `FleetAutoscaler`, `SLOBurnWatchdog`,
`CircuitBreaker` — in virtual time, against synthetic replica engines
whose tick/prefill/preemption timing is calibrated from the measured
engine (`stats()["tick_times"]` + the PR 11 PerfSample window, via
`tools/simcal`). Millions of sessions of diurnal / flash-crowd /
tenant-skew / chaos traffic replay in seconds of host time; runs are
byte-identical per seed; capacity-planning curves (replicas vs p99
TTFT) emit as JSON artifacts.

The headroom the curves reveal is harvested by the batch lane
(serve/llm/batch.py) — which the simulator also models, so batch-soak
policies can be tuned at a million sessions before they ever touch a
real fleet. BENCH_CORE.md "Traffic simulation anatomy" documents the
model and its fidelity gates.
"""

from __future__ import annotations

from .calibration import (CALIBRATION_BAND,  # noqa: F401
                          SimCalibration, default_cpu_calibration)
from .capacity import capacity_curve, write_artifact  # noqa: F401
from .core import (FleetSimulator, SimFleetConfig,  # noqa: F401
                   VirtualClock, assert_slos)
from .replica import Hist, SyntheticReplica  # noqa: F401
from .traffic import (BATCH, INTERACTIVE, ChaosEvent,  # noqa: F401
                      RecordedTrace, SimSession, TraceConfig,
                      batch_backlog, chaos_overlay, generate)

__all__ = [
    "FleetSimulator", "SimFleetConfig", "VirtualClock", "assert_slos",
    "SimCalibration", "default_cpu_calibration", "CALIBRATION_BAND",
    "SyntheticReplica", "Hist",
    "TraceConfig", "SimSession", "ChaosEvent", "generate",
    "batch_backlog", "chaos_overlay", "RecordedTrace",
    "INTERACTIVE", "BATCH",
    "capacity_curve", "write_artifact",
]
