"""SyntheticReplica: a continuous-batching engine in closed form.

The real engine's steady state is one ragged dispatch per tick, every
decoding slot emitting one token per tick (PR 1). That invariant is
what makes a fast simulation possible: a session's SERVICE demand is
exact in tick-index space — `prefill_ticks(prompt) + out_tokens`
ticks from slot admission — and only the tick DURATION varies (with
batch size and prefill load, drawn from the calibration's measured
percentiles). So the replica keeps a tick-index clock:

    tick(t) = tick(t0) + (t - t0) / tick_ms(current membership)

advanced lazily at every membership change; completions live in a
heap keyed by tick index (which never changes once assigned —
membership changes move their WALL time, not their tick), and the
wall estimate for the earliest completion is recomputed on demand.
One admission, one completion, and O(1) bookkeeping per session —
millions of sessions replay in seconds of host time.

Fidelity shortcuts (all verified against the real engine by the
sim-vs-real calibration band): TTFT is estimated at slot admission
(queue wait + prefill ticks at the then-current tick duration)
rather than evented; concurrent prefills share the chunk budget only
through the tick surcharge; KV pages reserve prompt+out up front
with hash-group prefix sharing.

The batch lane (ISSUE 14) is modeled with the engine's real policy:
priority-0 sessions admit only through free capacity, an interactive
arrival preempts the youngest batch slot when slots/pages are short
(spill latency charged from the calibration), and parked batch work
restores FIFO once no interactive request waits.
"""

from __future__ import annotations

import bisect
import heapq
import random
import zlib
from typing import Any, Dict, List, Optional

from .calibration import SimCalibration
from .traffic import BATCH, SimSession


class Hist:
    """Fixed log-spaced latency histogram (seconds in, deterministic
    percentiles out) — the summary's p50/p95/p99 source."""

    __slots__ = ("bins", "counts", "n", "total")

    _EDGES: List[float] = [1e-4 * (1.15 ** i) for i in range(180)]

    def __init__(self):
        self.counts = [0] * (len(self._EDGES) + 1)
        self.n = 0
        self.total = 0.0

    def add(self, v: float) -> None:
        self.counts[bisect.bisect_left(self._EDGES, v)] += 1
        self.n += 1
        self.total += v

    def pctl(self, q: float) -> float:
        if not self.n:
            return 0.0
        want = max(int(q * (self.n - 1) + 0.5), 0)
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc > want:
                return (self._EDGES[i] if i < len(self._EDGES)
                        else self._EDGES[-1])
        return self._EDGES[-1]

    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0

    def summary_ms(self) -> Dict[str, float]:
        return {"n": self.n,
                "mean_ms": round(self.mean() * 1e3, 3),
                "p50_ms": round(self.pctl(0.50) * 1e3, 3),
                "p95_ms": round(self.pctl(0.95) * 1e3, 3),
                "p99_ms": round(self.pctl(0.99) * 1e3, 3)}


class _Live:
    """One session holding a slot (or parked)."""

    __slots__ = ("sess", "enqueued_at", "admit_wall", "first_tick",
                 "done_tick", "pages", "version", "ttft_wall",
                 "remaining")

    def __init__(self, sess: SimSession):
        self.sess = sess
        self.enqueued_at = sess.at
        self.admit_wall = 0.0
        self.first_tick = 0.0
        self.done_tick = 0.0
        self.pages = 0
        self.version = 0
        self.ttft_wall = 0.0
        self.remaining = sess.out_tokens


class SyntheticReplica:
    """One replica's closed-form engine. The simulator owns the
    clock; every public method takes `now` (virtual seconds)."""

    def __init__(self, rid: str, calib: SimCalibration,
                 slots: int = 8, pages: int = 2048,
                 seed: int = 0, slo_targets: Optional[Dict[str,
                                                          float]] = None,
                 chips: int = 1):
        self.rid = rid
        self.calib = calib
        self.slots = slots
        self.num_pages = pages
        # slice topology (ISSUE 17): a tp-sharded replica spanning
        # `chips` chips runs each decode tick ~chips× faster (the
        # ragged dispatch is memory-bound, and tp shards the KV pool
        # and weight reads over heads), so the calibration's
        # single-chip tick duration divides by the slice size. The
        # per-tick collective tax is in the calibration when it was
        # measured on a sliced engine; this factor models topology
        # what-ifs on a single-chip calibration.
        self.chips = max(int(chips), 1)
        # crc32, not hash(): string hashing is salted per process and
        # would break the byte-identical-summary determinism gate
        self.rng = random.Random(
            (seed * 1_000_003) ^ zlib.crc32(rid.encode()))
        self.slo = {"ttft": 2.0, "queue_wait": 0.5, "e2e": 30.0,
                    **(slo_targets or {})}
        # tick-index clock
        self.tick = 0.0
        self.anchor = 0.0
        self.tick_ms = calib.tick_point(1, "p50") / self.chips
        # per-bucket (p50,p95,p99) memo: tick_point re-derives the
        # bucket and string keys on every call, and _retick runs ~3x
        # per session — at 1M sessions the lookup is the hot loop
        self._tick_pts: Dict[int, tuple] = {}
        # membership
        self.active: Dict[int, _Live] = {}      # sid -> live
        self.waiting: List[_Live] = []          # FIFO (deque-free:
        #                                         index head)
        self._wait_head = 0
        self.parked: List[_Live] = []           # preempted batch, FIFO
        self.used_pages = 0
        self._warm_groups: set = set()
        # (first_tick, tokens/tick) marks of in-flight prefills: the
        # running token sum feeds the tick-duration surcharge and
        # decays as the marks pass (lazily, at each retick)
        self._prefill_ticks_heap: List[tuple] = []
        self._prefill_token_load = 0.0
        self._done_heap: List[tuple] = []   # (done_tick, sid, version)
        # chaos
        self.stall_factor = 1.0
        self.dead = False
        # wake scheduling (core-managed)
        self.wake_version = 0
        self.scheduled_wall: Optional[float] = None
        # accounting (monotone; the control plane deltas them)
        self.slo_totals = {k: 0.0 for k in
                           ("ttft_s", "ttft_n", "ttft_bad", "queue_s",
                            "queue_n", "queue_bad", "e2e_s", "e2e_n",
                            "e2e_bad")}
        self.completed = 0
        self.decode_tokens = 0
        self.batch_tokens = 0
        self.batch_completed = 0
        self.preemptions = 0
        self.spills = 0
        self.restores = 0
        self.cache_hits = 0
        self.cache_queries = 0

    # -- clock ---------------------------------------------------------
    def _advance(self, now: float) -> None:
        # never REWIND the anchor: spill/restore charge their latency
        # by pushing it past now (a one-off stall) — snapping back to
        # now would erase the penalty in the same virtual instant and
        # make preemption churn free
        if now > self.anchor:
            if self.active:
                self.tick += (now - self.anchor) * 1e3 \
                    / (self.tick_ms * self.stall_factor)
            self.anchor = now

    def _eps(self) -> float:
        """Due tolerance, scaled with the tick index: at ~3e7 ticks
        (a simulated day) double-precision ulp is ~7e-9 ticks, so a
        fixed epsilon would leave events perpetually "almost due"
        and the wake loop spinning at one virtual instant."""
        return 1e-9 + 1e-11 * self.tick

    def _prefill_tokens(self) -> float:
        h = self._prefill_ticks_heap
        eps = self._eps()
        while h and h[0][0] <= self.tick + eps:
            self._prefill_token_load -= heapq.heappop(h)[1]
        if not h:
            self._prefill_token_load = 0.0    # drift backstop
        return self._prefill_token_load

    def _retick(self) -> None:
        """Membership changed: redraw the current tick duration from
        the calibration — batch size plus the prefill tokens riding
        the tick (capped at the engine's chunk budget, exactly as the
        Sarathi packer would). Same mixture as
        `SimCalibration.draw_tick_ms` (90% body / 8% p95 shoulder /
        2% p99 tail), with the percentile points memoized per bucket."""
        b = len(self.active) or 1
        pre = self._prefill_tokens()
        if pre > self.calib.prefill_chunk_tokens:
            pre = self.calib.prefill_chunk_tokens
        pts = self._tick_pts.get(b)
        if pts is None:
            pts = (self.calib.tick_point(b, "p50"),
                   self.calib.tick_point(b, "p95"),
                   self.calib.tick_point(b, "p99"))
            self._tick_pts[b] = pts
        u = self.rng.random()
        ms = (pts[0 if u < 0.90 else (1 if u < 0.98 else 2)]
              + pre * self.calib.prefill_ms_per_token) / self.chips
        self.tick_ms = ms if ms > 1e-3 else 1e-3

    # -- pages ---------------------------------------------------------
    def _pages_for(self, sess: SimSession) -> int:
        page = max(self.calib.page_size, 1)
        total = (sess.prompt_tokens + sess.out_tokens
                 + page - 1) // page
        self.cache_queries += 1
        if sess.group in self._warm_groups:
            self.cache_hits += 1
            shared = max((sess.prompt_tokens - 1) // page, 0)
            return max(total - shared, 1)
        return max(total, 1)

    @property
    def free_pages(self) -> int:
        return self.num_pages - self.used_pages

    def occupancy(self) -> float:
        return self.used_pages / self.num_pages \
            if self.num_pages else 0.0

    def batch_occupancy(self) -> float:
        """Fraction of the pool held by batch-lane slots (the
        autoscaler's displaceable-occupancy exclusion)."""
        if not self.num_pages:
            return 0.0
        return sum(lv.pages for lv in self.active.values()
                   if lv.sess.lane == BATCH) / self.num_pages

    def interactive_occupancy(self) -> float:
        return max(self.occupancy() - self.batch_occupancy(), 0.0)

    def page_pressure(self) -> float:
        parked = sum(lv.pages for lv in self.parked)
        return (self.used_pages + parked) / self.num_pages \
            if self.num_pages else 0.0

    # -- queue/slots ---------------------------------------------------
    def _waitq(self) -> List[_Live]:
        if self._wait_head > 64 \
                and self._wait_head * 2 > len(self.waiting):
            self.waiting = self.waiting[self._wait_head:]
            self._wait_head = 0
        return self.waiting

    def waiting_count(self) -> int:
        return len(self.waiting) - self._wait_head

    def waiting_batch_count(self) -> int:
        return sum(1 for i in range(self._wait_head,
                                    len(self.waiting))
                   if self.waiting[i].sess.lane == BATCH)

    def active_batch_count(self) -> int:
        return sum(1 for lv in self.active.values()
                   if lv.sess.lane == BATCH)

    def enqueue(self, sess: SimSession, now: float) -> None:
        lv = _Live(sess)
        lv.enqueued_at = now
        self.waiting.append(lv)
        self._fill(now)

    def _head(self) -> Optional[_Live]:
        return (self.waiting[self._wait_head]
                if self._wait_head < len(self.waiting) else None)

    def _fill(self, now: float) -> None:
        """The engine's admission loop in miniature: restore parked
        batch work first UNLESS an interactive request waits (the
        ISSUE 14 inversion guard), then head-of-line admission, with
        priority preemption when the interactive head finds the
        slots/pages held by batch work."""
        self._advance(now)
        changed = False
        while True:
            head = self._head()
            interactive_waiting = (head is not None
                                   and head.sess.lane != BATCH)
            # parked-first restore (PR 10), yielding to interactive
            if self.parked and not interactive_waiting \
                    and len(self.active) < self.slots:
                lv = self.parked[0]
                if lv.pages > self.free_pages:
                    break
                self.parked.pop(0)
                self._restore(lv, now)
                changed = True
                continue
            if head is None:
                break
            if len(self.active) >= self.slots:
                if not self._preempt_for(head):
                    break
                changed = True
            pages = self._pages_for(head.sess)
            while pages > self.free_pages \
                    and self._preempt_for(head):
                changed = True
            if pages > self.free_pages:
                break                     # head-of-line blocking
            self._wait_head += 1
            self._waitq()
            self._admit(head, pages, now)
            changed = True
        if changed:
            self._retick()

    def _preempt_for(self, head: _Live) -> bool:
        """Spill the designated victim (lowest priority, youngest —
        batch lane only carries priority 0 vs interactive 1) when the
        head strictly outranks it."""
        victims = [lv for lv in self.active.values()
                   if lv.sess.lane == BATCH]
        if head.sess.lane == BATCH or not victims:
            return False
        victim = max(victims, key=lambda lv: lv.admit_wall)
        sid = victim.sess.sid
        del self.active[sid]
        victim.version += 1
        # decrement the CURRENT remaining (a restored session's
        # first_tick was re-anchored at its restore): resetting from
        # out_tokens on a second preemption would double-count every
        # token decoded before the first one
        done = min(max(int(self.tick - victim.first_tick), 0),
                   victim.remaining)
        victim.remaining = max(victim.remaining - done, 1)
        self.decode_tokens += done
        self.batch_tokens += done
        self.used_pages -= victim.pages
        self.parked.append(victim)
        self.preemptions += 1
        self.spills += 1
        # the spill's gather latency lands as a one-off stall: the
        # anchor moves forward, so the next ticks start that late
        self.anchor += self.calib.spill_ms * 1e-3
        return True

    def _admit(self, lv: _Live, pages: int, now: float) -> None:
        sess = lv.sess
        lv.pages = pages
        lv.admit_wall = now
        self.used_pages += pages
        self._warm_groups.add(sess.group)
        pticks = self.calib.prefill_ticks(sess.prompt_tokens)
        lv.first_tick = self.tick + pticks
        lv.done_tick = lv.first_tick + lv.remaining
        per_tick = sess.prompt_tokens / pticks
        heapq.heappush(self._prefill_ticks_heap,
                       (lv.first_tick, per_tick))
        self._prefill_token_load += per_tick
        self.active[sess.sid] = lv
        heapq.heappush(self._done_heap,
                       (lv.done_tick, sess.sid, lv.version))
        # queue-wait + estimated TTFT recorded here (see module doc)
        queue_wait = max(now - lv.enqueued_at, 0.0)
        ttft = max(now - sess.at, 0.0) \
            + pticks * self.tick_ms * self.stall_factor * 1e-3
        lv.ttft_wall = sess.at + ttft
        if sess.lane != BATCH:
            t = self.slo_totals
            t["queue_s"] += queue_wait
            t["queue_n"] += 1
            if queue_wait > self.slo["queue_wait"]:
                t["queue_bad"] += 1
            t["ttft_s"] += ttft
            t["ttft_n"] += 1
            if ttft > self.slo["ttft"]:
                t["ttft_bad"] += 1

    def _restore(self, lv: _Live, now: float) -> None:
        """Re-admit a parked batch session token-exact: no prefill
        (its KV restores), remaining tokens only."""
        lv.version += 1
        lv.admit_wall = now
        self.used_pages += lv.pages
        lv.first_tick = self.tick
        lv.done_tick = self.tick + lv.remaining
        self.active[lv.sess.sid] = lv
        heapq.heappush(self._done_heap,
                       (lv.done_tick, lv.sess.sid, lv.version))
        self.restores += 1
        self.anchor += self.calib.restore_ms * 1e-3

    # -- completions ---------------------------------------------------
    def wake(self, now: float, ttft_hist: Hist, itl_hist: Hist,
             e2e_hist: Hist) -> List[SimSession]:
        """Advance to `now`, retire every due completion, refill.
        Returns the finished sessions (the core releases admission
        and counts them)."""
        self._advance(now)
        finished: List[SimSession] = []
        h = self._done_heap
        changed = False
        eps = self._eps()
        while h and h[0][0] <= self.tick + eps:
            done_tick, sid, version = heapq.heappop(h)
            lv = self.active.get(sid)
            if lv is None or lv.version != version:
                continue                    # preempted/stale entry
            del self.active[sid]
            self.used_pages -= lv.pages
            sess = lv.sess
            self.completed += 1
            self.decode_tokens += lv.remaining
            e2e = max(now - sess.at, 0.0)
            if sess.lane == BATCH:
                self.batch_tokens += lv.remaining
                self.batch_completed += 1
            else:
                t = self.slo_totals
                t["e2e_s"] += e2e
                t["e2e_n"] += 1
                if e2e > self.slo["e2e"]:
                    t["e2e_bad"] += 1
                ttft = max(lv.ttft_wall - sess.at, 0.0)
                ttft_hist.add(ttft)
                e2e_hist.add(e2e)
                if sess.out_tokens > 1:
                    itl_hist.add(max(now - lv.ttft_wall, 0.0)
                                 / (sess.out_tokens - 1))
            finished.append(sess)
            changed = True
        if changed or self.waiting_count() or self.parked:
            self._fill(now)
        if changed:
            self._retick()
        elif self._prefill_ticks_heap \
                and self._prefill_ticks_heap[0][0] \
                <= self.tick + eps:
            # the wake was a prefill-surcharge expiry: no membership
            # change, but the tick duration must relax NOW — without
            # this, a burst's prefill tax would linger on every
            # decode tick until the next completion (the sim-vs-real
            # band catches exactly this over-prediction), and a due
            # mark left unpopped would re-fire this wake at the same
            # virtual instant forever
            self._retick()
        return finished

    def next_wall(self, now: float) -> Optional[float]:
        """Wall estimate of the earliest event — a completion OR a
        prefill-surcharge expiry (None = idle). An early wake
        self-corrects: wake() simply reschedules."""
        h = self._done_heap
        target: Optional[float] = None
        while h:
            done_tick, sid, version = h[0]
            lv = self.active.get(sid)
            if lv is None or lv.version != version:
                heapq.heappop(h)
                continue
            target = done_tick
            break
        pm = self._prefill_ticks_heap
        if pm and (target is None or pm[0][0] < target):
            target = pm[0][0]
        if target is None:
            return None
        self._advance(now)
        dt = max(target - self.tick, 0.0) \
            * self.tick_ms * self.stall_factor * 1e-3
        return now + dt

    # -- chaos / lifecycle --------------------------------------------
    def fail_all(self, now: float) -> List[SimSession]:
        """The replica died: every resident session (active, waiting,
        parked) is returned for the core to fail over elsewhere (the
        PR 9 replay path — progress is lost, the relay re-dispatches
        the full request)."""
        self._advance(now)
        out = [lv.sess for lv in self.active.values()]
        out += [self.waiting[i].sess
                for i in range(self._wait_head, len(self.waiting))]
        out += [lv.sess for lv in self.parked]
        self.active.clear()
        self.waiting = []
        self._wait_head = 0
        self.parked = []
        self._done_heap = []
        self._prefill_ticks_heap = []
        self._prefill_token_load = 0.0
        self.used_pages = 0
        return out

    def idle(self) -> bool:
        return (not self.active and not self.parked
                and self.waiting_count() == 0)

    # -- control-plane surface ----------------------------------------
    def snapshot_stats(self) -> Dict[str, Any]:
        """The fleet_stats subset ReplicaSnapshot.from_stats reads —
        the SAME wire shape a real replica reports, so the production
        router scores simulated replicas through its production
        parser."""
        return {
            "replica": self.rid,
            "chips": self.chips,
            "active": len(self.active),
            "waiting": self.waiting_count(),
            "waiting_batch": self.waiting_batch_count(),
            "active_batch": self.active_batch_count(),
            "kv_occupancy": self.occupancy(),
            "kv_occupancy_batch": self.batch_occupancy(),
            "free_pages": self.free_pages,
            "cache_hit_rate": (self.cache_hits
                               / max(self.cache_queries, 1)),
            "page_pressure": self.page_pressure(),
            "parked_sessions": len(self.parked),
            "kv_offload": True,
        }


__all__ = ["SyntheticReplica", "Hist"]
