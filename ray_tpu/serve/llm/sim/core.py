"""FleetSimulator: the discrete-event loop over the REAL policy
objects.

This is the point of the whole package (ROADMAP item 5): every fleet
control policy — `FleetRouter` ring walks, `AdmissionController`
stride queueing and SLO sheds, `FleetAutoscaler` hysteresis,
`SLOBurnWatchdog` multi-window burn + brownout, per-replica
`CircuitBreaker`s — runs here as the PRODUCTION object, imported from
its production module, constructed with the simulator's virtual
clock injected through the `clock=` parameter ISSUE 14 threaded in.
No forks, no monkeypatching: a policy bug the simulator finds is a
bug the fleet ships, and the tier-1 suite asserts the identity
(`sim.router.__class__ is serve.llm.FleetRouter`, etc.).

Only the replicas are synthetic (replica.py — closed-form continuous
batching calibrated from measured tick times), which is what lets a
million sessions of simulated traffic replay in seconds: the event
heap carries one arrival per session, one wake per completion batch,
and a control tick at the fleet's refresh cadence.

Determinism: same (trace config, sim config, seed) → byte-identical
`run()` summary. All randomness flows from seeded `random.Random`
streams (traffic + per-replica tick draws); the virtual clock is the
only time source; iteration orders are index-stable. The summary is
canonical JSON (`summary_json()`, sorted keys) so the gate is one
string compare.
"""

from __future__ import annotations

import dataclasses
import heapq
import json
from typing import Any, Dict, Iterable, List, Optional

from ..admission import (AdmissionConfig, AdmissionController,
                         AdmissionRejected)
from ..autoscaler import (AutoscaleConfig, FleetAutoscaler,
                          FleetMetrics)
from ..failover import CircuitBreaker, HealthConfig
from ..router import FleetRouter, ReplicaSnapshot, RouterConfig
from ..watchdog import SLOBurnWatchdog, WatchdogConfig
from .calibration import SimCalibration
from .replica import Hist, SyntheticReplica
from .traffic import BATCH, ChaosEvent, SimSession

ACTIVE = "ACTIVE"
DRAINING = "DRAINING"
STANDBY = "STANDBY"
UNHEALTHY = "UNHEALTHY"

_ARRIVE, _WAKE, _CONTROL, _CHAOS = 0, 1, 2, 3


class VirtualClock:
    __slots__ = ("t",)

    def __init__(self):
        self.t = 0.0

    def now(self) -> float:
        return self.t


@dataclasses.dataclass
class SimFleetConfig:
    """The simulated fleet's shape. Replica counts mirror
    FleetConfig (min active at start, max provisioned)."""
    replicas: int = 4
    min_replicas: int = 1
    slots_per_replica: int = 8
    pages_per_replica: int = 2048
    # slice topology (ISSUE 17): chips per replica slice — a
    # tp-sharded replica's decode tick runs ~chips× faster, and the
    # capacity sweep prices each operating point per chip
    chips_per_replica: int = 1
    calibration: Optional[SimCalibration] = None
    router: Optional[RouterConfig] = None
    admission: Optional[AdmissionConfig] = None
    autoscale: Optional[AutoscaleConfig] = None
    watchdog: Optional[WatchdogConfig] = None
    health: Optional[HealthConfig] = None
    slo_targets: Optional[Dict[str, float]] = None
    control_period_s: float = 1.0
    autoscale_period_s: float = 5.0
    seed: int = 0


class FleetSimulator:
    def __init__(self, trace: Iterable[SimSession],
                 config: SimFleetConfig,
                 batch_jobs: Optional[List[SimSession]] = None,
                 chaos: Optional[List[ChaosEvent]] = None):
        cfg = config
        self.cfg = cfg
        self.clock = VirtualClock()
        clk = self.clock.now
        calib = cfg.calibration or SimCalibration(
            decode_tick_ms={"1": {"p50": 1.0, "p95": 1.5,
                                  "p99": 2.5}})
        self._calib = calib
        # artifact provenance (ISSUE 20 satellite): a RecordedTrace
        # carries its capture id — the summary names which capture
        # (if any) produced it
        self._capture_id = getattr(trace, "capture_id", None)
        # ---- the PRODUCTION policy objects, virtual-clocked --------
        self.router = FleetRouter(cfg.router or RouterConfig(),
                                  clock=clk)
        self.admission = AdmissionController(
            cfg.admission or AdmissionConfig(), clock=clk)
        auto = cfg.autoscale or AutoscaleConfig(
            min_replicas=cfg.min_replicas,
            max_replicas=cfg.replicas)
        self.autoscaler = FleetAutoscaler(auto, clock=clk)
        self.watchdog = SLOBurnWatchdog(
            cfg.watchdog or WatchdogConfig(), clock=clk)
        health = cfg.health or HealthConfig()
        # ---- synthetic data plane ----------------------------------
        self.replicas: List[SyntheticReplica] = [
            SyntheticReplica(f"r{i}", calib,
                             slots=cfg.slots_per_replica,
                             pages=cfg.pages_per_replica,
                             seed=cfg.seed,
                             slo_targets=cfg.slo_targets,
                             chips=cfg.chips_per_replica)
            for i in range(cfg.replicas)]
        self.status = [ACTIVE if i < max(cfg.min_replicas, 1)
                       else STANDBY for i in range(cfg.replicas)]
        self.breakers = [CircuitBreaker(health, clock=clk)
                         for _ in range(cfg.replicas)]
        self._by_rid = {r.rid: i
                        for i, r in enumerate(self.replicas)}
        self._sync_ring()
        # ---- event plumbing ----------------------------------------
        self._trace = iter(trace)
        self._batch_jobs = list(batch_jobs or [])
        self._chaos = sorted(chaos or [], key=lambda e: e.at)
        self._heap: List[tuple] = []
        self._seq = 0
        self._pending: Dict[Any, SimSession] = {}   # ticket -> sess
        self._snapshots: Dict[str, ReplicaSnapshot] = {}
        self._inflight: Dict[str, int] = {r.rid: 0
                                          for r in self.replicas}
        self._session_replica: Dict[int, str] = {}
        self._dead_until = [0.0] * cfg.replicas
        self._stall_until = [0.0] * cfg.replicas
        self._prev_slo: Dict[str, Dict[str, float]] = {}
        self._prev_shed = 0
        self._watch_accum = {k: 0.0 for k in
                             ("ttft_n", "ttft_bad", "queue_n",
                              "queue_bad", "e2e_n", "e2e_bad")}
        self._watch_prev: Dict[str, Dict[str, float]] = {}
        # ---- results -----------------------------------------------
        self.ttft = Hist()
        self.itl = Hist()
        self.e2e = Hist()
        self.front_wait = Hist()
        self.counts = {"arrived": 0, "admitted": 0, "completed": 0,
                       "failed_over": 0, "batch_submitted": 0,
                       "batch_completed": 0}
        self.shed: Dict[str, int] = {}
        self.per_tenant: Dict[str, int] = {}
        self.scale_events = 0
        self.active_minmax = [len(self._ring_ids()),
                              len(self._ring_ids())]
        self.pages_seen = 0
        self.evictions = 0
        self.readmissions = 0

    # -- membership ----------------------------------------------------
    def _ring_ids(self) -> List[str]:
        return [r.rid for i, r in enumerate(self.replicas)
                if self.status[i] == ACTIVE]

    def _sync_ring(self) -> None:
        self.router.set_replicas(self._ring_ids())

    # -- event heap ----------------------------------------------------
    def _push(self, t: float, kind: int, data: Any) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (t, self._seq, kind, data))

    def _schedule_wake(self, idx: int) -> None:
        rep = self.replicas[idx]
        nxt = rep.next_wall(self.clock.t)
        if nxt is None:
            rep.scheduled_wall = None
            return
        if rep.scheduled_wall is not None \
                and rep.scheduled_wall <= nxt + 1e-9:
            return             # an earlier (or equal) wake is pending
        rep.wake_version += 1
        rep.scheduled_wall = nxt
        self._push(nxt, _WAKE, (idx, rep.wake_version))

    # -- request path --------------------------------------------------
    def _route(self, sess: SimSession) -> Optional[int]:
        rid, _ = self.router.pick_ex(f"g{sess.group}",
                                     self._snapshots,
                                     self._inflight)
        return None if rid is None else self._by_rid[rid]

    def _dispatch(self, sess: SimSession) -> None:
        idx = self._route(sess)
        if idx is None:
            self.shed["no_active_replicas"] = \
                self.shed.get("no_active_replicas", 0) + 1
            if sess.lane != BATCH:
                self.admission.release()
            return
        rep = self.replicas[idx]
        self._inflight[rep.rid] += 1
        self._session_replica[sess.sid] = rep.rid
        rep.enqueue(sess, self.clock.t)
        self._schedule_wake(idx)

    def _arrive(self, sess: SimSession) -> None:
        self.counts["arrived"] += 1
        self.per_tenant[sess.tenant] = \
            self.per_tenant.get(sess.tenant, 0) + 1
        if sess.lane == BATCH:
            # the batch lane bypasses the front door (ISSUE 14) —
            # its backpressure is engine-side priority queueing
            self.counts["batch_submitted"] += 1
            self._dispatch(sess)
            return
        try:
            ticket = self.admission.submit(sess.tenant,
                                           now=self.clock.t)
        except AdmissionRejected as e:
            self.shed[e.reason] = self.shed.get(e.reason, 0) + 1
            return
        self._pending[ticket] = sess
        self._drain_grants()

    def _drain_grants(self) -> None:
        for ticket in self.admission.granted_sync():
            sess = self._pending.pop(ticket, None)
            if sess is None:
                continue
            self.counts["admitted"] += 1
            self.front_wait.add(max(self.clock.t - sess.at, 0.0))
            self._dispatch(sess)

    def _complete(self, sess: SimSession, rid: str) -> None:
        self._inflight[rid] = max(self._inflight[rid] - 1, 0)
        self._session_replica.pop(sess.sid, None)
        self.counts["completed"] += 1
        if sess.lane == BATCH:
            self.counts["batch_completed"] += 1
        else:
            self.admission.release()
            self._drain_grants()

    # -- control plane -------------------------------------------------
    def _refresh(self) -> None:
        """The FleetManager.refresh() analogue: probe each replica,
        drive its breaker, stamp fresh snapshots."""
        now = self.clock.t
        for i, rep in enumerate(self.replicas):
            if self.status[i] == STANDBY:
                continue
            br = self.breakers[i]
            if not br.should_probe(now):
                continue
            if self._dead_until[i] > now:
                if br.record_failure(now):
                    self._evict(i)
                continue
            closed = br.record_success(now)
            self._snapshots[rep.rid] = ReplicaSnapshot.from_stats(
                rep.snapshot_stats())
            self._snapshots[rep.rid].mono_ts = now
            if closed and self.status[i] == UNHEALTHY:
                self.status[i] = ACTIVE
                self.readmissions += 1
                self._sync_ring()

    def _evict(self, idx: int) -> None:
        if self.status[idx] != ACTIVE:
            return
        others = [r for r in self._ring_ids()
                  if r != self.replicas[idx].rid]
        if not others:
            # last-replica guard (fleet.py): activate a standby
            for j, st in enumerate(self.status):
                if st == STANDBY:
                    self.status[j] = ACTIVE
                    break
            else:
                return
        self.status[idx] = UNHEALTHY
        self.evictions += 1
        self._sync_ring()
        # fail the resident sessions over (PR 9 replay semantics)
        rep = self.replicas[idx]
        for sess in rep.fail_all(self.clock.t):
            self._inflight[rep.rid] = max(
                self._inflight[rep.rid] - 1, 0)
            self.counts["failed_over"] += 1
            self._dispatch(sess)

    def _watch_totals(self) -> Dict[str, float]:
        # per-replica clamped deltas into fleet-monotone totals, the
        # FleetManager._watchdog_totals discipline (synthetic
        # replicas never restart, but DRAINING->ACTIVE cycles reuse
        # the same accumulators)
        accum = self._watch_accum
        for rep in self.replicas:
            prev = self._watch_prev.get(rep.rid)
            tot = rep.slo_totals
            cur = {k: tot[k] for k in accum}
            if prev is None:
                for k in accum:
                    accum[k] += cur[k]
            else:
                for k in accum:
                    d = cur[k] - prev[k]
                    if d > 0:
                        accum[k] += d
            self._watch_prev[rep.rid] = cur
        return dict(accum)

    def _fleet_metrics(self) -> FleetMetrics:
        keys = ("ttft_s", "ttft_n", "queue_s", "queue_n")
        d = {k: 0.0 for k in keys}
        waiting = 0
        occ: List[float] = []
        pressure = 0.0
        for i, rep in enumerate(self.replicas):
            prev = self._prev_slo.get(rep.rid, {})
            cur = {k: rep.slo_totals[k] for k in keys}
            for k in keys:
                d[k] += max(cur[k] - prev.get(k, 0.0), 0.0)
            self._prev_slo[rep.rid] = cur
            if self.status[i] == ACTIVE:
                waiting += max(rep.waiting_count()
                               - rep.waiting_batch_count(), 0)
                # interactive occupancy only (the FleetManager
                # discipline): soaked batch pages are displaceable
                # and must not veto scale-down
                occ.append(rep.interactive_occupancy())
                pressure = max(pressure, rep.page_pressure())
        shed = (self.admission.shed_total
                + self.admission.rejected["queue_full"]
                + self.admission.rejected["brownout"])
        shed_delta = shed - self._prev_shed
        self._prev_shed = shed
        return FleetMetrics(
            ttft_ms=(d["ttft_s"] / d["ttft_n"] * 1e3
                     if d["ttft_n"] > 0 else 0.0),
            queue_wait_ms=(d["queue_s"] / d["queue_n"] * 1e3
                           if d["queue_n"] > 0 else 0.0),
            waiting=waiting,
            occupancy=(sum(occ) / len(occ) if occ else 0.0),
            shed_delta=shed_delta,
            slo_page=self.watchdog.paging,
            slo_burn=self.watchdog.max_burn,
            page_pressure=pressure,
            chips_per_slice=self.cfg.chips_per_replica)

    def _apply_target(self, target: int) -> None:
        active = [i for i, st in enumerate(self.status)
                  if st == ACTIVE]
        if target > len(active):
            for i, st in enumerate(self.status):
                if st == STANDBY and target > len(active):
                    self.status[i] = ACTIVE
                    active.append(i)
                    self.scale_events += 1
        elif target < len(active):
            # drain the emptiest first, never below one
            order = sorted(
                active,
                key=lambda i: (self._inflight[self.replicas[i].rid],
                               self.replicas[i].occupancy()))
            for i in order[:len(active) - target]:
                if len(self._ring_ids()) <= 1:
                    break
                self.status[i] = DRAINING
                self.scale_events += 1
        self._sync_ring()

    def _interactive_idle(self) -> bool:
        """FleetManager._interactive_idle analogue: no front-door
        tickets pending and no interactive session queued or decoding
        on any active replica (batch soak does not count)."""
        if self._pending:
            return False
        for i, rep in enumerate(self.replicas):
            if self.status[i] != ACTIVE:
                continue
            if any(lv.sess.lane != BATCH
                   for lv in rep.active.values()):
                return False
            if rep.waiting_count() - rep.waiting_batch_count() > 0:
                return False
        return True

    def _control(self) -> None:
        now = self.clock.t
        self._refresh()
        # watchdog + brownout (FleetManager.watchdog_tick analogue)
        self.watchdog.observe(self._watch_totals(), now,
                              idle=self._interactive_idle())
        pressure = 0.0
        spillable = True
        for i, rep in enumerate(self.replicas):
            if self.status[i] == ACTIVE:
                pressure = max(pressure, rep.page_pressure())
        self.watchdog.observe_pressure(pressure)
        shed_for_pressure = (self.watchdog.pressure_state == "high"
                             and not spillable)
        self.admission.set_page_pressure(pressure, spillable)
        self.admission.set_brownout(self.watchdog.paging
                                    or shed_for_pressure)
        # front-door SLO timer (acquire()'s asyncio timer analogue)
        for t in self.admission.shed_expired(now):
            sess = self._pending.pop(t, None)
            if sess is not None:
                self.shed["queue_wait_slo"] = \
                    self.shed.get("queue_wait_slo", 0) + 1
        self._drain_grants()
        # drained replicas park
        for i, st in enumerate(self.status):
            if st == DRAINING and self.replicas[i].idle() \
                    and self._inflight[self.replicas[i].rid] == 0:
                self.status[i] = STANDBY
        n_active = len([1 for st in self.status if st == ACTIVE])
        self.active_minmax[0] = min(self.active_minmax[0], n_active)
        self.active_minmax[1] = max(self.active_minmax[1], n_active)

    def _autoscale(self) -> None:
        active = len([1 for st in self.status if st == ACTIVE])
        target = self.autoscaler.decide(self._fleet_metrics(),
                                        active, self.clock.t)
        if target != active:
            self._apply_target(target)

    def _apply_chaos(self, ev: ChaosEvent) -> None:
        idx = ev.replica % len(self.replicas)
        rep = self.replicas[idx]
        if ev.kind == "die":
            self._dead_until[idx] = self.clock.t + ev.duration_s
            for sess in rep.fail_all(self.clock.t):
                self._inflight[rep.rid] = max(
                    self._inflight[rep.rid] - 1, 0)
                self.counts["failed_over"] += 1
                self._dispatch(sess)
        else:
            self._stall_until[idx] = self.clock.t + ev.duration_s
            rep.stall_factor = max(ev.factor, 1.0)
            self._push(self.clock.t + ev.duration_s, _CHAOS,
                       ("unstall", idx))
            self._schedule_wake(idx)

    # -- the loop ------------------------------------------------------
    def run(self, max_virtual_s: Optional[float] = None
            ) -> Dict[str, Any]:
        cfg = self.cfg
        for sess in self._batch_jobs:
            self._push(sess.at, _ARRIVE, sess)
        self._push(0.0, _CONTROL, None)
        for ev in self._chaos:
            self._push(ev.at, _CHAOS, ev)
        next_arrival = next(self._trace, None)
        last_autoscale = 0.0
        heap = self._heap
        while heap or next_arrival is not None:
            if next_arrival is not None and (
                    not heap or next_arrival.at <= heap[0][0]):
                self.clock.t = max(self.clock.t, next_arrival.at)
                self._arrive(next_arrival)
                next_arrival = next(self._trace, None)
                continue
            t, _, kind, data = heapq.heappop(heap)
            if max_virtual_s is not None and t > max_virtual_s \
                    and next_arrival is None:
                break
            self.clock.t = max(self.clock.t, t)
            if kind == _ARRIVE:
                # heap-scheduled arrivals (the batch backlog rides
                # here; trace arrivals stream from the iterator)
                self._arrive(data)
            elif kind == _WAKE:
                idx, version = data
                rep = self.replicas[idx]
                if rep.wake_version != version:
                    continue
                rep.scheduled_wall = None
                for sess in rep.wake(self.clock.t, self.ttft,
                                     self.itl, self.e2e):
                    self._complete(sess, rep.rid)
                self._schedule_wake(idx)
            elif kind == _CONTROL:
                self._control()
                if self.clock.t - last_autoscale \
                        >= cfg.autoscale_period_s:
                    last_autoscale = self.clock.t
                    self._autoscale()
                # stop ticking once the system has fully drained
                if (next_arrival is not None or heap
                        or any(not r.idle() for r in self.replicas)):
                    self._push(self.clock.t + cfg.control_period_s,
                               _CONTROL, None)
            elif kind == _CHAOS:
                if isinstance(data, tuple) and data[0] == "unstall":
                    idx = data[1]
                    if self._stall_until[idx] <= self.clock.t:
                        self.replicas[idx].stall_factor = 1.0
                        self._schedule_wake(idx)
                else:
                    self._apply_chaos(data)
        return self.summary()

    # -- results -------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        reps = self.replicas
        return {
            # artifact provenance (ISSUE 20 satellite): the exact
            # input set this summary is attributable to — the
            # calibration file by checksum, the RNG seed, and (for
            # replayed captures) the capture id
            "provenance": {
                "calibration": self._calib.name,
                "calibration_sha256": self._calib.checksum(),
                "seed": self.cfg.seed,
                "capture_id": self._capture_id,
            },
            "sim": {
                "seed": self.cfg.seed,
                "replicas": self.cfg.replicas,
                "min_replicas": self.cfg.min_replicas,
                "slots_per_replica": self.cfg.slots_per_replica,
                "pages_per_replica": self.cfg.pages_per_replica,
                "chips_per_replica": self.cfg.chips_per_replica,
                "virtual_s": round(self.clock.t, 3),
            },
            "sessions": dict(sorted(self.counts.items())),
            "shed": dict(sorted(self.shed.items())),
            "admission": {
                "admitted": self.admission.admitted,
                "rejected": dict(self.admission.rejected),
                "shed_total": self.admission.shed_total,
                "brownout": self.admission.brownout,
            },
            "latency": {
                "ttft": self.ttft.summary_ms(),
                "itl": self.itl.summary_ms(),
                "e2e": self.e2e.summary_ms(),
                "front_door_wait": self.front_wait.summary_ms(),
            },
            "router": self.router.stats(),
            "autoscale": {
                "events": self.scale_events,
                "active_min": self.active_minmax[0],
                "active_max": self.active_minmax[1],
                "final_active": len(self._ring_ids()),
            },
            "watchdog": {
                "paging": self.watchdog.paging,
                "alerts_total": self.watchdog.alerts_total,
                "state": dict(sorted(self.watchdog.state.items())),
            },
            "health": {
                "evictions": self.evictions,
                "readmissions": self.readmissions,
            },
            "batch": {
                "submitted": self.counts["batch_submitted"],
                "completed": self.counts["batch_completed"],
                "tokens": sum(r.batch_tokens for r in reps),
            },
            "engine": {
                "completed": sum(r.completed for r in reps),
                "decode_tokens": sum(r.decode_tokens for r in reps),
                "preemptions": sum(r.preemptions for r in reps),
                "spills": sum(r.spills for r in reps),
                "restores": sum(r.restores for r in reps),
            },
            "tenants": dict(sorted(self.per_tenant.items())),
        }

    def summary_json(self) -> str:
        """Canonical rendering — the determinism gate compares these
        byte-for-byte."""
        return json.dumps(self.summary(), sort_keys=True,
                          separators=(",", ":"))


def assert_slos(summary: Dict[str, Any],
                max_p99_ttft_s: Optional[float] = None,
                max_p99_itl_s: Optional[float] = None,
                max_shed_rate: Optional[float] = None,
                min_completion_rate: float = 0.99) -> None:
    """Fleet-level SLO assertions over a run summary (raises
    AssertionError naming the violated objective)."""
    s = summary["sessions"]
    interactive = s["arrived"] - s["batch_submitted"]
    done = s["completed"] - s["batch_completed"]
    shed = sum(summary["shed"].values())
    if interactive > 0:
        rate = (done + shed) / interactive
        assert rate >= min_completion_rate, (
            f"only {rate:.4f} of interactive sessions reached a "
            f"terminal state (completed {done} + shed {shed} of "
            f"{interactive})")
        if max_shed_rate is not None:
            assert shed / interactive <= max_shed_rate, (
                f"shed rate {shed / interactive:.4f} over "
                f"{max_shed_rate}")
    lat = summary["latency"]
    if max_p99_ttft_s is not None:
        got = lat["ttft"]["p99_ms"] / 1e3
        assert got <= max_p99_ttft_s, (
            f"p99 TTFT {got:.3f}s over {max_p99_ttft_s}s")
    if max_p99_itl_s is not None:
        got = lat["itl"]["p99_ms"] / 1e3
        assert got <= max_p99_itl_s, (
            f"p99 ITL {got:.3f}s over {max_p99_itl_s}s")


__all__ = ["FleetSimulator", "SimFleetConfig", "VirtualClock",
           "assert_slos"]
