"""Capacity-planning curves: replicas vs tail latency, as an artifact.

The question the Gemma-on-TPU serving study (PAPERS.md) asks of every
deployment — how many replicas until the p99 is bought? — answered by
sweeping the SAME trace over fleet sizes and emitting one JSON
artifact per sweep. `bench_llm --smoke` runs a small sweep as its sim
gate; operators point `python -m tools.simcal` at bigger ones.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional

from .core import FleetSimulator, SimFleetConfig
from .traffic import SimSession, TraceConfig, generate


def capacity_curve(trace_cfg: TraceConfig,
                   fleet_cfg: SimFleetConfig,
                   replica_counts: List[int],
                   batch_jobs: Optional[List[SimSession]] = None,
                   capture_id: Optional[str] = None
                   ) -> Dict[str, Any]:
    """Replay `trace_cfg` at each fleet size (fixed-size fleets: min
    = max = n, autoscaling off-axis so the curve isolates capacity)
    and collect the tail metrics. Deterministic like everything else
    here: the trace regenerates from its seed per point."""
    points: List[Dict[str, Any]] = []
    for n in replica_counts:
        cfg = dataclasses.replace(fleet_cfg, replicas=n,
                                  min_replicas=n)
        sim = FleetSimulator(generate(trace_cfg), cfg,
                             batch_jobs=list(batch_jobs or []))
        s = sim.run()
        lat = s["latency"]
        sessions = s["sessions"]
        shed = sum(s["shed"].values())
        # slice topology (ISSUE 17): price each operating point per
        # chip, not per replica — a 2-chip slice that doesn't halve
        # the tail is a capacity loss the per-replica view hides
        chips = n * max(fleet_cfg.chips_per_replica, 1)
        tokens = (s["engine"]["decode_tokens"]
                  + s["batch"]["tokens"])
        virtual_s = s["sim"]["virtual_s"]
        points.append({
            "replicas": n,
            "chips": chips,
            "p50_ttft_ms": lat["ttft"]["p50_ms"],
            "p99_ttft_ms": lat["ttft"]["p99_ms"],
            "p99_itl_ms": lat["itl"]["p99_ms"],
            "p99_e2e_ms": lat["e2e"]["p99_ms"],
            "shed": shed,
            "shed_rate": round(
                shed / max(sessions["arrived"]
                           - sessions["batch_submitted"], 1), 6),
            "completed": sessions["completed"],
            "batch_tokens": s["batch"]["tokens"],
            "tokens_per_chip_s": round(
                tokens / max(virtual_s, 1e-9) / chips, 3),
            "chip_s_per_1k_tokens": round(
                virtual_s * chips / max(tokens / 1e3, 1e-9), 3),
            "watchdog_alerts": s["watchdog"]["alerts_total"],
        })
    return {
        "object": "capacity_curve",
        "trace": dataclasses.asdict(trace_cfg),
        "fleet": {
            "slots_per_replica": fleet_cfg.slots_per_replica,
            "pages_per_replica": fleet_cfg.pages_per_replica,
            "chips_per_replica": fleet_cfg.chips_per_replica,
            "calibration": (fleet_cfg.calibration.name
                            if fleet_cfg.calibration else None),
        },
        # artifact provenance (ISSUE 20 satellite): the committed
        # artifact is attributable to exactly one input set
        "provenance": {
            "calibration": (fleet_cfg.calibration.name
                            if fleet_cfg.calibration else None),
            "calibration_sha256": (fleet_cfg.calibration.checksum()
                                   if fleet_cfg.calibration
                                   else None),
            "seed": fleet_cfg.seed,
            "capture_id": capture_id,
        },
        "points": points,
    }


def write_artifact(curve: Dict[str, Any], path: str) -> str:
    """Write the sweep as a canonical JSON artifact (sorted keys, so
    artifact diffs are meaningful across runs)."""
    with open(path, "w") as f:
        json.dump(curve, f, sort_keys=True, indent=2)
        f.write("\n")
    return path


__all__ = ["capacity_curve", "write_artifact"]
