"""ray_tpu.serve.llm: multi-replica LLM serving fleets (ISSUE 6).

Reference parity: python/ray/serve/llm — `serve.llm` is where the
reference composes its LLM engine with the serve deployment stack.
Here the single-replica surface (LLMConfig / build_openai_app,
re-exported from ray_tpu.llm) gains the fleet layer the ROADMAP's
"millions of users" item calls for:

- `FleetConfig` + `build_llm_fleet_app` — N `LLMServerImpl` engine
  replicas behind one ingress, deployed through `serve.run`
  (deployment.py);
- a continuous-batching-aware router: consistent-hash prefix affinity
  with load-based spillover over live KV-page occupancy and queue
  depth (router.py);
- a bounded admission front door: 429 + Retry-After backpressure,
  per-tenant weighted fair queueing (admission.py);
- a telemetry-driven autoscaler consuming PR 5's TTFT / queue-wait
  aggregates, with drain-before-downscale (autoscaler.py, fleet.py).

ISSUE 7 adds the fleet-wide observability layer:

- distributed request tracing: a trace context minted at ingress
  follows each request through admission → routing → replica engine
  lifecycle, merged (with Perfetto flow arrows) at
  `GET /fleet/debug/trace` with `?request_id=`/`?trace_id=` filters
  (tracemerge.py);
- an SLO burn-rate watchdog: multi-window error-budget burn over the
  replicas' slo_totals, paging pre-emptively into the autoscaler and
  admission brownout (watchdog.py);
- postmortem black-box bundles: guard violations, crashes, watchdog
  pages, and `POST /debug/dump` snapshot bounded on-disk bundles,
  listed/fetched at `GET /fleet/debug/bundles`
  (llm/_internal/blackbox.py).

ISSUE 9 adds the failure-handling plane:

- a per-replica health state machine: consecutive probe
  failures/timeouts open a circuit breaker and EVICT the replica
  from the router ring immediately; half-open probes after a
  (backed-off) cooldown decide re-admission (failover.py, fleet.py);
- token-exact mid-stream failover: a replica dying mid-stream is
  invisible to the client beyond latency — the fleet re-dispatches
  the original prompt + delivered tokens (same per-request sampling
  seed, indices deduped) to a healthy replica (failover.py);
- deadline propagation: a client `deadline_s` rides the body from
  ingress (expired → shed before queueing, 504) into the engine
  (aborted at fold boundaries, finish_reason="deadline");
- a deterministic, seeded chaos harness wrapping any replica client
  (call raises, stream severed after N chunks, probe timeouts, slow
  replicas) so all of the above is tier-1-testable on CPU (chaos.py).

ISSUE 12 adds the fleet KV transport — KV pages as a fleet-level
currency (kv_transport.py, one versioned checksummed wire format for
PR 10's ParkedSequence, three consumers):

- disaggregated prefill/decode: `FleetConfig.replica_roles` marks
  replicas prefill/decode/mixed; long prompts prefill on a prefill
  replica and the parked session ships to a decode replica that
  resumes it token-exact, so prompt-heavy bursts stop inflating
  decode ITL;
- live session migration: drain-before-downscale ships parked
  sessions instead of replaying tokens, and stream failover gains a
  failover-by-restore fast path when the victim can still export;
- a fleet prefix store: a system prompt prefilled once is published
  (`export_prefix`) and seeded into every replica that later serves
  the prefix, multiplying the per-replica prefix cache by fleet
  size. Every transport failure (severed ship, corrupted checksum,
  rejected import) degrades to the PR 9 replay path — token-exact
  either way.

ISSUE 14 closes the loop from measured cost model to fleet-scale
what-if analysis, then harvests what it finds:

- a million-session discrete-event fleet simulator (sim/): a seeded
  virtual clock + event heap driving the REAL FleetRouter /
  AdmissionController / FleetAutoscaler / SLOBurnWatchdog /
  CircuitBreaker objects (no forks — the injectable `clock=` on each
  is the whole integration) against synthetic replicas calibrated
  from PR 11's CostModel and measured tick-time distributions;
  diurnal / flash-crowd / tenant-skew / chaos traces, fleet SLO
  assertions, and capacity-planning curves (replicas vs p99 TTFT)
  as a JSON artifact;
- a preemptible batch-inference lane (batch.py): `POST /v1/batch`
  bulk jobs dispatched at priority 0 outside the admission queue,
  soaking idle capacity and preempted token-exact by interactive
  traffic via PR 10's spill/restore; the admission, autoscaler, and
  watchdog planes all EXCLUDE batch-lane depth from their overload
  and burn signals.

ISSUE 20 closes the loop from production back into the simulator:

- an always-on bounded traffic recorder at fleet ingress
  (trafficlog.py): one privacy-clean record per request (prefix
  fingerprint, token counts, sampling brief, outcome/latency brief —
  never prompt text) in a ring, sealable into a versioned
  checksummed capture (`GET/POST /fleet/debug/traffic`);
- deterministic trace replay: a capture replays through the fleet
  simulator (`sim.traffic.RecordedTrace`) or an in-process fleet via
  `python -m tools.tracereplay`, which emits a banded capture-diff
  (recorded vs replayed SLO histograms, prefix-hit rate, route mix,
  per-tenant rollups) and what-if re-pricing at overridden fleet
  shapes.

Scoring formula, admission thresholds, the autoscale policy, the
observability surface, the failure plane, the KV transport, the
traffic simulator, and the capture/replay plane are documented in
BENCH_CORE.md "Serving fleet anatomy", "Fleet observability
anatomy", "Fault tolerance anatomy", "KV transport anatomy",
"Traffic simulation anatomy" and "Traffic capture & replay anatomy".
"""

from __future__ import annotations

# the single-model serving surface lives in ray_tpu.llm; re-export
# ALL of it so `serve.llm` stays a strict superset — before ISSUE 6
# `serve.llm` WAS the ray_tpu.llm module, so every name in its
# __all__ must keep resolving here (reference: python/ray/serve/llm)
from ...llm import (ByteTokenizer, EngineConfig,  # noqa: F401
                    InferenceEngine, LLMConfig, Request,
                    SamplingParams, build_llm_deployment,
                    build_openai_app, load_tokenizer)

from .admission import (AdmissionConfig, AdmissionController,  # noqa: F401
                        AdmissionRejected)
from .autoscaler import (AutoscaleConfig, FleetAutoscaler,  # noqa: F401
                         FleetMetrics)
from .batch import (BATCH_PRIORITY, INTERACTIVE_PRIORITY,  # noqa: F401
                    BatchJob, BatchLane, BatchLaneConfig)
from .chaos import (ChaosError, ChaosReplicaClient,  # noqa: F401
                    ChaosSchedule, FaultSpec, StreamSevered)
from .deployment import (FleetConfig, LLMFleetIngressImpl,  # noqa: F401
                         build_llm_fleet_app)
from .failover import (CircuitBreaker, HealthConfig,  # noqa: F401
                       StreamTranscript)
from .fleet import (FleetManager, HandleReplicaClient,  # noqa: F401
                    LocalReplicaClient)
from .kv_transport import (FleetPrefixStore,  # noqa: F401
                           TransportChecksumError, TransportConfig,
                           TransportError, decode_prefix,
                           decode_session, encode_prefix,
                           encode_session)
from .router import (FleetRouter, HashRing, ReplicaSnapshot,  # noqa: F401
                     RouterConfig, prefix_fingerprint)
from .tracemerge import (IngressTraceBuffer,  # noqa: F401
                         filter_trace, merge_fleet_traces,
                         merge_flight_recorders)
from .trafficlog import (CaptureChecksumError,  # noqa: F401
                         CaptureError, TrafficRecorder,
                         decode_capture, load_capture,
                         sampling_brief, traffic_metrics)
from .watchdog import SLOBurnWatchdog, WatchdogConfig  # noqa: F401

__all__ = [
    # fleet layer
    "FleetConfig", "build_llm_fleet_app", "LLMFleetIngressImpl",
    "FleetManager", "LocalReplicaClient", "HandleReplicaClient",
    "FleetRouter", "RouterConfig", "ReplicaSnapshot", "HashRing",
    "prefix_fingerprint",
    "AdmissionConfig", "AdmissionController", "AdmissionRejected",
    "AutoscaleConfig", "FleetAutoscaler", "FleetMetrics",
    # failure-handling plane (ISSUE 9)
    "HealthConfig", "CircuitBreaker", "StreamTranscript",
    "ChaosSchedule", "ChaosReplicaClient", "ChaosError",
    "StreamSevered", "FaultSpec",
    # observability layer (ISSUE 7)
    "WatchdogConfig", "SLOBurnWatchdog", "IngressTraceBuffer",
    "merge_fleet_traces", "merge_flight_recorders", "filter_trace",
    # fleet KV transport (ISSUE 12)
    "TransportConfig", "TransportError", "TransportChecksumError",
    "FleetPrefixStore", "encode_session", "decode_session",
    "encode_prefix", "decode_prefix",
    # preemptible batch lane (ISSUE 14)
    "BatchLaneConfig", "BatchLane", "BatchJob",
    "BATCH_PRIORITY", "INTERACTIVE_PRIORITY",
    # traffic capture + replay (ISSUE 20)
    "TrafficRecorder", "CaptureError", "CaptureChecksumError",
    "decode_capture", "load_capture", "sampling_brief",
    "traffic_metrics",
    # single-model surface (ray_tpu.llm re-exports)
    "LLMConfig", "build_openai_app", "build_llm_deployment",
    "InferenceEngine", "EngineConfig", "SamplingParams", "Request",
    "ByteTokenizer", "load_tokenizer",
]
