"""Preemptible batch-inference lane for the serving fleet (ISSUE 14).

The capacity curves the traffic simulator (serve/llm/sim) emits all
show the same thing the Gemma-on-TPU serving study predicts: a fleet
provisioned for interactive p99 idles through its troughs. This module
harvests them — the Podracer priority-0 offline lane, grafted onto the
machinery PRs 6-13 already built:

- **Submission surface**: `POST /v1/batch` on the fleet ingress takes
  a JOB — a list of OpenAI completion/chat bodies — and returns a job
  id immediately; `GET /v1/batch` lists jobs, `GET /v1/batch/{id}`
  returns status + per-request results. No SSE, no client waiting on
  a socket: bulk inference is fire-and-collect (evals, synthetic
  data, Ray-Data-style pipelines, and the ISSUE-15 rollout farm).

- **Priority 0, admission-exempt**: batch requests dispatch through
  `FleetManager.dispatch(..., lane="batch")` — they skip the bounded
  front-door queue entirely (its SLO shed/brownout timers exist to
  bound USER-visible waits; a bulk job wants to wait out the rush),
  carry `Request.priority = BATCH_PRIORITY` (0) while the fleet
  stamps interactive traffic `INTERACTIVE_PRIORITY` (1), and so are
  exactly the sequences PR 10's spill/restore parks first: an
  interactive burst preempts them token-exact mid-decode and the
  trough restores them, byte-identical to never having yielded.

- **Soak governor**: the pump launches new batch streams only while
  the fleet shows headroom (front-door queue empty-ish, interactive
  engine queues shallow, KV occupancy under the bar, no brownout) and
  keeps at most `max_inflight` in flight — the lane fills idle
  capacity without ever being the thing that creates queueing.

- **Signal exclusion**: the engine excludes lane="batch" requests
  from the SLO sums the burn-rate watchdog differences; fleet_stats
  reports `waiting_batch`/`active_batch`, which FleetManager
  subtracts from the autoscaler's `waiting` overload signal and the
  router treats as displaceable load. A fleet soaking batch work to
  100% occupancy therefore still scales (and alerts) purely on its
  interactive traffic.

Pure host-side asyncio on the ingress loop — no jax, no device work.
"""

from __future__ import annotations

import asyncio
import dataclasses
import itertools
import time
from typing import Any, Callable, Dict, List, Optional

# the lane's priority tiers (ISSUE 14): batch jobs ride the engine's
# lowest tier — kv_offload.pick_victim preempts the LOWEST priority
# first — while the fleet stamps interactive bodies one tier up, so
# victim choice can never invert (engine-direct requests that name no
# priority land between sustained batch floods and fleet interactive
# traffic, which is the conservative order)
BATCH_PRIORITY = 0
INTERACTIVE_PRIORITY = 1

PENDING = "pending"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"


@dataclasses.dataclass
class BatchLaneConfig:
    """The batch lane's shape (FleetConfig.batch_lane; None = off)."""
    # concurrent batch streams in flight fleet-wide: small relative
    # to max_concurrent — the lane trickles into idle slots, it never
    # competes for the front door (which it bypasses)
    max_inflight: int = 2
    # jobs retained (finished included) before the oldest DONE job is
    # dropped from the listing
    max_jobs: int = 256
    # requests per job (bound the submission body)
    max_requests_per_job: int = 4096
    # -- soak governor: ALL must hold to launch another batch stream --
    # front-door admission queue at most this deep
    idle_queue_max: int = 0
    # fleet-wide INTERACTIVE engine-queue depth at most this
    idle_waiting_max: int = 0
    # mean KV occupancy over active replicas under this
    idle_occupancy_max: float = 0.85
    # pump cadence while work is pending
    poll_period_s: float = 0.02
    # re-dispatches per batch request before it fails (a preempted
    # request does NOT consume these — preemption resumes in-engine;
    # this covers replica loss beyond the relay's own failover)
    max_retries: int = 1


class BatchJob:
    __slots__ = ("job_id", "method", "bodies", "results", "errors",
                 "state", "created_at", "finished_at", "tenant",
                 "completed", "failed", "tokens")

    def __init__(self, job_id: str, method: str,
                 bodies: List[Dict[str, Any]], tenant: str,
                 created_at: float):
        self.job_id = job_id
        self.method = method               # "completions" | "chat"
        self.bodies = bodies
        self.results: List[Optional[Dict[str, Any]]] = \
            [None] * len(bodies)
        self.errors: List[Optional[str]] = [None] * len(bodies)
        self.state = PENDING
        self.created_at = created_at
        self.finished_at: Optional[float] = None
        self.tenant = tenant
        self.completed = 0
        self.failed = 0
        self.tokens = 0                    # completion tokens recovered

    def brief(self) -> Dict[str, Any]:
        return {
            "id": self.job_id, "object": "batch",
            "status": self.state, "method": self.method,
            "total": len(self.bodies),
            "completed": self.completed, "failed": self.failed,
            "completion_tokens": self.tokens,
            "created_at": self.created_at,
            "finished_at": self.finished_at,
            **({"tenant": self.tenant} if self.tenant else {}),
        }

    def detail(self) -> Dict[str, Any]:
        return {
            **self.brief(),
            "results": [
                (r if r is not None
                 else {"error": e} if e is not None else None)
                for r, e in zip(self.results, self.errors)],
        }


class BatchLane:
    """The fleet's bulk-inference pump. Owned by FleetManager; all
    state mutates on the ingress event loop (like the manager)."""

    def __init__(self, fleet: Any,
                 config: Optional[BatchLaneConfig] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.fleet = fleet
        self.config = config or BatchLaneConfig()
        self._clock = clock if clock is not None else time.monotonic
        self.jobs: "Dict[str, BatchJob]" = {}
        self._order: List[str] = []        # submission order
        self._seq = itertools.count(1)
        self._work: "asyncio.Queue[tuple]" = asyncio.Queue()
        self._tasks: set = set()
        self._pump_task: Optional[asyncio.Task] = None
        self.inflight = 0
        # lifetime counters (GET /fleet "batch" block + bench gates)
        self.submitted_requests = 0
        self.completed_requests = 0
        self.failed_requests = 0
        self.recovered_tokens = 0
        self.launch_holds = 0     # governor said "not now" (cadence
        #                           counts, not unique decisions)

    # -- submission surface --------------------------------------------
    def submit(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """POST /v1/batch: {"requests": [<completion/chat body>...],
        "method": "completions"|"chat" (default completions),
        "user": tenant}. Returns the job brief immediately."""
        cfg = self.config
        reqs = body.get("requests")
        if not isinstance(reqs, list) or not reqs:
            raise ValueError("batch body needs a non-empty "
                             "\"requests\" list")
        if len(reqs) > cfg.max_requests_per_job:
            raise ValueError(
                f"batch of {len(reqs)} exceeds "
                f"max_requests_per_job={cfg.max_requests_per_job}")
        method = str(body.get("method") or "completions")
        if method not in ("completions", "chat"):
            raise ValueError(f"unknown batch method {method!r}")
        bodies = []
        for r in reqs:
            if not isinstance(r, dict):
                raise ValueError("each batch request must be an "
                                 "object (an OpenAI body)")
            bodies.append(dict(r))
        job = BatchJob(f"batch-{next(self._seq)}", method, bodies,
                       tenant=str(body.get("user") or ""),
                       created_at=self._clock())
        self.jobs[job.job_id] = job
        self._order.append(job.job_id)
        self._gc_jobs()
        self.submitted_requests += len(bodies)
        for i in range(len(bodies)):
            self._work.put_nowait((job, i, 0))
        self.fleet.recorder.record("batch_submitted",
                                   job_id=job.job_id,
                                   requests=len(bodies),
                                   method=method)
        self.start()
        return job.brief()

    def get(self, job_id: str) -> Optional[Dict[str, Any]]:
        job = self.jobs.get(job_id)
        return None if job is None else job.detail()

    def cancel(self, job_id: str) -> Optional[Dict[str, Any]]:
        """POST /v1/batch/{id}/cancel: stop a job's not-yet-launched
        requests (the pump skips queued work of a CANCELLED job);
        requests already in flight run to completion — they hold
        engine slots the abort path would waste, and their results
        stay in the job. Finished jobs are left as-is. Returns the
        job brief (None = unknown id)."""
        job = self.jobs.get(job_id)
        if job is None:
            return None
        if job.state in (PENDING, RUNNING):
            job.state = CANCELLED
            job.finished_at = self._clock()
            self.fleet.recorder.record(
                "batch_cancelled", job_id=job_id,
                completed=job.completed,
                pending=len(job.bodies) - job.completed
                - job.failed)
        return job.brief()

    def list(self) -> List[Dict[str, Any]]:
        return [self.jobs[j].brief() for j in self._order
                if j in self.jobs]

    def _gc_jobs(self) -> None:
        while len(self._order) > self.config.max_jobs:
            for jid in self._order:
                job = self.jobs.get(jid)
                if job is None or job.state in (DONE, FAILED,
                                                CANCELLED):
                    self._order.remove(jid)
                    self.jobs.pop(jid, None)
                    break
            else:
                return      # everything live: keep them all

    # -- the soak governor ---------------------------------------------
    def headroom(self) -> bool:
        """Launch another batch stream now? Only while the fleet's
        INTERACTIVE planes show slack — the lane soaks troughs, it
        must never be the reason a user request queues."""
        from .fleet import ACTIVE    # deferred: fleet imports us
        cfg = self.config
        adm = self.fleet.admission
        if adm.brownout or adm._queue_len() > cfg.idle_queue_max:
            return False
        waiting = 0
        occ: List[float] = []
        for st in self.fleet.replicas.values():
            snap = st.snapshot
            if snap is None or st.status != ACTIVE:
                continue
            # interactive depth only: queued batch peers are the
            # lane's own backlog, not a reason to stop feeding it
            waiting += snap.displaceable_waiting()
            occ.append(snap.kv_occupancy)
        if waiting > cfg.idle_waiting_max:
            return False
        if occ and sum(occ) / len(occ) > cfg.idle_occupancy_max:
            return False
        return True

    # -- the pump ------------------------------------------------------
    def start(self) -> None:
        if self._pump_task is None or self._pump_task.done():
            self._pump_task = asyncio.get_running_loop().create_task(
                self._pump())

    async def stop(self) -> None:
        if self._pump_task is not None:
            self._pump_task.cancel()
            try:
                await self._pump_task
            except (asyncio.CancelledError, Exception):
                pass
            self._pump_task = None
        for t in list(self._tasks):
            t.cancel()

    async def _pump(self) -> None:
        cfg = self.config
        while True:
            if self._work.empty() and self.inflight == 0:
                # idle: park until the next submit() restarts us
                self._pump_task = None
                return
            if self.inflight < cfg.max_inflight \
                    and not self._work.empty() and self.headroom():
                job, i, attempt = self._work.get_nowait()
                if job.state == CANCELLED:
                    continue
                self.inflight += 1
                if job.state == PENDING:
                    job.state = RUNNING
                t = asyncio.get_running_loop().create_task(
                    self._run_one(job, i, attempt))
                self._tasks.add(t)
                t.add_done_callback(self._tasks.discard)
                continue        # try to fill every slot this turn
            if not self._work.empty() and self.inflight == 0 \
                    and not self.headroom():
                self.launch_holds += 1
            await asyncio.sleep(cfg.poll_period_s)

    async def _run_one(self, job: BatchJob, i: int,
                       attempt: int) -> None:
        body = dict(job.bodies[i])
        try:
            out = await self.fleet.dispatch(job.method, body,
                                            lane="batch")
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            self.inflight -= 1
            if attempt < self.config.max_retries:
                self._work.put_nowait((job, i, attempt + 1))
            else:
                job.errors[i] = repr(exc)
                job.failed += 1
                self.failed_requests += 1
                self.fleet.recorder.record(
                    "batch_request_failed", job_id=job.job_id,
                    index=i, error=repr(exc))
                self._maybe_finish(job)
            return
        self.inflight -= 1
        job.results[i] = out
        job.completed += 1
        self.completed_requests += 1
        toks = int(((out or {}).get("usage") or {})
                   .get("completion_tokens") or 0)
        job.tokens += toks
        self.recovered_tokens += toks
        self._maybe_finish(job)

    def _maybe_finish(self, job: BatchJob) -> None:
        if job.state == CANCELLED:
            return      # in-flight stragglers ran to completion and
            #             their results are kept, but a cancel is
            #             final — it must not resurface as "done"
        if job.completed + job.failed < len(job.bodies):
            return
        job.state = FAILED if job.completed == 0 else DONE
        job.finished_at = self._clock()
        self.fleet.recorder.record(
            "batch_finished", job_id=job.job_id, status=job.state,
            completed=job.completed, failed=job.failed,
            completion_tokens=job.tokens)

    # -- observability -------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        return {
            "jobs": len(self.jobs),
            "pending_requests": self._work.qsize(),
            "inflight": self.inflight,
            "submitted_requests": self.submitted_requests,
            "completed_requests": self.completed_requests,
            "failed_requests": self.failed_requests,
            "recovered_tokens": self.recovered_tokens,
            "launch_holds": self.launch_holds,
            "max_inflight": self.config.max_inflight,
        }


__all__ = ["BatchLane", "BatchLaneConfig", "BatchJob",
           "BATCH_PRIORITY", "INTERACTIVE_PRIORITY"]
