"""Deterministic fault injection for the serving fleet (ISSUE 9).

Every failure behavior the fleet's health plane promises — eviction on
probe timeouts, token-exact mid-stream failover on a severed stream,
half-open re-admission, deadline sheds under a slow replica — must be
tier-1-testable on CPU without killing real processes. This module
wraps any replica client (LocalReplicaClient, HandleReplicaClient, a
test fake) with a seeded, SCHEDULED fault plan:

    schedule = ChaosSchedule(seed=7)
    schedule.sever_stream(after_chunks=3)      # next stream: 3 chunks
                                               # then StreamSevered
    schedule.timeout_probes(count=3)           # next 3 fleet_stats
                                               # probes time out
    client = ChaosReplicaClient(inner, schedule)

Faults fire at exact per-method call indices (`at_call`, 0-based over
MATCHING calls), `count` times — the same schedule replays the same
failure sequence every run, which is what makes the chaos e2e suite
and the `bench_llm --smoke` chaos gate assertable. The seeded RNG is
for the optional randomized mode (`random_failures`), used to fuzz
the failover plane without fixing a script.

Injection is pure host-side asyncio: no device work, no engine
involvement — the dispatch-guard gates run with the wrapper installed
and still measure 1 dispatch/tick, 0 h2d, 0 compiles (failure
handling must add zero device work).
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
from typing import Any, Dict, List, Optional


class ChaosError(RuntimeError):
    """An injected replica failure (a call that raises)."""


class StreamSevered(ChaosError):
    """Injected mid-stream connection loss (the stream dies after N
    chunks, like a replica crash with tokens still in flight)."""


@dataclasses.dataclass
class FaultSpec:
    """One scheduled fault.

    kind: "call_error" | "stream_sever" | "stream_stall" |
          "probe_timeout" | "slow_call"
    method: replica method to match ("*" = any)
    at_call: fire from the Nth MATCHING call on (0-based, per method)
    after_chunks: stream_sever/stream_stall — chunks delivered first
    delay_s: slow_call — injected latency before the real call
    count: times to fire (-1 = every matching call)
    """
    kind: str
    method: str = "*"
    at_call: int = 0
    after_chunks: int = 0
    delay_s: float = 0.0
    count: int = 1


class ChaosSchedule:
    """A seeded, inspectable fault plan for ONE wrapped replica.
    `fired` logs every injection (method, kind, call index) so tests
    assert the schedule actually executed."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.faults: List[FaultSpec] = []
        self.fired: List[Dict[str, Any]] = []
        self._calls: Dict[str, int] = {}
        # randomized mode: per-call probabilities (random_failures)
        self._p_call_error = 0.0
        self._p_sever = 0.0

    # -- plan builders (chainable) -------------------------------------
    def add(self, **kw: Any) -> "ChaosSchedule":
        self.faults.append(FaultSpec(**kw))
        return self

    def sever_stream(self, after_chunks: int, method: str = "*",
                     at_call: int = 0,
                     count: int = 1) -> "ChaosSchedule":
        return self.add(kind="stream_sever", method=method,
                        at_call=at_call, after_chunks=after_chunks,
                        count=count)

    def fail_calls(self, method: str = "*", at_call: int = 0,
                   count: int = 1) -> "ChaosSchedule":
        return self.add(kind="call_error", method=method,
                        at_call=at_call, count=count)

    def stall_stream(self, after_chunks: int, method: str = "*",
                     at_call: int = 0,
                     count: int = 1) -> "ChaosSchedule":
        """The HUNG-replica case: the stream delivers N chunks then
        produces nothing forever (no raise — only the fleet's stall
        watchdog can save the client)."""
        return self.add(kind="stream_stall", method=method,
                        at_call=at_call, after_chunks=after_chunks,
                        count=count)

    def timeout_probes(self, at_call: int = 0,
                       count: int = 1) -> "ChaosSchedule":
        """fleet_stats probes raise TimeoutError — indistinguishable
        from the refresh loop's own wait_for expiry, but instant."""
        return self.add(kind="probe_timeout", method="fleet_stats",
                        at_call=at_call, count=count)

    def slow_calls(self, delay_s: float, method: str = "*",
                   at_call: int = 0,
                   count: int = 1) -> "ChaosSchedule":
        return self.add(kind="slow_call", method=method,
                        at_call=at_call, delay_s=delay_s, count=count)

    def random_failures(self, p_call_error: float = 0.0,
                        p_sever: float = 0.0) -> "ChaosSchedule":
        """Seeded randomized mode (fuzzing): each call/stream fails
        with the given probability, driven by this schedule's RNG —
        the same seed replays the same failure sequence."""
        self._p_call_error = p_call_error
        self._p_sever = p_sever
        return self

    # -- evaluation ----------------------------------------------------
    def take(self, method: str,
             is_stream: bool = False) -> Optional[FaultSpec]:
        """Consume the fault (if any) scheduled for this call. Faults
        only match the call shape they apply to: a `stream_sever`
        waits for a STREAM (a wildcard-method sever must not be eaten
        by the next fleet_stats probe), `probe_timeout` for a unary
        call."""
        n = self._calls.get(method, 0)
        self._calls[method] = n + 1
        for f in self.faults:
            if f.count == 0:
                continue
            if f.kind in ("stream_sever", "stream_stall") \
                    and not is_stream:
                continue
            if f.kind == "probe_timeout" and is_stream:
                continue
            if f.method not in ("*", method):
                continue
            if n < f.at_call:
                continue
            if f.count > 0:
                f.count -= 1
            self.fired.append({"method": method, "kind": f.kind,
                               "call": n})
            return f
        if is_stream and self._p_sever > 0.0 \
                and self.rng.random() < self._p_sever:
            f = FaultSpec(kind="stream_sever", method=method,
                          after_chunks=self.rng.randrange(1, 8))
            self.fired.append({"method": method, "kind": f.kind,
                               "call": n, "random": True})
            return f
        if not is_stream and self._p_call_error > 0.0 \
                and self.rng.random() < self._p_call_error:
            f = FaultSpec(kind="call_error", method=method)
            self.fired.append({"method": method, "kind": f.kind,
                               "call": n, "random": True})
            return f
        return None

    def stats(self) -> Dict[str, Any]:
        return {"seed": self.seed, "fired": list(self.fired),
                "pending": sum(1 for f in self.faults if f.count != 0),
                "calls": dict(self._calls)}


class ChaosReplicaClient:
    """Wrap a replica client with the schedule's faults. Implements
    the exact client interface the FleetManager consumes
    (replica_id / shares_registry / call / stream), so it can wrap
    in-process servers, deployment handles, and test fakes alike."""

    def __init__(self, inner: Any,
                 schedule: Optional[ChaosSchedule] = None,
                 seed: int = 0):
        self.inner = inner
        self.schedule = schedule or ChaosSchedule(seed)
        self.replica_id = inner.replica_id

    @property
    def shares_registry(self) -> bool:
        return bool(getattr(self.inner, "shares_registry", False))

    async def call(self, method: str, *args: Any) -> Any:
        f = self.schedule.take(method)
        if f is not None:
            if f.kind == "probe_timeout":
                raise asyncio.TimeoutError(
                    f"chaos: injected probe timeout on "
                    f"{self.replica_id}")
            if f.kind == "call_error":
                raise ChaosError(
                    f"chaos: injected {method} failure on "
                    f"{self.replica_id}")
            if f.kind == "slow_call":
                await asyncio.sleep(f.delay_s)
        return await self.inner.call(method, *args)

    def stream(self, method: str, body: Dict[str, Any]):
        f = self.schedule.take(method, is_stream=True)
        if f is None:
            return self.inner.stream(method, body)
        if f.kind == "call_error":
            return self._broken(method)
        if f.kind == "stream_sever":
            return self._severed(self.inner.stream(method, body),
                                 f.after_chunks)
        if f.kind == "stream_stall":
            return self._stalled(self.inner.stream(method, body),
                                 f.after_chunks)
        if f.kind == "slow_call":
            return self._delayed(self.inner.stream(method, body),
                                 f.delay_s)
        return self.inner.stream(method, body)

    async def _broken(self, method: str):
        raise ChaosError(
            f"chaos: injected {method} dispatch failure on "
            f"{self.replica_id}")
        yield  # pragma: no cover — makes this an async generator

    async def _severed(self, gen: Any, after_chunks: int):
        """Deliver `after_chunks` chunks, then die like a lost
        connection: the inner stream is CLOSED (so the replica's
        server aborts the engine request and frees its slot, exactly
        as a real disconnect would) and StreamSevered raises into the
        fleet's failover path. Note the replica may already have
        generated tokens past the sever point — those are the
        'in flight, never delivered' tokens the token-exact
        continuation must regenerate."""
        i = 0
        try:
            async for chunk in gen:
                if i >= after_chunks:
                    raise StreamSevered(
                        f"chaos: stream severed after {i} chunks on "
                        f"{self.replica_id}")
                yield chunk
                i += 1
        finally:
            from .failover import close_quietly
            await close_quietly(gen)

    async def _stalled(self, gen: Any, after_chunks: int):
        """Deliver `after_chunks` chunks then HANG — no raise, no
        end-of-stream: the wedged-replica case only a consumer-side
        stall watchdog can detect. Cancellation (the watchdog firing)
        unwinds through the hang and closes the inner stream."""
        i = 0
        try:
            async for chunk in gen:
                if i >= after_chunks:
                    await asyncio.Event().wait()     # hangs until
                yield chunk                          # cancelled
                i += 1
        finally:
            from .failover import close_quietly
            await close_quietly(gen)

    async def _delayed(self, gen: Any, delay_s: float):
        await asyncio.sleep(delay_s)
        async for chunk in gen:
            yield chunk


__all__ = ["ChaosError", "StreamSevered", "FaultSpec",
           "ChaosSchedule", "ChaosReplicaClient"]
