"""Serve configuration models.

Reference parity: python/ray/serve/config.py (AutoscalingConfig,
DeploymentConfig pydantic models) and HTTPOptions. Plain dataclasses here.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class AutoscalingConfig:
    """Request-driven replica autoscaling (reference
    serve/_private/autoscaling_state.py:262 — replicas sized from ongoing
    request metrics)."""
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 1.0
    downscale_delay_s: float = 5.0
    metrics_interval_s: float = 0.5

    def desired(self, total_ongoing: float, current: int) -> int:
        import math
        want = math.ceil(total_ongoing / max(self.target_ongoing_requests,
                                             1e-9))
        return max(self.min_replicas, min(self.max_replicas, want))


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    user_config: Any = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 2.0
    health_check_timeout_s: float = 10.0
    graceful_shutdown_timeout_s: float = 5.0
    ray_actor_options: Dict[str, Any] = dataclasses.field(
        default_factory=dict)

    def initial_target(self) -> int:
        if self.autoscaling_config is not None:
            return self.autoscaling_config.min_replicas
        return self.num_replicas


@dataclasses.dataclass
class HTTPOptions:
    host: str = "127.0.0.1"
    port: int = 8000


@dataclasses.dataclass
class gRPCOptions:
    """Placeholder for API parity (reference serves gRPC alongside HTTP);
    the TPU build routes everything through handles/HTTP."""
    port: int = 9000
    grpc_servicer_functions: tuple = ()
