"""ASGI ingress adapter: mount any ASGI application (FastAPI,
Starlette, Quart, a raw ASGI callable) as a deployment's HTTP ingress.

Reference parity: python/ray/serve/api.py:172 ``@serve.ingress(app)`` —
the reference wires FastAPI into its uvicorn proxy; here the adapter
speaks the ASGI protocol DIRECTLY: the proxy's picklable
``serve.Request`` becomes an ASGI http scope, the app's
``http.response.*`` messages become a ``serve.Response``. No web
framework is imported by the adapter itself, so it works with whatever
ASGI framework the environment provides (FastAPI is not bundled in
this image; the protocol is exercised against a hand-rolled ASGI app
in tests and accepts FastAPI/Starlette apps unchanged).

    app = FastAPI()          # or any ASGI callable

    @serve.deployment
    @serve.ingress(app)
    class Api:
        pass                 # routes live on the ASGI app

The app's lifespan protocol runs once per replica on first request
(startup; a reported startup failure makes every request fail loudly)
and ``aclose()`` sends lifespan.shutdown best-effort on teardown.
Streaming ASGI responses are buffered (one proxy hop carries the full
body); use the native StreamingHint ingress for SSE/chunked streams.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional
from urllib.parse import urlencode

from ._private.proxy import Request, Response


class ASGIAdapter:
    """Runs one ASGI app; converts serve.Request <-> ASGI messages."""

    def __init__(self, app):
        self.app = app
        self._startup: Optional[asyncio.Future] = None
        self._startup_error: Optional[Exception] = None
        self._lifespan_receive_q: Optional[asyncio.Queue] = None

    async def _start_lifespan(self) -> None:
        """Best-effort lifespan.startup (FastAPI apps that register
        startup hooks need it; apps without a lifespan handler raise —
        that is allowed by the spec and simply skipped)."""
        receive_q: asyncio.Queue = asyncio.Queue()
        started = asyncio.get_event_loop().create_future()

        async def receive():
            return await receive_q.get()

        async def send(message):
            if message["type"] == "lifespan.startup.complete" \
                    and not started.done():
                started.set_result(True)
            if message["type"] == "lifespan.startup.failed" \
                    and not started.done():
                started.set_exception(
                    RuntimeError(message.get("message", "startup failed")))

        await receive_q.put({"type": "lifespan.startup"})
        self._lifespan_task = asyncio.ensure_future(
            self.app({"type": "lifespan", "asgi": {"version": "3.0"}},
                     receive, send))
        self._lifespan_receive_q = receive_q
        # watch BOTH the completion future and the app task: an app
        # that raises on the lifespan scope (no lifespan support, per
        # spec) is detected instantly, not after a 10s stall
        done, _ = await asyncio.wait(
            {started, self._lifespan_task},
            timeout=10.0, return_when=asyncio.FIRST_COMPLETED)
        if started in done and started.exception() is not None:
            # the app REPORTED lifespan.startup.failed: serving against
            # a half-initialized app produces confusing per-request
            # errors — fail loudly instead (ASGI spec: do not serve)
            self._startup_error = started.exception()
            raise RuntimeError(
                f"ASGI app startup failed: {self._startup_error}")
        if started not in done:
            # app died on / ignored the lifespan scope: allowed by the
            # spec — serve without lifespan
            self._lifespan_task.cancel()
        if not started.done():
            started.cancel()

    async def handle(self, request: Request) -> Response:
        if self._startup is None:
            # one shared startup: concurrent first requests all await
            # the same future instead of racing past a boolean
            self._startup = asyncio.ensure_future(self._start_lifespan())
        await asyncio.shield(self._startup)
        if self._startup_error is not None:
            raise RuntimeError(
                f"ASGI app startup failed: {self._startup_error}")
        headers = [(k.lower().encode("latin-1"), v.encode("latin-1"))
                   for k, v in (request.headers or {}).items()]
        scope = {
            "type": "http",
            "asgi": {"version": "3.0", "spec_version": "2.3"},
            "http_version": "1.1",
            "method": request.method.upper(),
            "scheme": "http",
            "path": request.path or "/",
            "raw_path": (request.path or "/").encode("latin-1"),
            "query_string": urlencode(
                request.query_params or {}).encode("latin-1"),
            "root_path": "",
            "headers": headers,
            "client": ("127.0.0.1", 0),
            "server": ("127.0.0.1", 0),
        }
        body = request.body() or b""
        sent_request = False
        status: Dict[str, Any] = {"code": 500, "headers": []}
        chunks: List[bytes] = []
        done = asyncio.Event()

        async def receive():
            nonlocal sent_request
            if not sent_request:
                sent_request = True
                return {"type": "http.request", "body": body,
                        "more_body": False}
            await done.wait()            # client never disconnects early
            return {"type": "http.disconnect"}

        async def send(message):
            if message["type"] == "http.response.start":
                status["code"] = message["status"]
                status["headers"] = message.get("headers", [])
            elif message["type"] == "http.response.body":
                chunks.append(message.get("body", b"") or b"")
                if not message.get("more_body", False):
                    done.set()

        await self.app(scope, receive, send)
        done.set()
        content_type = "application/octet-stream"
        extra: Dict[str, str] = {}
        for k, v in status["headers"]:
            name = k.decode("latin-1").lower()
            if name == "content-type":
                content_type = v.decode("latin-1").split(";")[0].strip()
            elif name != "content-length":   # proxy recomputes length
                extra[name] = v.decode("latin-1")
        return Response(b"".join(chunks), status=status["code"],
                        content_type=content_type,
                        headers=extra or None)


    async def aclose(self) -> None:
        """Best-effort lifespan.shutdown (replica teardown)."""
        task = getattr(self, "_lifespan_task", None)
        q = self._lifespan_receive_q
        if task is None or task.done() or q is None:
            return
        try:
            await q.put({"type": "lifespan.shutdown"})
            await asyncio.wait_for(asyncio.shield(task), timeout=5.0)
        except Exception:
            task.cancel()


def ingress(app):
    """Class decorator mounting ``app`` (ASGI) as the deployment's HTTP
    ingress: requests hitting the deployment's route prefix run through
    the ASGI app; class methods/handle calls still work normally."""

    def decorator(cls):
        adapter_holder = {}

        class ASGIIngress(cls):
            async def __call__(self, request: Request):
                adapter = adapter_holder.get("a")
                if adapter is None:
                    adapter = adapter_holder["a"] = ASGIAdapter(app)
                return await adapter.handle(request)

            async def __serve_shutdown__(self):
                adapter = adapter_holder.get("a")
                if adapter is not None:
                    await adapter.aclose()

        ASGIIngress.__name__ = cls.__name__
        ASGIIngress.__qualname__ = getattr(cls, "__qualname__",
                                           cls.__name__)
        ASGIIngress.__module__ = cls.__module__
        ASGIIngress.__asgi_app__ = app
        return ASGIIngress

    return decorator
