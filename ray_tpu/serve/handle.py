"""DeploymentHandle: the client-side call path into a deployment.

Reference parity: serve/handle.py:628 (DeploymentHandle.remote →
DeploymentResponse), router.py:340 (AsyncioRouter) and
replica_scheduler/pow_2_scheduler.py:52 (power-of-two-choices over cached
queue lengths). The router keeps a per-process view of replica targets
(refreshed from the controller) and its own in-flight counts; each
assignment samples two replicas and picks the less loaded.
"""

from __future__ import annotations

import asyncio
import random
import threading
import time
import uuid
from typing import Any, Dict, Optional, Tuple

import ray_tpu

from ._private.common import (CONTROLLER_NAME, DeploymentTargets,
                              RequestMetadata, deployment_key)

_routers: Dict[str, "Router"] = {}
_routers_lock = threading.Lock()


def _controller():
    return ray_tpu.get_actor(CONTROLLER_NAME)


async def _controller_async():
    return await ray_tpu.aio_get_actor(CONTROLLER_NAME)


class Router:
    """Per-process, per-deployment replica picker."""

    REFRESH_S = 1.0

    def __init__(self, dep_key: str):
        self.dep_key = dep_key
        self.targets: Optional[DeploymentTargets] = None
        self.inflight: Dict[str, int] = {}
        self._last_refresh = 0.0
        self._lock = threading.Lock()

    # -- target refresh -----------------------------------------------------
    def _apply(self, wire: Dict[str, Any]) -> None:
        with self._lock:
            self.targets = DeploymentTargets.from_wire(wire)
            live = {r.replica_id for r in self.targets.replicas}
            self.inflight = {rid: n for rid, n in self.inflight.items()
                             if rid in live}
            self._last_refresh = time.monotonic()

    def _stale(self) -> bool:
        return (self.targets is None
                or time.monotonic() - self._last_refresh > self.REFRESH_S)

    def refresh_sync(self, deadline_s: float = 30.0) -> None:
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            if self._stale():
                wire = ray_tpu.get(
                    _controller().get_deployment_targets.remote(
                        self.dep_key), timeout=10)
                if wire is not None:
                    self._apply(wire)
            if self.targets is not None and self.targets.replicas:
                return
            time.sleep(0.1)
            # force the next loop iteration to re-poll the controller.
            # Under _lock: _apply writes _last_refresh while holding
            # it, and a bare store here can clobber a refresh that
            # landed between the sleep and the write (racelint RL001)
            with self._lock:
                self._last_refresh = 0.0
        raise TimeoutError(
            f"no running replicas for {self.dep_key} after {deadline_s}s")

    async def refresh_async(self, deadline_s: float = 30.0) -> None:
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            if self._stale():
                controller = await _controller_async()
                wire = await controller.get_deployment_targets.remote(
                    self.dep_key)
                if wire is not None:
                    self._apply(wire)
            if self.targets is not None and self.targets.replicas:
                return
            await asyncio.sleep(0.1)
            # see refresh_sync: the re-poll marker must not race a
            # concurrent _apply (racelint RL001)
            with self._lock:
                self._last_refresh = 0.0
        raise TimeoutError(
            f"no running replicas for {self.dep_key} after {deadline_s}s")

    # -- power of two choices ----------------------------------------------
    def _pick(self):
        replicas = self.targets.replicas
        if len(replicas) == 1:
            return replicas[0]
        a, b = random.sample(replicas, 2)
        na = self.inflight.get(a.replica_id, 0)
        nb = self.inflight.get(b.replica_id, 0)
        return a if na <= nb else b

    def _launch(self, meta: RequestMetadata, args, kwargs,
                stream: bool = False):
        with self._lock:
            target = self._pick()
            rid = target.replica_id
            self.inflight[rid] = self.inflight.get(rid, 0) + 1
        if stream:
            gen = target.actor_handle.handle_request_stream.options(
                num_returns="streaming").remote(
                    meta.__dict__, *args, **kwargs)

            def _stream_done():
                with self._lock:
                    n = self.inflight.get(rid, 1)
                    self.inflight[rid] = max(n - 1, 0)

            # decrement when the STREAM ends (exhaustion/close/GC), not at
            # launch: long-lived streams must weigh in pow-2 routing
            if hasattr(gen, "on_finish"):
                gen.on_finish = _stream_done
            else:                       # local-mode eager generator
                _stream_done()
            return gen
        ref = target.actor_handle.handle_request.remote(
            meta.__dict__, *args, **kwargs)

        def _done(_):
            with self._lock:
                n = self.inflight.get(rid, 1)
                self.inflight[rid] = max(n - 1, 0)
        try:
            ref.future().add_done_callback(_done)
        except Exception:
            _done(None)
        return ref

    def assign_sync(self, meta, args, kwargs, stream: bool = False):
        self.refresh_sync()
        return self._launch(meta, args, kwargs, stream)

    async def assign_async(self, meta, args, kwargs, stream: bool = False):
        await self.refresh_async()
        return self._launch(meta, args, kwargs, stream)


def _router_for(dep_key: str) -> Router:
    with _routers_lock:
        r = _routers.get(dep_key)
        if r is None:
            r = _routers[dep_key] = Router(dep_key)
        return r


class DeploymentResponse:
    """Future-like result of handle.remote() (reference handle.py:
    DeploymentResponse — awaitable in replicas, .result() on drivers)."""

    def __init__(self, ref=None, task: Optional[asyncio.Task] = None):
        self._ref = ref
        self._task = task

    def _object_ref_sync(self):
        if self._ref is None:
            raise RuntimeError(
                "response was created in an async context; await it")
        return self._ref

    def result(self, timeout_s: Optional[float] = None):
        return ray_tpu.get(self._object_ref_sync(), timeout=timeout_s)

    def __await__(self):
        async def _wait():
            ref = self._ref
            if ref is None:
                ref = await self._task
            return await ref
        return _wait().__await__()


class DeploymentHandle:
    """Callable reference to a deployment; picklable (travels into other
    replicas' init args and between processes)."""

    def __init__(self, deployment_name: str, app_name: str = "default",
                 *, method_name: str = "__call__",
                 multiplexed_model_id: str = "", stream: bool = False):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._method = method_name
        self._model_id = multiplexed_model_id
        self._stream = stream

    # -- options / composition ---------------------------------------------
    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name, self.app_name,
            method_name=method_name or self._method,
            multiplexed_model_id=(multiplexed_model_id
                                  if multiplexed_model_id is not None
                                  else self._model_id),
            stream=self._stream if stream is None else stream)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodProxy(self, name)

    # -- call path ----------------------------------------------------------
    def _meta(self) -> RequestMetadata:
        return RequestMetadata(
            request_id=uuid.uuid4().hex[:12], call_method=self._method,
            multiplexed_model_id=self._model_id)

    def remote(self, *args, **kwargs):
        dep_key = deployment_key(self.app_name, self.deployment_name)
        from ._private import local_testing
        local = local_testing.get(dep_key)
        if local is not None:
            # local testing mode: straight to the in-process replica
            return local.call(self._meta(), args, kwargs,
                              stream=self._stream)
        router = _router_for(dep_key)
        meta = self._meta()
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if self._stream:
            # streaming calls: resolve the replica + ObjectRefGenerator
            # eagerly, wrap in a value-yielding generator
            if loop is not None:
                task = loop.create_task(
                    router.assign_async(meta, args, kwargs, stream=True))
                return DeploymentResponseGenerator(task=task)
            return DeploymentResponseGenerator(
                gen=router.assign_sync(meta, args, kwargs, stream=True))
        if loop is not None:
            task = loop.create_task(router.assign_async(meta, args, kwargs))
            return DeploymentResponse(task=task)
        return DeploymentResponse(ref=router.assign_sync(meta, args, kwargs))

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name),
                {"_method": self._method, "_model_id": self._model_id,
                 "_stream": self._stream})

    def __setstate__(self, state):
        self._method = state.get("_method", "__call__")
        self._model_id = state.get("_model_id", "")
        self._stream = state.get("_stream", False)

    def __repr__(self):
        return (f"DeploymentHandle({self.app_name}#{self.deployment_name}"
                f".{self._method})")


class DeploymentResponseGenerator:
    """Streaming counterpart of DeploymentResponse: iterates the replica
    generator's VALUES (reference handle.py DeploymentResponseGenerator).
    Sync iteration on drivers, async inside replicas."""

    def __init__(self, gen=None, task: Optional[asyncio.Task] = None):
        self._gen = gen
        self._task = task

    def __iter__(self):
        return self

    def __next__(self):
        if self._gen is None:
            raise RuntimeError("created in an async context; use async for")
        ref = next(self._gen)                     # raises StopIteration
        return ray_tpu.get(ref)

    def __aiter__(self):
        return self

    async def __anext__(self):
        if self._gen is None:
            self._gen = await self._task
            self._task = None
        ref = await self._gen.__anext__()         # StopAsyncIteration
        return await ref

    def close(self):
        if self._gen is not None and hasattr(self._gen, "close"):
            self._gen.close()


class _MethodProxy:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle.options(method_name=method)

    def options(self, **opts) -> "DeploymentHandle":
        return self._handle.options(**opts)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle.remote(*args, **kwargs)


class _HandlePlaceholder:
    """Marker replacing a nested Application in serialized init args."""

    def __init__(self, deployment_name: str, app_name: str):
        self.deployment_name = deployment_name
        self.app_name = app_name


def _materialize_handle_placeholders(obj):
    if isinstance(obj, _HandlePlaceholder):
        return DeploymentHandle(obj.deployment_name, obj.app_name)
    if isinstance(obj, tuple):
        return tuple(_materialize_handle_placeholders(x) for x in obj)
    if isinstance(obj, list):
        return [_materialize_handle_placeholders(x) for x in obj]
    if isinstance(obj, dict):
        return {k: _materialize_handle_placeholders(v)
                for k, v in obj.items()}
    return obj
