"""Local testing mode: run an app's deployments in-process, no cluster.

Reference parity: serve/_private/local_testing_mode.py:49-133
(make_local_deployment_handle / LocalReplicaResult) — `serve.run(app,
local_testing_mode=True)` constructs every deployment's user callable
eagerly in THIS process and routes DeploymentHandle calls straight to
them on a background asyncio loop, so handle unit tests need no
controller, proxy, or workers. The same Replica wrapper class used by
real replica actors hosts the callable, so local behavior (method
dispatch, request context, reconfigure, streaming) matches the cluster
path.
"""

from __future__ import annotations

import asyncio
import queue as _queue
import threading
from typing import Any, Dict, Optional

from .common import deployment_key

_replicas: Dict[str, "LocalReplica"] = {}
_lock = threading.Lock()
_loop: Optional[asyncio.AbstractEventLoop] = None


def _ensure_loop() -> asyncio.AbstractEventLoop:
    """One background event loop thread hosts every local replica."""
    global _loop
    with _lock:
        if _loop is None or _loop.is_closed():
            loop = asyncio.new_event_loop()
            t = threading.Thread(
                target=loop.run_forever, name="serve-local", daemon=True)
            t.start()
            _loop = loop
        return _loop


class LocalResponse:
    """DeploymentResponse stand-in backed by a concurrent future."""

    def __init__(self, future):
        self._future = future

    def result(self, timeout_s: Optional[float] = None):
        return self._future.result(timeout=timeout_s)

    def __await__(self):
        return asyncio.wrap_future(self._future).__await__()


class LocalResponseGenerator:
    """Streaming stand-in: values arrive on a thread-safe queue fed by
    the replica's async generator on the background loop."""

    _DONE = object()

    def __init__(self, q: "_queue.Queue", future):
        self._q = q
        self._future = future   # resolves when the generator finishes

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._DONE:
            exc = self._future.exception()
            if exc is not None:
                raise exc
            raise StopIteration
        return item

    def __aiter__(self):
        return self

    async def __anext__(self):
        loop = asyncio.get_running_loop()
        item = await loop.run_in_executor(None, self._q.get)
        if item is self._DONE:
            exc = self._future.exception()
            if exc is not None:
                raise exc
            raise StopAsyncIteration
        return item

    def close(self):
        self._future.cancel()


class LocalReplica:
    """In-process host for one deployment's callable."""

    def __init__(self, replica):
        self.replica = replica          # _private.replica.Replica

    def call(self, meta, args, kwargs, stream: bool = False):
        loop = _ensure_loop()
        if stream:
            q: _queue.Queue = _queue.Queue()

            async def _drain():
                try:
                    agen = self.replica.handle_request_stream(
                        meta.__dict__, *args, **kwargs)
                    async for item in agen:
                        q.put(item)
                finally:
                    q.put(LocalResponseGenerator._DONE)

            fut = asyncio.run_coroutine_threadsafe(_drain(), loop)
            return LocalResponseGenerator(q, fut)
        fut = asyncio.run_coroutine_threadsafe(
            self.replica.handle_request(meta.__dict__, *args, **kwargs),
            loop)
        return LocalResponse(fut)


def get(dep_key: str) -> Optional[LocalReplica]:
    with _lock:
        return _replicas.get(dep_key)


def active() -> bool:
    with _lock:
        return bool(_replicas)


def has_app(app_name: str) -> bool:
    prefix = deployment_key(app_name, "")
    with _lock:
        return any(k.startswith(prefix) for k in _replicas)


def clear(app_name: Optional[str] = None) -> None:
    with _lock:
        if app_name is None:
            _replicas.clear()
        else:
            prefix = deployment_key(app_name, "")
            for k in [k for k in _replicas if k.startswith(prefix)]:
                del _replicas[k]


def deploy_local(app_name: str, ingress: str, specs) -> None:
    """Instantiate every deployment in-process (children first — specs
    arrive in dependency order from _build_app_specs, so a parent whose
    __init__ immediately calls a child handle finds it registered)."""
    from .replica import Replica

    for spec in specs:
        dep_key = deployment_key(app_name, spec["name"])
        replica = Replica(
            dep_key, "local", spec["callable_blob"],
            spec["init_args_blob"],
            user_config=spec["config"].user_config)
        with _lock:
            _replicas[dep_key] = LocalReplica(replica)
