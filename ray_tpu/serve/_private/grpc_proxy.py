"""gRPC ingress proxy.

Reference parity: serve/_private/proxy.py gRPCProxy (:534-1131 region —
the reference runs an HTTP and a gRPC proxy side by side). Ours is
built on grpc.aio generic handlers, so neither side needs protoc
codegen: the service is ``raytpu.serve.Serve`` with

    Predict        unary bytes -> bytes
    PredictStream  unary bytes -> stream of bytes

and routing metadata:

    application:  serve application name (default "default")
    call-method:  optional ingress method (default __call__)

Any gRPC client in any language can call it with identity (bytes)
serializers — see tests/test_serve_grpc.py for the Python shape. The
ingress deployment receives the raw request bytes and returns
bytes/str (unary) or a StreamingHint (streamed chunks), exactly like
the HTTP side's streaming contract.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional

import ray_tpu

from .common import CONTROLLER_NAME
from .proxy import StreamingHint

logger = logging.getLogger("ray_tpu.serve.grpc")

SERVICE_NAME = "raytpu.serve.Serve"


class GrpcProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 9000):
        self._host = host
        self._port = port
        self._server = None
        self._apps: Dict[str, str] = {}       # app name -> ingress
        self._handles: Dict[str, object] = {}
        self._refresh_task = None

    async def ready(self) -> int:
        if self._server is not None:
            return self._port
        import grpc

        self._server = grpc.aio.server()
        ident = lambda b: b                    # bytes-in / bytes-out
        handlers = {
            "Predict": grpc.unary_unary_rpc_method_handler(
                self._predict, request_deserializer=ident,
                response_serializer=ident),
            "PredictStream": grpc.unary_stream_rpc_method_handler(
                self._predict_stream, request_deserializer=ident,
                response_serializer=ident),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),))
        bound = self._server.add_insecure_port(
            f"{self._host}:{self._port}")
        if bound == 0:
            raise OSError(
                f"gRPC proxy could not bind {self._host}:{self._port} "
                "(port in use?)")
        self._port = bound
        await self._server.start()
        self._refresh_task = asyncio.create_task(self._refresh_loop())
        return self._port

    # ------------------------------------------------------------- routes

    async def _refresh_once(self) -> None:
        controller = await ray_tpu.aio_get_actor(CONTROLLER_NAME)
        table = await controller.get_route_table.remote()
        self._apps = {app: ingress for app, ingress in table.values()}

    async def _refresh_loop(self) -> None:
        while True:
            try:
                await self._refresh_once()
            except Exception:
                pass
            await asyncio.sleep(1.0)

    async def _resolve(self, context):
        md = dict(context.invocation_metadata())
        app = md.get("application", "default")
        method = md.get("call-method")
        if app not in self._apps:
            try:
                await self._refresh_once()
            except Exception:
                pass
        ingress = self._apps.get(app)
        if ingress is None:
            import grpc
            await context.abort(grpc.StatusCode.NOT_FOUND,
                                f"no serve application {app!r}")
        key = f"{app}#{ingress}"
        handle = self._handles.get(key)
        if handle is None:
            from ..handle import DeploymentHandle
            handle = DeploymentHandle(ingress, app)
            self._handles[key] = handle
        return handle, method

    # ------------------------------------------------------------ methods

    async def _predict(self, request: bytes, context) -> bytes:
        handle, method = await self._resolve(context)
        if method:
            handle = handle.options(method_name=method)
        try:
            result = await handle.remote(request)
        except Exception as e:
            import grpc
            logger.exception("grpc Predict failed")
            await context.abort(grpc.StatusCode.INTERNAL, repr(e))
        if isinstance(result, StreamingHint):
            import grpc
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "ingress returned a stream; call PredictStream")
        return self._to_bytes(result)

    async def _predict_stream(self, request: bytes, context):
        handle, method = await self._resolve(context)
        if method:
            handle = handle.options(method_name=method)
        result = await handle.remote(request)
        if not isinstance(result, StreamingHint):
            # unary result over the stream method: one chunk
            yield self._to_bytes(result)
            return
        gen = handle.options(method_name=result.call_method,
                             stream=True).remote(result.payload)
        try:
            async for chunk in gen:
                yield self._to_bytes(chunk)
        finally:
            gen.close()

    @staticmethod
    def _to_bytes(result) -> bytes:
        if isinstance(result, bytes):
            return result
        if isinstance(result, str):
            return result.encode()
        if result is None:
            return b""
        import json
        return json.dumps(result).encode()

    async def shutdown(self) -> bool:
        if self._refresh_task:
            self._refresh_task.cancel()
        if self._server is not None:
            await self._server.stop(grace=0.5)
            self._server = None
        return True
