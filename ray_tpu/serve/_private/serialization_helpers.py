"""Blob helpers shared by api.py (driver) and replica.py (worker)."""

from __future__ import annotations


def serialize_callable(func_or_class) -> bytes:
    from ..._private.serialization import serialize_code
    return serialize_code(func_or_class)


def serialize_args(args, kwargs) -> bytes:
    from ..._private.serialization import serialize
    return serialize((args, kwargs)).to_flat()


def deserialize_args(blob: bytes):
    from ..._private.serialization import SerializedObject
    return SerializedObject.from_flat(blob).deserialize()
