"""ServeController: reconciles desired app/deployment state to replicas.

Reference parity: serve/_private/controller.py:84 (control loop :369),
deployment_state.py (DeploymentStateManager.update :2663 — replica
start/stop/rolling update), autoscaling_state.py:262 (request-metric
autoscaling), long_poll.py:204 (change broadcast — here a versioned
long-poll on the replica-target snapshot).

Runs as a named async ray_tpu actor; the reconcile loop is an asyncio
task on the actor's event loop. Blocking client APIs (kill) are pushed to
a thread so the loop never blocks.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

import ray_tpu

from ..config import AutoscalingConfig, DeploymentConfig
from .common import (ApplicationStatus, DeploymentStatus, ReplicaState,
                     deployment_key)
from .replica import Replica

logger = logging.getLogger("ray_tpu.serve")

RECONCILE_PERIOD_S = 0.25


class _ReplicaInfo:
    def __init__(self, replica_id: str, handle, version: str):
        self.replica_id = replica_id
        self.handle = handle
        self.version = version
        self.state = ReplicaState.STARTING
        self.last_health = time.time()
        self.ongoing = 0.0
        self.qps = 0.0
        self.total_requests = 0.0
        # optional health_detail() payload from the last metrics poll
        # (LLM replicas: queue depth, KV occupancy, last-tick age)
        self.detail: Optional[Dict] = None
        self.health_task: Optional[asyncio.Task] = None


def _retire_replica(info: "_DeploymentInfo", replica_id: str):
    """Remove a replica, folding its request count into the
    deployment's retired total (cumulative metrics must not drop when
    replicas churn)."""
    rep = info.replicas.pop(replica_id, None)
    if rep is not None:
        info.retired_requests += getattr(rep, "total_requests", 0.0)
    return rep


class _DeploymentInfo:
    def __init__(self, name: str, app: str, spec: Dict[str, Any]):
        self.name = name
        self.app = app
        self.key = deployment_key(app, name)
        self.replicas: Dict[str, _ReplicaInfo] = {}
        self.seq = 0
        self.targets_version = 0
        self.status = DeploymentStatus.UPDATING
        # autoscaling bookkeeping
        self.autoscale_target: Optional[int] = None
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        # consecutive replica-start failures → exponential respawn backoff
        self.start_failures = 0
        self.next_start_at = 0.0
        # requests served by replicas that have since been removed
        # (downscale/health-kill/update) — keeps the deployment's
        # total_requests metric genuinely cumulative
        self.retired_requests = 0.0
        self.apply_spec(spec)

    def apply_spec(self, spec: Dict[str, Any]) -> None:
        if spec["version"] != getattr(self, "version", None):
            # fresh code/config deserves a fresh backoff ladder
            self.start_failures = 0
            self.next_start_at = 0.0
        self.callable_blob = spec["callable_blob"]
        self.init_args_blob = spec["init_args_blob"]
        self.version = spec["version"]
        cfg = spec["config"]
        self.config: DeploymentConfig = (
            cfg if isinstance(cfg, DeploymentConfig)
            else DeploymentConfig(**cfg))
        if self.autoscale_target is None and self.config.autoscaling_config:
            self.autoscale_target = \
                self.config.autoscaling_config.min_replicas

    # -- target sizing ------------------------------------------------------
    def target_count(self) -> int:
        auto = self.config.autoscaling_config
        if auto is None:
            return self.config.num_replicas
        return self.autoscale_target or auto.min_replicas

    def autoscale_tick(self) -> None:
        auto = self.config.autoscaling_config
        if auto is None:
            return
        running = [r for r in self.replicas.values()
                   if r.state == ReplicaState.RUNNING]
        if not running:
            return
        total_ongoing = sum(r.ongoing for r in running)
        desired = auto.desired(total_ongoing, len(running))
        now = time.time()
        current = self.target_count()
        if desired > current:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            if now - self._above_since >= auto.upscale_delay_s:
                self.autoscale_target = desired
                self._above_since = None
        elif desired < current:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            if now - self._below_since >= auto.downscale_delay_s:
                self.autoscale_target = desired
                self._below_since = None
        else:
            self._above_since = self._below_since = None


class ServeController:
    def __init__(self):
        self._apps: Dict[str, Dict[str, Any]] = {}
        self._deployments: Dict[str, _DeploymentInfo] = {}
        self._loop_task: Optional[asyncio.Task] = None
        self._change_event: Optional[asyncio.Event] = None
        self._shutdown = False

    # -- lifecycle ----------------------------------------------------------
    async def start_loop(self) -> bool:
        if self._loop_task is None:
            self._change_event = asyncio.Event()
            self._loop_task = asyncio.create_task(self._reconcile_loop())
        return True

    async def _reconcile_loop(self) -> None:
        while not self._shutdown:
            try:
                await self._reconcile_once()
            except Exception:
                logger.exception("reconcile iteration failed")
            await asyncio.sleep(RECONCILE_PERIOD_S)

    # -- public control plane ----------------------------------------------
    async def deploy_application(self, app_name: str, route_prefix: str,
                                 ingress: str,
                                 deployments: List[Dict[str, Any]]) -> bool:
        app = self._apps.setdefault(
            app_name, {"route_prefix": route_prefix, "ingress": ingress,
                       "status": ApplicationStatus.DEPLOYING,
                       "deployment_names": []})
        app["route_prefix"] = route_prefix
        app["ingress"] = ingress
        app["status"] = ApplicationStatus.DEPLOYING
        new_names = []
        for spec in deployments:
            name = spec["name"]
            new_names.append(name)
            key = deployment_key(app_name, name)
            info = self._deployments.get(key)
            if info is None:
                self._deployments[key] = _DeploymentInfo(
                    name, app_name, spec)
            else:
                info.apply_spec(spec)
                info.status = DeploymentStatus.UPDATING
        # deployments removed from the app spec get torn down
        for old in app["deployment_names"]:
            if old not in new_names:
                key = deployment_key(app_name, old)
                info = self._deployments.get(key)
                if info is not None:
                    await self._drain_all(info)
                    del self._deployments[key]
        app["deployment_names"] = new_names
        return True

    async def delete_application(self, app_name: str) -> bool:
        app = self._apps.pop(app_name, None)
        if app is None:
            return False
        for name in app["deployment_names"]:
            key = deployment_key(app_name, name)
            info = self._deployments.pop(key, None)
            if info is not None:
                await self._drain_all(info)
        return True

    async def get_deployment_targets(self, key: str
                                     ) -> Optional[Dict[str, Any]]:
        info = self._deployments.get(key)
        if info is None:
            return None
        replicas = [(r.replica_id, r.handle, self._moq(info))
                    for r in info.replicas.values()
                    if r.state == ReplicaState.RUNNING]
        return {"version": info.targets_version, "replicas": replicas}

    @staticmethod
    def _moq(info: _DeploymentInfo) -> int:
        return info.config.max_ongoing_requests

    async def listen_for_change(self, key: str, known_version: int,
                                timeout_s: float = 10.0
                                ) -> Optional[Dict[str, Any]]:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            info = self._deployments.get(key)
            if info is not None and info.targets_version != known_version:
                return await self.get_deployment_targets(key)
            try:
                await asyncio.wait_for(self._change_event.wait(),
                                       timeout=0.5)
            except asyncio.TimeoutError:
                pass
        return await self.get_deployment_targets(key)

    async def get_route_table(self) -> Dict[str, Any]:
        return {app["route_prefix"]: (name, app["ingress"])
                for name, app in self._apps.items()
                if app["status"] != ApplicationStatus.DELETING}

    async def get_app_ingress(self, app_name: str) -> Optional[str]:
        app = self._apps.get(app_name)
        return app["ingress"] if app else None

    async def status(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"applications": {}}
        for app_name, app in self._apps.items():
            deps = {}
            for name in app["deployment_names"]:
                info = self._deployments.get(
                    deployment_key(app_name, name))
                if info is None:
                    continue
                running = [r for r in info.replicas.values()
                           if r.state == ReplicaState.RUNNING]
                deps[name] = {
                    "status": info.status,
                    "replica_states": {
                        rid: r.state for rid, r in info.replicas.items()},
                    "target": info.target_count(),
                    "version": info.version,
                    # request metrics aggregated from the controller's
                    # replica polls (powers serve gauges on /metrics)
                    "metrics": {
                        "ongoing": sum(r.ongoing for r in running),
                        "qps_10s": sum(r.qps for r in running),
                        # cumulative: live replicas (any state) plus
                        # everything retired replicas ever served
                        "total_requests": info.retired_requests + sum(
                            r.total_requests
                            for r in info.replicas.values()),
                    },
                    # per-replica health detail (ISSUE 6): replicas
                    # exposing health_detail() — LLM servers report
                    # queue depth / KV occupancy / last-tick age —
                    # show their routing inputs here, so operators
                    # read them from serve.status() instead of
                    # hitting each replica's /stats
                    "replica_details": {
                        rid: r.detail
                        for rid, r in info.replicas.items()
                        if r.detail is not None},
                }
            out["applications"][app_name] = {
                "status": app["status"],
                "route_prefix": app["route_prefix"],
                "deployments": deps,
            }
        return out

    async def shutdown(self) -> bool:
        self._shutdown = True
        for info in list(self._deployments.values()):
            await self._drain_all(info)
        self._deployments.clear()
        self._apps.clear()
        return True

    # -- reconciliation -----------------------------------------------------
    async def _reconcile_once(self) -> None:
        for info in list(self._deployments.values()):
            await self._reconcile_deployment(info)
        # roll app statuses up from their deployments
        for app_name, app in self._apps.items():
            infos = [self._deployments.get(deployment_key(app_name, n))
                     for n in app["deployment_names"]]
            infos = [i for i in infos if i is not None]
            if infos and all(i.status == DeploymentStatus.HEALTHY
                             for i in infos):
                app["status"] = ApplicationStatus.RUNNING
            elif app["status"] != ApplicationStatus.DELETING:
                app["status"] = ApplicationStatus.DEPLOYING

    async def _reconcile_deployment(self, info: _DeploymentInfo) -> None:
        target = info.target_count()
        cur_version = [r for r in info.replicas.values()
                       if r.version == info.version]
        old_version = [r for r in info.replicas.values()
                       if r.version != info.version]
        running_new = [r for r in cur_version
                       if r.state == ReplicaState.RUNNING]
        # 1) start missing current-version replicas (with exponential
        # backoff after consecutive startup failures — a crashlooping
        # constructor must not hot-spin the cluster)
        missing = target - len(cur_version)
        if missing > 0 and time.time() >= info.next_start_at:
            for _ in range(missing):
                self._start_replica(info)
        # 2) rolling update: once enough new replicas run, drain old ones
        if old_version and len(running_new) >= min(target,
                                                   len(cur_version)):
            for r in old_version:
                await self._stop_replica(info, r)
        # 3) downscale excess current-version replicas
        excess = len(cur_version) - target
        if excess > 0:
            victims = sorted(
                cur_version,
                key=lambda r: (r.state == ReplicaState.RUNNING, r.ongoing)
            )[:excess]
            for r in victims:
                await self._stop_replica(info, r)
        # 4) health checks + metrics
        await self._probe_replicas(info)
        # 5) autoscaling decision
        info.autoscale_tick()
        # 6) status rollup
        healthy = [r for r in info.replicas.values()
                   if r.state == ReplicaState.RUNNING
                   and r.version == info.version]
        if len(healthy) >= info.target_count() and not old_version:
            info.status = DeploymentStatus.HEALTHY

    def _start_replica(self, info: _DeploymentInfo) -> None:
        info.seq += 1
        rid = f"{info.key}#{info.seq}"
        opts = dict(info.config.ray_actor_options)
        opts.setdefault("num_cpus", 0)
        actor_cls = ray_tpu.remote(**opts)(Replica) if opts else \
            ray_tpu.remote(Replica)
        handle = actor_cls.options(
            max_concurrency=info.config.max_ongoing_requests).remote(
            info.key, rid, info.callable_blob, info.init_args_blob,
            info.config.user_config)
        rep = _ReplicaInfo(rid, handle, info.version)
        info.replicas[rid] = rep
        rep.health_task = asyncio.create_task(
            self._await_startup(info, rep))

    async def _await_startup(self, info: _DeploymentInfo,
                             rep: _ReplicaInfo) -> None:
        try:
            await asyncio.wait_for(
                self._as_coro(rep.handle.check_health.remote()),
                timeout=60.0)
        except Exception as e:
            logger.warning("replica %s failed to start: %r",
                           rep.replica_id, e)
            _retire_replica(info, rep.replica_id)
            await self._kill(rep.handle)
            info.status = DeploymentStatus.UNHEALTHY
            info.start_failures += 1
            info.next_start_at = time.time() + min(
                2.0 ** min(info.start_failures, 10) * 0.5, 30.0)
            return
        rep.state = ReplicaState.RUNNING
        rep.last_health = time.time()
        info.start_failures = 0
        info.next_start_at = 0.0
        self._bump(info)

    async def _stop_replica(self, info: _DeploymentInfo,
                            rep: _ReplicaInfo) -> None:
        if rep.state == ReplicaState.STOPPING:
            return
        rep.state = ReplicaState.STOPPING
        self._bump(info)

        async def _drain_and_kill():
            try:
                await asyncio.wait_for(
                    self._as_coro(rep.handle.prepare_for_shutdown.remote()),
                    timeout=info.config.graceful_shutdown_timeout_s)
            except Exception:
                pass
            await self._kill(rep.handle)
            _retire_replica(info, rep.replica_id)

        asyncio.create_task(_drain_and_kill())

    async def _drain_all(self, info: _DeploymentInfo) -> None:
        for r in list(info.replicas.values()):
            try:
                await self._kill(r.handle)
            except Exception:
                pass
        info.replicas.clear()
        self._bump(info)

    async def _probe_replicas(self, info: _DeploymentInfo) -> None:
        now = time.time()
        for rep in list(info.replicas.values()):
            if rep.state != ReplicaState.RUNNING:
                continue
            if now - rep.last_health < info.config.health_check_period_s:
                continue
            try:
                metrics = await asyncio.wait_for(
                    self._as_coro(rep.handle.metrics.remote()),
                    timeout=info.config.health_check_timeout_s)
                rep.ongoing = float(metrics.get("ongoing", 0))
                rep.qps = float(metrics.get("qps_10s", 0.0))
                rep.total_requests = float(metrics.get("total", 0))
                rep.detail = metrics.get("detail")
                rep.last_health = now
            except Exception as e:
                logger.warning("replica %s failed health check: %r",
                               rep.replica_id, e)
                _retire_replica(info, rep.replica_id)
                await self._kill(rep.handle)
                self._bump(info)

    # -- helpers ------------------------------------------------------------
    @staticmethod
    async def _as_coro(ref):
        return await ref

    async def _kill(self, handle) -> None:
        loop = asyncio.get_running_loop()
        try:
            await loop.run_in_executor(
                None, lambda: ray_tpu.kill(handle, no_restart=True))
        except Exception:
            pass

    def _bump(self, info: _DeploymentInfo) -> None:
        info.targets_version += 1
        if self._change_event is not None:
            self._change_event.set()
            self._change_event = asyncio.Event()
