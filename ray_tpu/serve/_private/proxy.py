"""HTTP ingress proxy.

Reference parity: serve/_private/proxy.py:534-1131 (HTTPProxy on uvicorn;
route table from the controller, requests forwarded through handles).
Here: an aiohttp server inside an async actor; the route table refreshes
on a short poll of the controller; request bodies are forwarded to the
app's ingress deployment via the async handle path.

Ingress contract: the ingress callable receives a `serve.Request`
(method/path/headers/query/body helpers). Return values map to HTTP:
dict/list → JSON, str → text/plain, bytes → octet-stream,
Response(status, body, content_type) for full control.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Dict, Optional, Tuple

import ray_tpu

from .common import CONTROLLER_NAME

logger = logging.getLogger("ray_tpu.serve.proxy")


class Request:
    """What HTTP ingress callables receive (picklable, unlike an ASGI
    scope)."""

    def __init__(self, method: str, path: str, query: Dict[str, str],
                 headers: Dict[str, str], body: bytes):
        self.method = method
        self.path = path
        self.query_params = query
        self.headers = headers
        self._body = body

    def body(self) -> bytes:
        return self._body

    def json(self) -> Any:
        return json.loads(self._body or b"null")

    @property
    def text(self) -> str:
        return (self._body or b"").decode()


class Response:
    def __init__(self, body: Any = b"", status: int = 200,
                 content_type: str = "application/octet-stream",
                 headers: Optional[Dict[str, str]] = None):
        self.body = body
        self.status = status
        self.content_type = content_type
        # extra response headers (Location, Set-Cookie, ...); content
        # length is recomputed by the proxy
        self.headers = headers


class StreamingHint:
    """Returned by an ingress to switch the proxy to a streaming call:
    the proxy re-invokes `call_method` on the SAME ingress with
    stream=True and writes each yielded str/bytes chunk to the HTTP
    response as it arrives (SSE and chunked responses ride this)."""

    def __init__(self, call_method: str, payload: Any,
                 content_type: str = "text/event-stream"):
        self.call_method = call_method
        self.payload = payload
        self.content_type = content_type


class ProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self._host = host
        self._port = port
        self._routes: Dict[str, Tuple[str, str]] = {}
        self._handles: Dict[str, Any] = {}
        self._runner = None
        self._refresh_task: Optional[asyncio.Task] = None

    async def ready(self) -> int:
        """Start the server; returns the bound port."""
        if self._runner is not None:
            return self._port
        from aiohttp import web

        app = web.Application(client_max_size=64 * 1024 * 1024)
        app.router.add_route("*", "/{tail:.*}", self._handle_http)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self._host, self._port)
        await site.start()
        self._refresh_task = asyncio.create_task(self._refresh_routes())
        return self._port

    async def _refresh_once(self) -> None:
        controller = await ray_tpu.aio_get_actor(CONTROLLER_NAME)
        table = await controller.get_route_table.remote()
        self._routes = dict(table)

    async def _refresh_routes(self) -> None:
        while True:
            try:
                await self._refresh_once()
            except Exception:
                pass
            await asyncio.sleep(1.0)

    def _match(self, path: str) -> Optional[Tuple[str, str, str]]:
        """Longest-prefix route match → (prefix, app, ingress)."""
        best = None
        for prefix, (app, ingress) in self._routes.items():
            norm = prefix.rstrip("/") or "/"
            if path == norm or path.startswith(
                    norm + ("" if norm == "/" else "/")) or norm == "/":
                if best is None or len(norm) > len(best[0]):
                    best = (norm, app, ingress)
        return best

    async def _handle_http(self, request):
        from aiohttp import web

        path = request.path
        if path == "/-/healthz":
            return web.Response(text="success")
        if path == "/-/routes":
            return web.json_response(
                {p: f"{a}#{i}" for p, (a, i) in self._routes.items()})
        match = self._match(path)
        if match is None:
            # the app may have deployed since the last poll tick —
            # refresh inline once before giving up
            try:
                await self._refresh_once()
            except Exception:
                pass
            match = self._match(path)
        if match is None:
            return web.Response(status=404,
                                text=f"no app mounted at {path}")
        prefix, app_name, ingress = match
        from ..handle import DeploymentHandle
        hkey = f"{app_name}#{ingress}"
        handle = self._handles.get(hkey)
        if handle is None:
            handle = DeploymentHandle(ingress, app_name)
            self._handles[hkey] = handle
        body = await request.read()
        sub_path = path[len(prefix):] if prefix != "/" else path
        req = Request(request.method, sub_path or "/",
                      dict(request.query), dict(request.headers), body)
        try:
            result = await handle.remote(req)
        except Exception as e:
            logger.exception("request to %s failed", hkey)
            return web.Response(status=500, text=repr(e))
        if isinstance(result, StreamingHint):
            return await self._stream_http(web, request, handle, result)
        return self._to_http(web, result)

    async def _stream_http(self, web, request, handle, hint: StreamingHint):
        resp = web.StreamResponse(
            status=200, headers={"Content-Type": hint.content_type,
                                 "Cache-Control": "no-cache"})
        await resp.prepare(request)
        gen = handle.options(method_name=hint.call_method,
                             stream=True).remote(hint.payload)
        try:
            async for chunk in gen:
                if isinstance(chunk, str):
                    chunk = chunk.encode()
                await resp.write(chunk)
        finally:
            gen.close()
            await resp.write_eof()
        return resp

    @staticmethod
    def _to_http(web, result):
        if isinstance(result, Response):
            body = result.body
            if isinstance(body, (dict, list)):
                body = json.dumps(body).encode()
            elif isinstance(body, str):
                body = body.encode()
            extra = {k: v for k, v in (result.headers or {}).items()
                     if k.lower() not in ("content-type",
                                          "content-length")}
            return web.Response(body=body, status=result.status,
                                content_type=result.content_type,
                                headers=extra or None)
        if isinstance(result, (dict, list)):
            return web.json_response(result)
        if isinstance(result, str):
            return web.Response(text=result)
        if isinstance(result, bytes):
            return web.Response(body=result)
        if result is None:
            return web.Response(status=204)
        return web.json_response({"result": repr(result)})

    async def shutdown(self) -> bool:
        if self._refresh_task:
            self._refresh_task.cancel()
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        return True
