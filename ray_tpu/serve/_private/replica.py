"""Replica actor: hosts one copy of a deployment's user callable.

Reference parity: serve/_private/replica.py (UserCallableWrapper, request
counting, health checks, reconfigure). Runs as an async ray_tpu actor with
max_concurrency = max_ongoing_requests, so concurrent requests interleave
on the worker's event loop; sync user code runs in the worker thread pool.
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
import time
from typing import Any, Dict, Optional

# Visible to user code via serve.get_multiplexed_model_id() and
# serve.context helpers.
_request_context: contextvars.ContextVar = contextvars.ContextVar(
    "serve_request_context", default=None)


def current_request_context():
    return _request_context.get()


class Replica:
    """The actor class the controller spawns per replica."""

    def __init__(self, deployment_key: str, replica_id: str,
                 callable_blob: bytes, init_args_blob: bytes,
                 user_config: Any = None):
        from ..._private.serialization import deserialize_code
        from ..handle import _materialize_handle_placeholders
        from .serialization_helpers import deserialize_args

        self._deployment_key = deployment_key
        self._replica_id = replica_id
        self._ongoing = 0
        self._total = 0
        self._window: list = []   # (ts,) of recent request starts
        cls_or_fn = deserialize_code(callable_blob)
        args, kwargs = deserialize_args(init_args_blob)
        args = _materialize_handle_placeholders(args)
        kwargs = _materialize_handle_placeholders(kwargs)
        if inspect.isclass(cls_or_fn):
            self._instance = cls_or_fn(*args, **kwargs)
            self._is_function = False
        else:
            self._instance = cls_or_fn
            self._is_function = True
        if user_config is not None:
            self._reconfigure_sync(user_config)

    # -- request path -------------------------------------------------------
    async def handle_request(self, meta: Dict[str, Any], *args, **kwargs):
        self._ongoing += 1
        self._total += 1
        now = time.time()
        self._window.append(now)
        if len(self._window) > 1000:
            del self._window[:500]
        token = _request_context.set(meta)
        try:
            if self._is_function:
                target = self._instance
            else:
                target = getattr(self._instance,
                                 meta.get("call_method") or "__call__")
            if inspect.iscoroutinefunction(target):
                return await target(*args, **kwargs)
            loop = asyncio.get_running_loop()
            ctx = contextvars.copy_context()
            return await loop.run_in_executor(
                None, lambda: ctx.run(target, *args, **kwargs))
        finally:
            _request_context.reset(token)
            self._ongoing -= 1

    async def handle_request_stream(self, meta: Dict[str, Any],
                                    *args, **kwargs):
        """Streaming twin of handle_request: the target user method is a
        (sync or async) generator; items are re-yielded, so calling this
        with num_returns="streaming" streams them to the consumer
        (reference parity: replica.py handle_request_streaming)."""
        self._ongoing += 1
        self._total += 1
        token = _request_context.set(meta)
        try:
            target = (self._instance if self._is_function else
                      getattr(self._instance,
                              meta.get("call_method") or "__call__"))
            gen = target(*args, **kwargs)
            if hasattr(gen, "__anext__"):
                async for item in gen:
                    yield item
            else:
                for item in gen:
                    yield item
        finally:
            _request_context.reset(token)
            self._ongoing -= 1

    # -- control plane ------------------------------------------------------
    def _reconfigure_sync(self, user_config: Any) -> None:
        if not self._is_function and hasattr(self._instance, "reconfigure"):
            self._instance.reconfigure(user_config)

    async def reconfigure(self, user_config: Any) -> bool:
        fn = getattr(self._instance, "reconfigure", None)
        if fn is None:
            return False
        if inspect.iscoroutinefunction(fn):
            await fn(user_config)
        else:
            fn(user_config)
        return True

    async def check_health(self) -> bool:
        fn = getattr(self._instance, "check_health", None)
        if fn is not None:
            if inspect.iscoroutinefunction(fn):
                await fn()
            else:
                fn()
        return True

    async def metrics(self) -> Dict[str, Any]:
        cutoff = time.time() - 10.0
        recent = sum(1 for t in self._window if t >= cutoff)
        out: Dict[str, Any] = {"ongoing": self._ongoing,
                               "total": self._total,
                               "qps_10s": recent / 10.0}
        # optional per-replica health detail (ISSUE 6): a callable
        # exposing health_detail() — the LLM server reports queue
        # depth / KV occupancy / last-tick age — gets it piggybacked
        # on the controller's existing metrics poll and surfaced in
        # serve.status(). Best-effort: a broken hook must not fail
        # the health probe and kill the replica.
        fn = getattr(self._instance, "health_detail", None)
        if fn is not None:
            try:
                detail = fn()
                if inspect.isawaitable(detail):
                    detail = await detail
                out["detail"] = detail
            except Exception:
                pass
        return out

    async def prepare_for_shutdown(self) -> None:
        """Drain: wait for ongoing requests to finish (graceful stop),
        then run the instance's teardown hook if it defines one (the
        ASGI ingress wrapper uses it to send lifespan.shutdown)."""
        deadline = time.time() + 30
        while self._ongoing > 0 and time.time() < deadline:
            await asyncio.sleep(0.05)
        hook = getattr(self._instance, "__serve_shutdown__", None)
        if hook is not None:
            try:
                result = hook()
                if inspect.isawaitable(result):
                    await asyncio.wait_for(result, timeout=10.0)
            except Exception:
                pass   # teardown is best-effort
