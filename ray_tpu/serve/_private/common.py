"""Shared Serve types: statuses, request context, deployment ids.

Reference parity: serve/_private/common.py (DeploymentID, ReplicaID,
RequestMetadata) and serve/schema.py status models.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"
PROXY_NAME = "SERVE_PROXY"
GRPC_PROXY_NAME = "SERVE_GRPC_PROXY"


def deployment_key(app_name: str, deployment_name: str) -> str:
    return f"{app_name}#{deployment_name}"


class DeploymentStatus:
    UPDATING = "UPDATING"
    HEALTHY = "HEALTHY"
    UNHEALTHY = "UNHEALTHY"
    UPSCALING = "UPSCALING"
    DOWNSCALING = "DOWNSCALING"


class ApplicationStatus:
    DEPLOYING = "DEPLOYING"
    RUNNING = "RUNNING"
    DEPLOY_FAILED = "DEPLOY_FAILED"
    DELETING = "DELETING"
    NOT_STARTED = "NOT_STARTED"


class ReplicaState:
    STARTING = "STARTING"
    RUNNING = "RUNNING"
    STOPPING = "STOPPING"


@dataclasses.dataclass
class RequestMetadata:
    request_id: str = ""
    call_method: str = "__call__"
    multiplexed_model_id: str = ""
    http_method: str = ""
    route: str = ""


@dataclasses.dataclass
class ReplicaTarget:
    """What the router needs to reach one replica."""
    replica_id: str
    actor_handle: Any
    max_ongoing_requests: int = 8


@dataclasses.dataclass
class DeploymentTargets:
    version: int
    replicas: list

    def to_wire(self) -> Dict[str, Any]:
        return {"version": self.version,
                "replicas": [(r.replica_id, r.actor_handle,
                              r.max_ongoing_requests)
                             for r in self.replicas]}

    @staticmethod
    def from_wire(d: Dict[str, Any]) -> "DeploymentTargets":
        return DeploymentTargets(
            version=d["version"],
            replicas=[ReplicaTarget(rid, h, moq)
                      for rid, h, moq in d["replicas"]])
