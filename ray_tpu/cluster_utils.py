"""Multi-daemon test cluster: real node-daemon PROCESSES as fake nodes.

Reference parity: python/ray/cluster_utils.py:135 (Cluster / add_node /
remove_node) — the workhorse of the reference's distributed test suite.
Unlike `ray_tpu.add_fake_node` (an extra in-process daemon sharing the
driver's event loop), every node here is a separate OS process running
the CLI worker-join path (`ray_tpu start --address`), so scheduling,
gossip, object transfer, and failure handling all cross real process +
socket boundaries.

    NOTE: init(ignore_reinit_error=True) — when the process already
    holds a head runtime, head_cpus is ignored and that session is
    reused; start Cluster first for a head sized by head_cpus.

    cluster = Cluster(head_cpus=2)
    n1 = cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2, resources={"accel": 1})
    ... drive ray_tpu tasks/actors ...
    cluster.remove_node(n1)        # SIGKILL: node-failure chaos
    cluster.shutdown()
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

import ray_tpu


class Cluster:
    def __init__(self, head_cpus: float = 2.0, **init_kwargs):
        self._rt = ray_tpu.init(num_cpus=head_cpus,
                                ignore_reinit_error=True, **init_kwargs)
        if self._rt.controller is None or self._rt.head_daemon is None:
            raise RuntimeError(
                "Cluster needs a head-owning runtime; this process is "
                "attached to a remote cluster (init(address=...)) — "
                "run Cluster in the head process")
        host, port = self._rt.controller.address
        self.address = f"{host}:{port}"
        self.head_node_id = self._rt.head_daemon.node_id
        self._procs: Dict[str, subprocess.Popen] = {}
        self._logs: Dict[str, str] = {}

    # ------------------------------------------------------------ nodes
    def _alive_node_ids(self) -> List[str]:
        from ray_tpu.util.state import list_nodes
        return [n["node_id"] for n in list_nodes() if n.get("alive")]

    def add_node(self, num_cpus: float = 1.0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 timeout: float = 60.0) -> str:
        """Spawn a daemon process joined to this cluster; returns its
        node_id once the controller sees it alive."""
        before = set(self._alive_node_ids())
        cmd = [sys.executable, "-m", "ray_tpu", "start",
               "--address", self.address, "--num-cpus", str(num_cpus)]
        if resources:
            cmd += ["--resources", json.dumps(resources)]
        if labels:
            cmd += ["--labels", json.dumps(labels)]
        penv = dict(os.environ)
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        penv["PYTHONPATH"] = os.pathsep.join(
            [pkg_parent] + [p for p in
                            penv.get("PYTHONPATH", "").split(os.pathsep)
                            if p])
        penv.update(env or {})
        log_path = os.path.join(
            self._rt.head_daemon.temp_dir, "logs",
            f"cluster-node-{len(self._procs)}.log")
        log_file = open(log_path, "ab")
        proc = subprocess.Popen(cmd, stdout=log_file,
                                stderr=subprocess.STDOUT, env=penv,
                                start_new_session=True)
        log_file.close()
        deadline = time.time() + timeout
        while time.time() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"cluster node process exited rc={proc.returncode}; "
                    f"see {log_path}")
            new = set(self._alive_node_ids()) - before
            if new:
                node_id = new.pop()
                self._procs[node_id] = proc
                self._logs[node_id] = log_path
                return node_id
            time.sleep(0.1)
        proc.kill()
        raise TimeoutError(
            f"node did not join within {timeout}s; see {log_path}")

    def remove_node(self, node_id: str, graceful: bool = False,
                    timeout: float = 30.0) -> None:
        """Kill a node's daemon process. graceful=False (default) is the
        chaos path: SIGKILL the whole process group, exactly like a node
        crash — the controller must detect it via health probes."""
        proc = self._procs.pop(node_id, None)
        if proc is None:
            return     # already removed (idempotent)
        sig = signal.SIGTERM if graceful else signal.SIGKILL
        try:
            os.killpg(proc.pid, sig)
        except ProcessLookupError:
            pass
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait(timeout=5)
        # wait until the controller notices (probe-before-declare-dead)
        deadline = time.time() + timeout
        while time.time() < deadline:
            if node_id not in self._alive_node_ids():
                return
            time.sleep(0.2)
        raise TimeoutError(
            f"controller still thinks {node_id[:8]} is alive "
            f"after {timeout}s")

    def wait_for_nodes(self, count: int, timeout: float = 60.0) -> None:
        """Block until `count` nodes (incl. head) are alive."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if len(self._alive_node_ids()) >= count:
                return
            time.sleep(0.1)
        raise TimeoutError(
            f"{count} nodes not alive within {timeout}s "
            f"(have {len(self._alive_node_ids())})")

    # ------------------------------------------------------------ teardown
    def shutdown(self) -> None:
        for node_id in list(self._procs):
            proc = self._procs.pop(node_id, None)
            if proc is None:
                continue
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
        ray_tpu.shutdown()

    def __enter__(self) -> "Cluster":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
