"""TPU accelerator manager: detection, isolation, pod-slice resources.

Reference parity: python/ray/_private/accelerators/tpu.py:109-375
(TPUAcceleratorManager) — resource name "TPU", TPU_VISIBLE_CHIPS isolation,
GCE/GKE metadata probing, pod-type detection, the auto
"TPU-{version}-{cores}-head" resource, valid chip counts {1, 2, 4, 8}.

Detection is environment-driven (no jax import here — importing jax grabs
the chips, which must only happen inside the worker that owns them).
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional

TPU_RESOURCE_NAME = "TPU"
VALID_CHIPS_PER_HOST = (1, 2, 4, 8)
TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
# GCE metadata env mirrors (set by TPU VM images / GKE webhook).
ACCEL_TYPE_ENVS = ("TPU_ACCELERATOR_TYPE", "ACCELERATOR_TYPE")
WORKER_ID_ENV = "TPU_WORKER_ID"
POD_NAME_ENVS = ("TPU_NAME", "TPU_POD_NAME")


class TPUAcceleratorManager:
    """Static methods mirroring the reference AcceleratorManager ABC
    (python/ray/_private/accelerators/accelerator.py:5)."""

    @staticmethod
    def get_resource_name() -> str:
        return TPU_RESOURCE_NAME

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        override = os.environ.get("RAY_TPU_NUM_CHIPS")
        if override:
            return int(override)
        # TPU VM images expose one /dev/accel* (or vfio group) per chip.
        chips = glob.glob("/dev/accel*")
        if chips:
            return len(chips)
        chips = glob.glob("/dev/vfio/[0-9]*")
        if chips:
            return len(chips)
        return 0

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        for env in ACCEL_TYPE_ENVS:
            v = os.environ.get(env)
            if v:
                return v  # e.g. "v5p-64"
        return None

    @staticmethod
    def validate_resource_request_quantity(quantity: float):
        if quantity not in VALID_CHIPS_PER_HOST and quantity >= 1:
            return (False,
                    f"TPU request must be one of {VALID_CHIPS_PER_HOST} "
                    f"chips (got {quantity}); multi-host workloads request "
                    f"whole hosts via the pod-slice head resource.")
        return True, None

    @staticmethod
    def set_current_process_visible_accelerators(chip_ids: List[int]) -> None:
        """Restrict this process (and jax in it) to the given chips."""
        os.environ[TPU_VISIBLE_CHIPS_ENV] = ",".join(str(c) for c in chip_ids)
        # Bounds for subsets of a host (reference tpu.py:193-209).
        n = len(chip_ids)
        if n in (1, 2):
            os.environ["TPU_CHIPS_PER_HOST_BOUNDS"] = f"{n},1,1"
            os.environ["TPU_HOST_BOUNDS"] = "1,1,1"

    @staticmethod
    def get_current_process_visible_accelerators() -> Optional[List[int]]:
        v = os.environ.get(TPU_VISIBLE_CHIPS_ENV)
        if v is None:
            return None
        return [int(x) for x in v.split(",") if x]

    # ------------------------------------------------------ pod-slice info

    @staticmethod
    def get_current_pod_name() -> Optional[str]:
        for env in POD_NAME_ENVS:
            v = os.environ.get(env)
            if v:
                return v
        return None

    @staticmethod
    def get_current_pod_worker_count() -> Optional[int]:
        hostnames = os.environ.get("TPU_WORKER_HOSTNAMES")
        if hostnames:
            return len(hostnames.split(","))
        return None

    @staticmethod
    def get_current_pod_head_resource_name() -> Optional[str]:
        """The gang-scheduling anchor: e.g. 'TPU-v5p-64-head' exists (=1)
        only on worker 0 of a slice (reference tpu.py:352-375)."""
        accel = TPUAcceleratorManager.get_current_node_accelerator_type()
        if accel is None:
            return None
        worker_id = os.environ.get(WORKER_ID_ENV, "0")
        if worker_id != "0":
            return None
        return f"TPU-{accel}-head"

    @staticmethod
    def autodetect_resources() -> Dict[str, float]:
        """Resources this node should advertise."""
        out: Dict[str, float] = {}
        n = TPUAcceleratorManager.get_current_node_num_accelerators()
        if n > 0:
            out[TPU_RESOURCE_NAME] = float(n)
            accel = TPUAcceleratorManager.get_current_node_accelerator_type()
            if accel:
                out[f"TPU-{accel}"] = float(n)
            head = TPUAcceleratorManager.get_current_pod_head_resource_name()
            if head:
                out[head] = 1.0
        return out
