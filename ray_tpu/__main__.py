"""`python -m ray_tpu` → the CLI (reference: the `ray` console script)."""
from .scripts.cli import main

main()
