"""Job manager: run submitted entrypoints as supervised subprocesses.

Reference parity: dashboard/modules/job/job_manager.py:60 (JobManager +
per-job supervisor; PENDING → RUNNING → SUCCEEDED/FAILED/STOPPED),
with logs captured to the session log dir.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
import threading
import time
import uuid
from typing import Any, Dict, List, Optional


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


class JobManager:
    def __init__(self, log_dir: Optional[str] = None):
        self._jobs: Dict[str, Dict[str, Any]] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()
        self._log_dir = log_dir or os.path.join(
            tempfile.gettempdir(), "ray_tpu", "job_logs")
        os.makedirs(self._log_dir, exist_ok=True)

    def submit(self, entrypoint: str,
               runtime_env: Optional[Dict[str, Any]] = None,
               metadata: Optional[Dict[str, str]] = None,
               submission_id: Optional[str] = None) -> str:
        job_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id} already exists")
            self._jobs[job_id] = {
                "submission_id": job_id,
                "entrypoint": entrypoint,
                "status": JobStatus.PENDING,
                "metadata": dict(metadata or {}),
                "start_time": None, "end_time": None,
                "submit_time": time.time(),
                "return_code": None,
                "message": "",
            }
        threading.Thread(target=self._supervise,
                         args=(job_id, entrypoint, runtime_env or {}),
                         daemon=True).start()
        return job_id

    def _supervise(self, job_id: str, entrypoint: str,
                   runtime_env: Dict[str, Any]) -> None:
        log_path = os.path.join(self._log_dir, f"{job_id}.log")
        env = dict(os.environ)
        env.update({str(k): str(v)
                    for k, v in (runtime_env.get("env_vars") or {}).items()})
        cwd = runtime_env.get("working_dir") or None
        info = self._jobs[job_id]
        try:
            with open(log_path, "wb") as log:
                proc = subprocess.Popen(entrypoint, shell=True, stdout=log,
                                        stderr=subprocess.STDOUT, env=env,
                                        cwd=cwd,
                                        start_new_session=True)
                with self._lock:
                    self._procs[job_id] = proc
                    info["status"] = JobStatus.RUNNING
                    info["start_time"] = time.time()
                rc = proc.wait()
        except Exception as e:
            with self._lock:
                info["status"] = JobStatus.FAILED
                info["message"] = repr(e)
                info["end_time"] = time.time()
            return
        with self._lock:
            self._procs.pop(job_id, None)
            info["return_code"] = rc
            info["end_time"] = time.time()
            if info["status"] == JobStatus.STOPPED:
                pass
            elif rc == 0:
                info["status"] = JobStatus.SUCCEEDED
            else:
                info["status"] = JobStatus.FAILED
                info["message"] = f"exit code {rc}"

    # -- queries ------------------------------------------------------------
    def list_jobs(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(j) for j in self._jobs.values()]

    def get_job(self, job_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            info = self._jobs.get(job_id)
            return dict(info) if info else None

    def get_logs(self, job_id: str) -> Optional[str]:
        if job_id not in self._jobs:
            return None
        path = os.path.join(self._log_dir, f"{job_id}.log")
        try:
            with open(path, "r", errors="replace") as f:
                return f.read()
        except FileNotFoundError:
            return ""

    def stop(self, job_id: str) -> bool:
        with self._lock:
            proc = self._procs.get(job_id)
            info = self._jobs.get(job_id)
            if info is None:
                return False
            if proc is None:
                return info["status"] in (JobStatus.STOPPED,)
            info["status"] = JobStatus.STOPPED
        try:
            import signal
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except Exception:
            proc.terminate()
        return True

    def stop_all(self) -> None:
        with self._lock:
            ids = list(self._procs)
        for job_id in ids:
            self.stop(job_id)
