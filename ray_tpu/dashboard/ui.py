"""Dashboard frontend: one self-contained HTML page (no external assets —
this environment has zero egress, and one file keeps the dashboard
deployable anywhere the head runs).

Reference parity: python/ray/dashboard/client (the React SPA) reduced to
the tables that matter: cluster summary, nodes, actors, tasks, placement
groups, jobs, objects — live against the existing REST API — plus the
stack-dump profiler view (reference: dashboard/modules/reporter).
"""

INDEX_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  :root { --bg:#0f1318; --panel:#171d26; --line:#2a3340; --fg:#dce3ec;
          --dim:#8a96a8; --acc:#5aa9e6; --ok:#57c78a; --bad:#e66a6a; }
  * { box-sizing:border-box; }
  body { margin:0; background:var(--bg); color:var(--fg);
         font:13px/1.5 ui-monospace,Menlo,Consolas,monospace; }
  header { display:flex; align-items:center; gap:16px;
           padding:10px 18px; border-bottom:1px solid var(--line); }
  header h1 { font-size:15px; margin:0; color:var(--acc); }
  header .dim { color:var(--dim); font-size:12px; }
  nav { display:flex; gap:4px; padding:8px 14px 0; }
  nav button { background:none; border:1px solid var(--line);
               border-bottom:none; border-radius:6px 6px 0 0;
               color:var(--dim); padding:6px 14px; cursor:pointer;
               font:inherit; }
  nav button.on { color:var(--fg); background:var(--panel); }
  main { padding:14px 18px; }
  .cards { display:flex; gap:12px; flex-wrap:wrap; margin-bottom:14px; }
  .card { background:var(--panel); border:1px solid var(--line);
          border-radius:8px; padding:10px 16px; min-width:130px; }
  .card .k { color:var(--dim); font-size:11px; text-transform:uppercase; }
  .card .v { font-size:20px; margin-top:2px; }
  table { width:100%; border-collapse:collapse; background:var(--panel);
          border:1px solid var(--line); border-radius:8px; overflow:hidden; }
  th, td { text-align:left; padding:6px 10px;
           border-bottom:1px solid var(--line); font-size:12px; }
  th { color:var(--dim); font-weight:normal; text-transform:uppercase;
       font-size:11px; }
  tr:last-child td { border-bottom:none; }
  .ok { color:var(--ok); } .bad { color:var(--bad); }
  pre { background:var(--panel); border:1px solid var(--line);
        border-radius:8px; padding:12px; white-space:pre-wrap;
        font-size:11px; max-height:70vh; overflow:auto; }
  .dim { color:var(--dim); }
</style>
</head>
<body>
<header>
  <h1>ray_tpu</h1>
  <span class="dim" id="session"></span>
  <span class="dim" id="updated" style="margin-left:auto"></span>
</header>
<nav id="tabs"></nav>
<main id="main"></main>
<script>
const TABS = ["cluster","nodes","actors","tasks","placement_groups",
              "serve","jobs","objects","metrics","profile","timeline"];
let tab = location.hash.slice(1) || "cluster";
const $ = (id) => document.getElementById(id);
const esc = (s) => String(s ?? "").replace(/[&<>"']/g,
    c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));

function renderTabs() {
  $("tabs").innerHTML = TABS.map(t =>
    `<button class="${t===tab?"on":""}" onclick="setTab('${t}')">`
    + `${t.replace("_"," ")}</button>`).join("");
}
function setTab(t) {
  tab = t; location.hash = t;
  sortKey = null; sortDir = 1; filterText = "";
  renderTabs(); refresh();
}

async function api(path) {
  const r = await fetch(path);
  if (!r.ok) throw new Error(`${path}: ${r.status}`);
  return r.json();
}
let sortKey = null, sortDir = 1, filterText = "";
function table(rows, cols, limit) {
  if (!rows || !rows.length) return `<p class="dim">none</p>`;
  if (filterText) {
    const f = filterText.toLowerCase();
    rows = rows.filter(r => JSON.stringify(r).toLowerCase().includes(f));
  }
  if (sortKey != null) {
    const col = cols[sortKey];
    rows = [...rows].sort((a, b) => {
      const rawA = String(col[1](a)), rawB = String(col[1](b));
      const dvA = rawA.match(/data-v="([-\\d.e]+)"/);
      const dvB = rawB.match(/data-v="([-\\d.e]+)"/);
      let av = dvA ? dvA[1] : stripTags(rawA);
      let bv = dvB ? dvB[1] : stripTags(rawB);
      const na = parseFloat(av), nb = parseFloat(bv);
      if (!isNaN(na) && !isNaN(nb)) { av = na; bv = nb; }
      return (av > bv ? 1 : av < bv ? -1 : 0) * sortDir;
    });
  }
  // truncate AFTER filter+sort, so searches reach every row
  const total = rows.length;
  if (limit && rows.length > limit) rows = rows.slice(0, limit);
  const head = cols.map((c, i) =>
    `<th style="cursor:pointer" onclick="sortBy(${i})">${c[0]}` +
    `${sortKey===i ? (sortDir>0?" \u25b4":" \u25be") : ""}</th>`).join("");
  const body = rows.map(r =>
    `<tr>${cols.map(c => `<td>${c[1](r)}</td>`).join("")}</tr>`).join("");
  const note = limit && total > limit
    ? `<span class="dim"> showing ${limit} of ${total}</span>` : "";
  return `<input id="filter" placeholder="filter..." value="${esc(filterText)}"`
    + ` oninput="setFilter(this.value)" style="margin:0 0 8px;background:var(--panel);`
    + `border:1px solid var(--line);border-radius:6px;color:var(--fg);`
    + `padding:5px 10px;font:inherit;width:220px">` + note
    + `<table><thead><tr>${head}</tr></thead><tbody>${body}</tbody></table>`;
}
const stripTags = (s) => String(s ?? "").replace(/<[^>]*>/g, "");
function sortBy(i) {
  if (sortKey === i) sortDir = -sortDir; else { sortKey = i; sortDir = 1; }
  refresh();
}
let filterTimer = null;
function setFilter(v) {
  filterText = v;
  clearTimeout(filterTimer);
  filterTimer = setTimeout(() => {
    refresh().then(() => {
      const el = $("filter");
      if (el) { el.focus(); el.setSelectionRange(v.length, v.length); }
    });
  }, 250);
}
// in-browser metric history: ring buffers fed on every refresh tick
const HISTORY = {};   // key -> [{t, v}]
function record(key, v) {
  if (v == null || isNaN(v)) return;
  const arr = HISTORY[key] = HISTORY[key] || [];
  arr.push({t: Date.now(), v: Number(v)});
  if (arr.length > 240) arr.shift();   // ~12 min at 3s ticks
}
function spark(key, w = 180, h = 28) {
  // buffers fill only while the metrics tab renders; the x-axis is
  // TIME-based and the line BREAKS across sampling gaps, so history
  // never misrepresents a spike that spans an unobserved window
  const arr = HISTORY[key] || [];
  if (arr.length < 2) return `<span class="dim">collecting…</span>`;
  const vs = arr.map(p => p.v);
  const lo = Math.min(...vs), hi = Math.max(...vs);
  const span = Math.max(hi - lo, 1e-9);
  const t0 = arr[0].t, t1 = arr[arr.length-1].t;
  const tspan = Math.max(t1 - t0, 1);
  const segs = [];
  let seg = [];
  for (let i = 0; i < arr.length; i++) {
    if (i && arr[i].t - arr[i-1].t > 10000) {   // >10s: sampling gap
      if (seg.length) segs.push(seg);
      seg = [];
    }
    seg.push([((arr[i].t - t0)/tspan*w).toFixed(1),
      (h - 2 - (arr[i].v - lo)/span*(h-4)).toFixed(1)]);
  }
  if (seg.length) segs.push(seg);
  const lines = segs.map(s => s.length === 1
    // an isolated sample still shows: dot instead of zero-length line
    ? `<circle cx="${s[0][0]}" cy="${s[0][1]}" r="1.5" fill="var(--acc)"/>`
    : `<polyline points="${s.map(p => p.join(",")).join(" ")}"` +
      ` fill="none" stroke="var(--acc)" stroke-width="1.5"/>`).join("");
  return `<svg width="${w}" height="${h}" style="vertical-align:middle">`
    + lines + `</svg>`
    + ` <span class="dim">${Math.round(lo*100)/100}…${Math.round(hi*100)/100}</span>`;
}
const shortid = (s) => `<span title="${esc(s)}">${esc(String(s||"").slice(0,12))}</span>`;
const alive = (a) => a ? `<span class="ok">ALIVE</span>`
                       : `<span class="bad">DEAD</span>`;
const fmtRes = (r) => esc(Object.entries(r||{})
    .map(([k,v]) => `${k}:${Math.round(v*100)/100}`).join(" "));
const fmtBytes = (n) => {
  if (n == null) return "";
  const units = ["B","KB","MB","GB","TB"];
  let i = 0, v = Number(n);
  while (v >= 1024 && i < units.length - 1) { v /= 1024; i++; }
  // data-v carries the raw byte count so column sort is numeric, not
  // lexicographic over "1.5GB" vs "900KB"
  return `<span data-v="${Number(n)}">`
    + esc(`${Math.round(v*10)/10}${units[i]}`) + `</span>`;
};

const VIEWS = {
  async cluster() {
    const s = await api("/api/cluster_status");
    const cards = Object.entries({
      "nodes": s.nodes_alive ?? (s.nodes||[]).length,
      "CPUs": (s.cluster_resources||{}).CPU ?? "-",
      "TPUs": (s.cluster_resources||{}).TPU ?? 0,
      "CPUs free": (s.available_resources||{}).CPU ?? "-",
      "actors": s.num_actors ?? "-",
      "pending tasks": s.num_pending_tasks ?? "-",
    }).map(([k,v]) =>
      `<div class="card"><div class="k">${k}</div><div class="v">${v}</div></div>`);
    return `<div class="cards">${cards.join("")}</div>`
      + `<pre>${esc(JSON.stringify(s, null, 2))}</pre>`;
  },
  async nodes() {
    const rows = await api("/api/nodes");
    return table(rows, [
      ["node", r => shortid(r.node_id)],
      ["state", r => alive(r.alive)],
      ["addr", r => esc((r.addr||[]).join(":"))],
      ["total", r => fmtRes(r.resources_total)],
      ["available", r => fmtRes(r.resources_available)],
      ["labels", r => fmtRes(r.labels)],
      // gossiped daemon stats (syncer view): workers, store bytes, OOM
      ["workers", r => esc(String((r.stats||{}).num_workers ?? ""))],
      ["store", r => fmtBytes((r.stats||{}).object_store_bytes)],
      ["spilled", r => fmtBytes((r.stats||{}).bytes_spilled)],
      ["oom kills", r => esc(String((r.stats||{}).oom_kills ?? ""))],
      ["draining", r => r.draining ? "yes" : ""],
    ]);
  },
  async actors() {
    const rows = await api("/api/actors");
    return table(rows, [
      ["actor", r => shortid(r.actor_id)],
      ["class", r => esc(r.class_name)],
      ["name", r => esc(r.name || "")],
      ["state", r => r.state === "ALIVE" ? `<span class="ok">ALIVE</span>`
          : r.state === "DEAD" ? `<span class="bad">DEAD</span>` : esc(r.state)],
      ["node", r => shortid(r.node_id)],
      ["restarts", r => r.restarts],
    ]);
  },
  async tasks() {
    const rows = await api("/api/tasks");
    rows.sort((a,b) => (b.creation_time||0)-(a.creation_time||0));
    return table(rows, [
      ["task", r => shortid(r.task_id)],
      ["name", r => esc(r.name)],
      ["type", r => esc(r.type)],
      ["state", r => r.state === "FINISHED" ? `<span class="ok">FINISHED</span>`
          : r.state === "FAILED" ? `<span class="bad">FAILED</span>` : esc(r.state)],
      ["node", r => shortid(r.node_id)],
    ], 200);
  },
  async placement_groups() {
    const data = await api("/api/placement_groups");
    const rows = Object.values(data);
    return table(rows, [
      ["pg", r => shortid(r.placement_group_id)],
      ["name", r => esc(r.name)],
      ["strategy", r => esc(r.strategy)],
      ["state", r => r.state === "CREATED" ? `<span class="ok">CREATED</span>`
          : esc(r.state)],
      ["bundles", r => (r.bundles||[]).length],
    ]);
  },
  async jobs() {
    const rows = await api("/api/jobs");
    return table(rows, [
      ["job", r => shortid(r.job_id || r.submission_id)],
      ["status", r => r.status === "SUCCEEDED" ? `<span class="ok">SUCCEEDED</span>`
          : r.status === "FAILED" ? `<span class="bad">FAILED</span>` : esc(r.status)],
      ["entrypoint", r => esc(String(r.entrypoint||"").slice(0,80))],
    ]);
  },
  async objects() {
    const rows = await api("/api/objects");
    return table(rows, [
      ["object", r => shortid(r.object_id)],
      ["size", r => `${Math.round((r.size||0)/1024)} KiB`],
      ["backend", r => esc(r.backend)],
      ["node", r => shortid(r.node_id)],
    ], 200);
  },
  async serve() {
    const s = await api("/api/serve");
    if (s.error) return `<p class="bad">serve controller error: `
      + `${esc(s.error)}</p>`;
    const apps = s.applications || {};
    const rows = [];
    for (const [app, info] of Object.entries(apps)) {
      const deps = (info.deployments || info || {});
      for (const [dep, d] of Object.entries(
          typeof deps === "object" ? deps : {})) {
        rows.push({app, dep, status: d.status || info.status || "?",
                   replicas: d.replica_states || d.replicas || "",
                   route: info.route_prefix || ""});
      }
      if (!Object.keys(deps).length)
        rows.push({app, dep: "", status: info.status || "?",
                   replicas: "", route: info.route_prefix || ""});
    }
    if (!rows.length) return `<p class="dim">serve not running</p>`;
    return table(rows, [
      ["app", r => esc(r.app)],
      ["deployment", r => esc(r.dep)],
      ["status", r => r.status === "RUNNING" || r.status === "HEALTHY"
          ? `<span class="ok">${esc(r.status)}</span>` : esc(r.status)],
      ["replicas", r => esc(JSON.stringify(r.replicas))],
      ["route", r => esc(r.route)],
    ]);
  },
  async metrics() {
    // feed ring buffers from the cluster summary + per-node stats
    const [s, nodes] = await Promise.all(
      [api("/api/cluster_status"), api("/api/nodes")]);
    record("pending tasks", s.num_pending_tasks);
    record("actors", s.num_actors);
    record("CPUs free", (s.available_resources||{}).CPU);
    let nodeRows = "";
    for (const n of nodes) {
      const id = String(n.node_id).slice(0, 8);
      record(`store ${id}`, (n.stats||{}).object_store_bytes);
      record(`workers ${id}`, (n.stats||{}).num_workers);
      nodeRows += `<tr><td>${esc(id)}</td>` +
        `<td>${spark("store " + id)}</td>` +
        `<td>${spark("workers " + id)}</td></tr>`;
    }
    return `<p class="dim">live history (in-browser ring buffers,
      3s ticks; <a href="/metrics" style="color:inherit">raw
      Prometheus</a>)</p>` +
      `<div class="cards">` +
      ["pending tasks","actors","CPUs free"].map(k =>
        `<div class="card"><div class="k">${k}</div>` +
        `<div>${spark(k)}</div></div>`).join("") + `</div>` +
      `<table><thead><tr><th>node</th><th>store bytes</th>` +
      `<th>workers</th></tr></thead><tbody>${nodeRows}</tbody></table>`;
  },
  async timeline() {
    const data = await api("/api/timeline");
    const evs = (data.traceEvents||[]);
    if (!evs.length) return `<p class="dim">no finished tasks yet</p>`;
    const t0 = Math.min(...evs.map(e => e.ts));
    const t1 = Math.max(...evs.map(e => e.ts + e.dur));
    const span = Math.max(t1 - t0, 1);
    const lanes = [...new Set(evs.map(e => e.pid))];
    const rows = lanes.map(pid => {
      const bars = evs.filter(e => e.pid === pid).map(e => {
        const l = (e.ts - t0) / span * 100, w = Math.max(e.dur/span*100, 0.3);
        return `<div title="${esc(e.name)} ${Math.round(e.dur/1000)}ms"` +
          ` style="position:absolute;left:${l}%;width:${w}%;height:14px;` +
          `background:var(--accent,#4c8);border-radius:2px;opacity:.8"></div>`;
      }).join("");
      return `<div style="margin:6px 0"><span class="dim">node ${esc(pid)}</span>` +
        `<div style="position:relative;height:16px;background:var(--panel)">${bars}</div></div>`;
    }).join("");
    return `<p class="dim">task timeline (${evs.length} tasks, ` +
      `${Math.round(span/1000)}ms) — ` +
      `<a href="/api/timeline" download="timeline.json" style="color:inherit">` +
      `download chrome-trace JSON</a> for Perfetto</p>` + rows;
  },
  async profile() {
    const data = await api("/api/profile/stacks");
    const blocks = (data.nodes||[]).map(n =>
      `<h3 class="dim">node ${esc(String(n.node_id).slice(0,12))}</h3>`
      + `<pre>${esc(n.stacks)}</pre>`).join("");
    return `<p class="dim">live thread stacks across the cluster
      (py-spy-equivalent; refreshed on tab load)</p>` + blocks;
  },
};

async function refresh() {
  try {
    $("main").innerHTML = await VIEWS[tab]();
    $("updated").textContent = "updated " + new Date().toLocaleTimeString();
  } catch (e) {
    $("main").innerHTML = `<p class="bad">${esc(e)}</p>`;
  }
}
renderTabs();
refresh();
setInterval(() => {
  // never yank the DOM out from under someone typing in the filter
  if (tab === "profile") return;
  if (document.activeElement && document.activeElement.id === "filter")
    return;
  refresh();
}, 3000);
</script>
</body>
</html>
"""
