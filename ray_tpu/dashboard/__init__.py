"""Dashboard: REST state/metrics endpoints + job manager.

Reference parity: python/ray/dashboard (head.py + modules: api, node,
job, metrics, state). TS frontend replaced by JSON endpoints (the state
CLI renders tables); Prometheus text at /metrics.
"""

from .head import DashboardHead, start_dashboard

__all__ = ["DashboardHead", "start_dashboard"]
