"""Dashboard head: aiohttp REST server in the head process.

Routes (reference: dashboard/modules/*):
  GET  /api/cluster_status      nodes + resources
  GET  /api/nodes               list_nodes
  GET  /api/tasks               task events
  GET  /api/actors              actor directory
  GET  /api/objects             shm object tables
  GET  /api/placement_groups
  GET  /metrics                 Prometheus text (driver + flushed workers)
  POST /api/jobs                {"entrypoint": shell-cmd, ...} → job id
  GET  /api/jobs                all jobs
  GET  /api/jobs/{id}           one job
  GET  /api/jobs/{id}/logs      captured stdout/stderr
  POST /api/jobs/{id}/stop
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Any, Dict, List, Optional

from .job_manager import JobManager


class DashboardHead:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self.job_manager = JobManager()
        self._runner = None

    async def start(self) -> int:
        from aiohttp import web

        app = web.Application()
        app.router.add_get("/api/cluster_status", self._cluster_status)
        app.router.add_get("/api/nodes", self._nodes)
        app.router.add_get("/api/tasks", self._tasks)
        app.router.add_get("/api/actors", self._actors)
        app.router.add_get("/api/objects", self._objects)
        app.router.add_get("/api/placement_groups", self._pgs)
        app.router.add_get("/api/serve", self._serve_status)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/api/timeline", self._timeline)
        app.router.add_get("/api/profile/stacks", self._profile_stacks)
        app.router.add_post("/api/jobs", self._submit_job)
        app.router.add_get("/api/jobs", self._list_jobs)
        app.router.add_get("/api/jobs/{job_id}", self._get_job)
        app.router.add_get("/api/jobs/{job_id}/logs", self._job_logs)
        app.router.add_post("/api/jobs/{job_id}/stop", self._stop_job)
        app.router.add_get("/", self._index)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        # port=0 binds an ephemeral port: report the one actually bound
        sockets = getattr(site._server, "sockets", None) or []
        if sockets:
            self.port = sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        self.job_manager.stop_all()
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- helpers ------------------------------------------------------------
    @staticmethod
    async def _in_thread(fn, *args):
        return await asyncio.get_running_loop().run_in_executor(
            None, fn, *args)

    @staticmethod
    def _json(payload) -> "web.Response":
        from aiohttp import web
        return web.json_response(payload)

    # -- state routes -------------------------------------------------------
    async def _index(self, request):
        from aiohttp import web

        from .ui import INDEX_HTML
        return web.Response(text=INDEX_HTML, content_type="text/html")

    async def _cluster_status(self, request):
        import ray_tpu

        from ..util import state as state_api
        # five independent control-plane reads, fetched concurrently
        total, avail, nodes, actors, tasks = await asyncio.gather(
            self._in_thread(ray_tpu.cluster_resources),
            self._in_thread(ray_tpu.available_resources),
            self._in_thread(ray_tpu.nodes),
            self._in_thread(state_api.list_actors),
            self._in_thread(state_api.list_tasks))
        return self._json({
            "cluster_resources": total,
            "available_resources": avail,
            "num_nodes": len(nodes),
            "nodes_alive": sum(1 for n in nodes if n.get("alive")),
            "num_actors": sum(1 for a in actors
                              if a.get("state") == "ALIVE"),
            "num_pending_tasks": sum(
                1 for t in tasks
                if t.get("state", "").startswith("PENDING")),
        })

    async def _serve_status(self, request):
        """Serve application/deployment status (reference parity:
        dashboard serve module over the serve controller)."""
        def read():
            from .. import serve
            try:
                return serve.status()
            except Exception as e:
                # distinguish "serve not running" (benign empty) from a
                # genuine controller failure (surfaced in the payload)
                msg = repr(e)
                benign = isinstance(e, (ValueError, KeyError)) or \
                    "not running" in msg or "no controller" in msg.lower()
                out = {"applications": {}}
                if not benign:
                    out["error"] = msg
                return out
        return self._json(await self._in_thread(read))

    async def _profile_stacks(self, request):
        """py-spy-equivalent: live thread stacks of the head + every
        worker on every node (reference parity:
        dashboard/modules/reporter/profile_manager.py)."""
        from ray_tpu._private import state as pstate
        client = pstate.current_client()
        out = []
        for node in await self._in_thread(
                lambda: client.controller_rpc("list_nodes")):
            if not node.get("alive") or not node.get("addr"):
                continue
            try:
                stacks = await self._in_thread(
                    lambda a=node["addr"]: client.daemon_rpc(
                        a, "node_stacks"))
            except Exception as e:
                stacks = f"<unreachable: {e!r}>"
            out.append({"node_id": node["node_id"], "stacks": stacks})
        return self._json({"nodes": out})

    async def _nodes(self, request):
        from ..util import state as state_api
        return self._json(await self._in_thread(state_api.list_nodes))

    async def _tasks(self, request):
        from ..util import state as state_api
        return self._json(await self._in_thread(state_api.list_tasks))

    async def _actors(self, request):
        from ..util import state as state_api
        return self._json(await self._in_thread(state_api.list_actors))

    async def _objects(self, request):
        from ..util import state as state_api
        return self._json(await self._in_thread(state_api.list_objects))

    async def _pgs(self, request):
        from ..util import state as state_api
        return self._json(
            await self._in_thread(state_api.list_placement_groups))

    async def _metrics(self, request):
        from aiohttp import web

        from ..util import metrics as metrics_api
        text, node_text, serve_text = await asyncio.gather(
            self._in_thread(metrics_api.export_prometheus),
            self._in_thread(self._node_metrics_text),
            self._in_thread(self._serve_metrics_text))
        return web.Response(text=text + node_text + serve_text,
                            content_type="text/plain")

    @staticmethod
    def _serve_metrics_text() -> str:
        """Per-deployment serve gauges from the controller's aggregated
        replica polls (reference parity role: serve's autoscaling/
        request metrics surfaced to Prometheus). Empty when serve is
        not running."""
        try:
            from .. import serve
            status = serve.status()
        except Exception:
            return ""
        lines: List[str] = []

        def gauge(name, help_):
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} gauge")

        def esc(v: str) -> str:
            # Prometheus label-value escaping: an unescaped quote or
            # newline in an app name would corrupt the whole exposition
            return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                    .replace("\n", "\\n"))

        rows = []
        for app_name, app in status.get("applications", {}).items():
            for dep, info in app.get("deployments", {}).items():
                m = info.get("metrics", {})
                running = sum(1 for s in info.get(
                    "replica_states", {}).values() if s == "RUNNING")
                rows.append((esc(app_name), esc(dep), running,
                             info.get("target", 0), m))
        if not rows:
            return ""
        for field, metric, help_ in (
                (None, "ray_tpu_serve_replicas_running",
                 "running replicas per deployment"),
                (None, "ray_tpu_serve_replicas_target",
                 "target replicas per deployment"),
                ("ongoing", "ray_tpu_serve_ongoing_requests",
                 "in-flight requests per deployment"),
                ("qps_10s", "ray_tpu_serve_qps",
                 "requests/s over the last 10s per deployment"),
                ("total_requests", "ray_tpu_serve_total_requests",
                 "cumulative requests per deployment")):
            gauge(metric, help_)
            for app_name, dep, running, target, m in rows:
                if field is None:
                    val = (running
                           if metric.endswith("running") else target)
                else:
                    val = m.get(field, 0)
                lines.append(
                    f'{metric}{{app="{app_name}",deployment="{dep}"}} '
                    f'{val}')
        return "\n".join(lines) + "\n"

    @staticmethod
    def _node_metrics_text() -> str:
        """Per-node gauges synthesized from the controller's node views
        (the per-node stats ride the resource gossip — this IS the
        per-node metrics pipeline; reference parity role:
        _private/metrics_agent.py:492 + dashboard metrics module)."""
        from ..util import state as state_api
        lines: List[str] = []

        def gauge(name, help_, rows):
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} gauge")
            lines.extend(rows)

        try:
            nodes = [n for n in state_api.list_nodes() if n.get("alive")]
        except Exception:
            return ""
        stats_fields = (
            ("num_workers", "ray_tpu_node_workers", "workers per node"),
            ("object_store_bytes", "ray_tpu_node_object_store_bytes",
             "node object store bytes"),
            ("bytes_spilled", "ray_tpu_node_bytes_spilled",
             "cumulative spilled bytes"),
            ("oom_kills", "ray_tpu_node_oom_kills",
             "cumulative OOM kills"),
            ("arena_pressure", "ray_tpu_node_arena_pressure",
             "shm arena allocated/capacity"),
            # native C++ arena operation counters
            ("arena_allocs", "ray_tpu_node_arena_allocs",
             "cumulative native arena allocations"),
            ("arena_alloc_fails", "ray_tpu_node_arena_alloc_fails",
             "native arena allocation failures (pressure signal)"),
            ("arena_frees", "ray_tpu_node_arena_frees",
             "cumulative native arena frees"),
            ("arena_coalesces", "ray_tpu_node_arena_coalesces",
             "native arena free-block coalesces"),
            ("arena_crash_sweeps", "ray_tpu_node_arena_crash_sweeps",
             "native arena crash-recovery sweeps"),
        )
        for field, metric, help_ in stats_fields:
            gauge(metric, help_, [
                f'{metric}{{node_id="{n["node_id"][:12]}"}} '
                f'{n.get("stats", {}).get(field, 0)}'
                for n in nodes])
        for which in ("total", "available"):
            metric = f"ray_tpu_node_resource_{which}"
            gauge(metric, f"node resources {which}", [
                f'{metric}{{node_id="{n["node_id"][:12]}",'
                f'resource="{res}"}} {val}'
                for n in nodes
                for res, val in (n.get(f"resources_{which}") or {}).items()
            ])
        return "\n".join(lines) + "\n"

    async def _timeline(self, request):
        """Chrome-trace ("traceEvents") JSON of the task-event table —
        load in Perfetto / chrome://tracing (reference parity: the
        dashboard timeline built on task events)."""
        from ..util import state as state_api
        tasks = await self._in_thread(state_api.list_tasks)
        events = []
        for t in tasks:
            start = t.get("start_time")
            if start is None:
                continue
            end = t.get("end_time") or time.time()
            events.append({
                "name": t.get("name") or t["task_id"][:8],
                "cat": t.get("type", "NORMAL_TASK"),
                "ph": "X",
                "ts": start * 1e6,
                "dur": max((end - start) * 1e6, 1.0),
                "pid": (t.get("node_id") or "pending")[:12],
                "tid": t["task_id"][:8],
                "args": {"state": t.get("state"),
                         "task_id": t["task_id"]},
            })
        return self._json({"traceEvents": events,
                           "displayTimeUnit": "ms"})

    # -- job routes ---------------------------------------------------------
    async def _submit_job(self, request):
        body = await request.json()
        entrypoint = body.get("entrypoint")
        if not entrypoint:
            from aiohttp import web
            return web.json_response({"error": "entrypoint required"},
                                     status=400)
        job_id = await self._in_thread(
            lambda: self.job_manager.submit(
                entrypoint,
                runtime_env=body.get("runtime_env"),
                metadata=body.get("metadata"),
                submission_id=body.get("submission_id")))
        return self._json({"submission_id": job_id, "job_id": job_id})

    async def _list_jobs(self, request):
        return self._json(self.job_manager.list_jobs())

    async def _get_job(self, request):
        info = self.job_manager.get_job(request.match_info["job_id"])
        if info is None:
            from aiohttp import web
            return web.json_response({"error": "no such job"}, status=404)
        return self._json(info)

    async def _job_logs(self, request):
        logs = self.job_manager.get_logs(request.match_info["job_id"])
        if logs is None:
            from aiohttp import web
            return web.json_response({"error": "no such job"}, status=404)
        return self._json({"logs": logs})

    async def _stop_job(self, request):
        ok = self.job_manager.stop(request.match_info["job_id"])
        return self._json({"stopped": bool(ok)})


_dashboard: Optional[DashboardHead] = None
_thread_loop: Optional[asyncio.AbstractEventLoop] = None


def start_dashboard(host: str = "127.0.0.1",
                    port: int = 8265) -> DashboardHead:
    """Start the dashboard on a background event loop thread (driver- or
    head-process side)."""
    global _dashboard, _thread_loop
    if _dashboard is not None:
        return _dashboard
    dash = DashboardHead(host, port)
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)
        loop.run_until_complete(dash.start())
        started.set()
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True,
                         name="ray_tpu-dashboard")
    t.start()
    if not started.wait(timeout=15):
        raise TimeoutError("dashboard failed to start")
    _dashboard = dash
    _thread_loop = loop
    # Materialize the Prometheus/Grafana provisioning configs beside the
    # session (reference parity: dashboard metrics_head generation)
    try:
        from ray_tpu._private import state as _state
        client = _state.current_client_or_none()
        session = getattr(client, "session_name", None)
        if session:
            from ray_tpu._private.config import session_dir
            from .metrics_config import write_metrics_configs
            write_metrics_configs(session_dir(session),
                                  f"{dash.host}:{dash.port}")
    except Exception:
        pass
    return dash


def stop_dashboard() -> None:
    global _dashboard, _thread_loop
    if _dashboard is None:
        return
    dash, loop = _dashboard, _thread_loop
    _dashboard = _thread_loop = None
    fut = asyncio.run_coroutine_threadsafe(dash.stop(), loop)
    try:
        fut.result(timeout=10)
    except Exception:
        pass
    loop.call_soon_threadsafe(loop.stop)
